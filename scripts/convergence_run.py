"""Convergence + kill/resume artifact (VERDICT r4 "Missing #5").

Produces ``docs/artifacts/convergence_r5.json``: a multi-hundred-step
ResNet@32px training run on real hardware with

- a falling loss curve and above-chance accuracy on a learnable synthetic
  dataset (:class:`mpi4dl_tpu.data.ClassPatternImages` — the benchmark
  machine has no CIFAR-10 on disk; the reference's ``--app 2`` path,
  ``benchmark_amoebanet_sp.py:264-306``, is the analog),
- a REAL process kill mid-run: phase A runs in a subprocess that is
  SIGKILLed after it writes the checkpoint at ``--kill-step``; phase B is
  a fresh subprocess that restores from the checkpoint directory and
  continues on the same deterministic stream,
- continuity assertions: the resumed curve picks up where the killed one
  stopped (loss at resume within a band of loss at kill; final loss well
  below initial; final train accuracy well above chance).

Run (defaults are the committed artifact's config):

    python scripts/convergence_run.py --out docs/artifacts/convergence_r5.json

The same single-run logic (``run_phase``) is exercised CPU-small by the
fast-tier test ``tests/test_checkpoint.py::test_resume_continues_curve``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_trainer(depth: int, image_size: int, batch_size: int, lr: float = 0.001):
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.train import Trainer

    cfg = ParallelConfig(
        batch_size=batch_size,
        split_size=1,
        spatial_size=0,
        image_size=image_size,
    )
    # v2 downsamples twice after the stem: the head pool must match the
    # final feature map (image_size/4), as the reference pins for 32px.
    cells = get_resnet_v2(depth=depth, pool_kernel=image_size // 4)
    return Trainer(cells, num_spatial_cells=0, config=cfg, learning_rate=lr)


def run_phase(
    *,
    depth: int,
    image_size: int,
    batch_size: int,
    steps: int,
    ckpt_dir: str,
    ckpt_every: int,
    log_path: str,
    resume: bool,
    seed: int = 0,
    lr: float = 0.001,
    kill_after_ckpt_step: int | None = None,
    compile_cache: bool = True,
):
    """Train to ``steps`` total, appending {step, loss, accuracy} JSON lines
    to ``log_path``. With ``resume``, restores the newest checkpoint and
    continues the SAME deterministic batch stream (batch index == step).
    ``kill_after_ckpt_step``: after saving the checkpoint at that step,
    SIGKILL this process — a hard mid-run death, not a clean exit."""
    import jax

    from mpi4dl_tpu.checkpoint import restore_checkpoint, save_checkpoint
    from mpi4dl_tpu.data import ClassPatternImages
    if compile_cache:
        from mpi4dl_tpu.utils import enable_compilation_cache

        enable_compilation_cache()
    trainer = build_trainer(depth, image_size, batch_size, lr=lr)
    sample = (batch_size, image_size, image_size, 3)
    state = trainer.init(jax.random.PRNGKey(seed), sample)
    if resume:
        state = restore_checkpoint(ckpt_dir, state)
    start = int(jax.device_get(state.step))

    ds = ClassPatternImages(batch_size, image_size, num_classes=10, seed=seed)
    with open(log_path, "a") as log:
        for step in range(start, steps):
            x, y = ds.batch(step)
            state, metrics = trainer.train_step(
                state, *trainer.shard_batch(x, y)
            )
            rec = {
                "step": step + 1,
                "loss": float(metrics["loss"]),
                "accuracy": float(metrics["accuracy"]),
            }
            log.write(json.dumps(rec) + "\n")
            log.flush()
            done = step + 1
            if done % ckpt_every == 0 or done == steps:
                save_checkpoint(ckpt_dir, state)
                if kill_after_ckpt_step is not None and done >= kill_after_ckpt_step:
                    os.kill(os.getpid(), signal.SIGKILL)
    return state


def _phase_main(argv):
    p = argparse.ArgumentParser()
    p.add_argument("--depth", type=int, required=True)
    p.add_argument("--image-size", type=int, required=True)
    p.add_argument("--batch-size", type=int, required=True)
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--ckpt-every", type=int, required=True)
    p.add_argument("--log", required=True)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--kill-after", type=int, default=None)
    p.add_argument("--lr", type=float, default=0.001)
    a = p.parse_args(argv)
    run_phase(
        depth=a.depth,
        image_size=a.image_size,
        batch_size=a.batch_size,
        steps=a.steps,
        ckpt_dir=a.ckpt_dir,
        ckpt_every=a.ckpt_every,
        log_path=a.log,
        resume=a.resume,
        lr=a.lr,
        kill_after_ckpt_step=a.kill_after,
    )


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--depth", type=int, default=20)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--kill-step", type=int, default=150)
    p.add_argument("--ckpt-every", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument(
        "--out", default=os.path.join(REPO, "docs/artifacts/convergence_r5.json")
    )
    p.add_argument("--workdir", default=None)
    a = p.parse_args()
    if a.kill_step % a.ckpt_every or not 0 < a.kill_step < a.steps:
        # The kill fires at the first checkpoint boundary >= kill_step, so
        # a non-aligned or out-of-range value would fail the curve
        # assertions only AFTER minutes of real-hardware training.
        p.error(
            f"--kill-step {a.kill_step} must be a multiple of "
            f"--ckpt-every {a.ckpt_every} and inside (0, --steps {a.steps})"
        )

    workdir = a.workdir or os.path.join(REPO, ".cache", "convergence_run")
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    log_a = os.path.join(workdir, "phase_a.jsonl")
    log_b = os.path.join(workdir, "phase_b.jsonl")
    for f in (log_a, log_b):
        if os.path.exists(f):
            os.unlink(f)
    if os.path.isdir(ckpt_dir):
        import shutil

        shutil.rmtree(ckpt_dir)

    common = [
        sys.executable, os.path.abspath(__file__), "phase",
        "--depth", str(a.depth), "--image-size", str(a.image_size),
        "--batch-size", str(a.batch_size), "--steps", str(a.steps),
        "--ckpt-dir", ckpt_dir, "--ckpt-every", str(a.ckpt_every),
        "--lr", str(a.lr),
    ]
    t0 = time.time()
    ra = subprocess.run(common + ["--log", log_a, "--kill-after", str(a.kill_step)])
    # SIGKILL → negative returncode; a phase A that exited cleanly never
    # reached the kill, which would make the "resume after death" claim
    # vacuous.
    assert ra.returncode == -signal.SIGKILL, f"phase A rc={ra.returncode}"
    rb = subprocess.run(common + ["--log", log_b, "--resume"])
    assert rb.returncode == 0, f"phase B rc={rb.returncode}"
    wall = time.time() - t0

    curve_a = [json.loads(l) for l in open(log_a)]
    curve_b = [json.loads(l) for l in open(log_b)]
    assert curve_a[-1]["step"] == a.kill_step
    assert curve_b[0]["step"] == a.kill_step + 1, curve_b[0]
    assert curve_b[-1]["step"] == a.steps

    import numpy as np

    first5 = float(np.mean([r["loss"] for r in curve_a[:5]]))
    last20 = [r for r in curve_b if r["step"] > a.steps - 20]
    final_loss = float(np.mean([r["loss"] for r in last20]))
    final_acc = float(np.mean([r["accuracy"] for r in last20]))
    pre_kill = [r["loss"] for r in curve_a[-10:]]
    post_resume = [r["loss"] for r in curve_b[:10]]
    band = max(3 * float(np.std(pre_kill)), 0.15 * float(np.mean(pre_kill)), 0.05)
    jump = abs(float(np.mean(post_resume)) - float(np.mean(pre_kill)))

    checks = {
        "loss_fell": final_loss < 0.5 * first5,
        "above_chance": final_acc > 3 * (1 / 10),
        "resume_continues_curve": jump < band,
    }
    artifact = {
        "config": {
            "model": f"resnet-{a.depth}-v2",
            "image_size": a.image_size,
            "batch_size": a.batch_size,
            "lr": a.lr,
            "steps": a.steps,
            "kill": f"SIGKILL after checkpoint @ step {a.kill_step}",
            "dataset": "ClassPatternImages(num_classes=10, noise=0.25)",
            "platform": _platform(),
        },
        "initial_loss_mean5": round(first5, 4),
        "final_loss_mean20": round(final_loss, 4),
        "final_accuracy_mean20": round(final_acc, 4),
        "resume_jump": round(jump, 4),
        "resume_band": round(band, 4),
        "checks": checks,
        "wall_seconds": round(wall, 1),
        "curve": [
            r for r in curve_a + curve_b
            if r["step"] % 10 == 0 or r["step"] in (1, a.kill_step, a.kill_step + 1)
        ],
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({k: v for k, v in artifact.items() if k != "curve"}, indent=1))
    if not all(checks.values()):
        sys.exit(f"convergence checks failed: {checks}")


def _platform() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'device_kind', '?')} x{jax.device_count()}"


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "phase":
        _phase_main(sys.argv[2:])
    else:
        main()
