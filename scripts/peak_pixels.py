"""Peak trainable resolution per chip — the BASELINE.json capability metric.

Walks image sizes upward (powers of two) for a model family and reports the
largest resolution whose full training step (fwd + bwd + update) compiles
and runs on one chip, with throughput at each size. The reference frames
this as "spatial parallelism trains very-high-res images that DP cannot"
(README.md:6, DP_MP_SP_Vs_Memory.png); on TPU the single-chip ceiling is
set by HBM and the remat policy, and the multi-chip SP path raises it by
tiling H/W over the mesh.

Usage: python scripts/peak_pixels.py [--model resnet|amoebanet] [--batch 1]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def try_size(model: str, size: int, batch: int, remats) -> tuple[float, str] | str:
    import numpy as np

    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.utils import get_depth

    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    if model == "resnet":
        from mpi4dl_tpu.models.resnet import get_resnet_v2

        layout = "packed" if dtype == jnp.bfloat16 else "nhwc"
        cells = get_resnet_v2(
            depth=get_depth(2, 12), num_classes=10, pool_kernel=size // 4,
            layout=layout, dtype=dtype,
        )
    else:
        from mpi4dl_tpu.models.amoebanet import amoebanetd

        cells = amoebanetd(
            num_classes=10, num_layers=18, num_filters=416, dtype=dtype
        )
    cfg = ParallelConfig(
        batch_size=batch, split_size=1, spatial_size=0, image_size=size
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)), dtype)
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)
    last_err = "no remat policy attempted"
    for remat in remats:
        try:
            tr = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
            xs, ys = tr.shard_batch(x, y)
            state = tr.init(jax.random.PRNGKey(0), x.shape, dtype=dtype)
            state, m = tr.train_step(state, xs, ys)
            float(m["loss"])  # force real execution (see bench.py note)
            t0 = time.perf_counter()
            for _ in range(3):
                state, m = tr.train_step(state, xs, ys)
            float(m["loss"])
            return batch * 3 / (time.perf_counter() - t0), remat
        except Exception as e:  # noqa: BLE001 — probe must keep walking
            last_err = f"{remat}: {type(e).__name__}: {str(e)[:160]}"
    return last_err


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet", choices=["resnet", "amoebanet"])
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--start", type=int, default=1024)
    ap.add_argument("--max", type=int, default=16384)
    args = ap.parse_args()
    peak = None
    size = args.start
    while size <= args.max:
        if size >= 4096:
            # Straight to the anchored-quadratic tier: scanlog's live set
            # is a confirmed OOM at 4096 (docs/PERF.md round 5) and its
            # doomed compile costs ~10 uncacheable minutes per size.
            remats = ["scanq"]
        elif size >= 3072:
            # Whole-model logarithmic recursion (fits and is 3.7x faster
            # than scanq at 3072), then the anchored-quadratic tier whose
            # live boundary set is O(1) per run; leaner policies would
            # waste a multi-minute doomed compile per size here.
            remats = ["scanlog", "scanq"]
        elif args.model == "amoebanet":
            remats = ["scan_save", "scan"]
        else:
            remats = ["cell_save", "scan_save", "scan"]
        # One size per SUBPROCESS: a failed compile can wedge the tunneled
        # runtime, which must not kill the whole walk.
        import subprocess

        code = (
            "import sys; sys.path.insert(0, {root!r});"
            "from scripts.peak_pixels import try_size;"
            "r = try_size({model!r}, {size}, {batch}, {remats!r});"
            "print('RESULT', repr(r))"
        ).format(
            root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            model=args.model, size=size, batch=args.batch, remats=remats,
        )
        env = dict(os.environ)
        if "scanq" in remats:
            # Measured scanq default: grant the late small-carry runs
            # stored carries (+67% at 4096; 6000 MB OOMs — docs/PERF.md
            # round 5). Explicit env wins.
            env.setdefault("MPI4DL_TPU_SCANQ_STORE_MB", "3000")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=3600, env=env,
        )
        line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT")]
        if not line:
            print(f"{size}px: CRASH ({proc.returncode})", flush=True)
            break
        result = eval(line[-1][len("RESULT "):])  # noqa: S307 — own output
        if isinstance(result, tuple):
            ips, remat = result
            px = size * size
            print(
                f"{size}px: OK {ips:.3f} img/s ({remat}, "
                f"{px / 1e6:.0f} Mpx/image)", flush=True,
            )
            peak = size
            size *= 2
        else:
            print(f"{size}px: FAIL {result}", flush=True)
            break
    print(f"peak trainable: {peak}px at bs={args.batch}" if peak else "none")


if __name__ == "__main__":
    main()
