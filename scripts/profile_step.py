"""Capture an xprof trace of the bench train step and print op stats.

Usage: python scripts/profile_step.py [--model resnet|amoebanet]
       [--image-size 1024] [--batch 2] [--steps 3] [--out /tmp/trace]

Prints the framework_op_stats table (top ops by self-time) so perf work
targets measured costs, not standalone microbenchmarks (which round 2
showed can mislead by 5x on this device — docs/PERF.md).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.utils import enable_compilation_cache

    enable_compilation_cache()  # share bench.py's warm persistent cache

    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    if args.model == "resnet":
        from mpi4dl_tpu.models.resnet import get_resnet_v2
        from mpi4dl_tpu.utils import get_depth

        cells = get_resnet_v2(
            depth=get_depth(2, 12), num_classes=10,
            pool_kernel=args.image_size // 4, layout=args.layout, dtype=dtype,
        )
    else:
        from mpi4dl_tpu.models.amoebanet import amoebanetd

        cells = amoebanetd(
            num_classes=10, num_layers=18, num_filters=416, dtype=dtype
        )
    cfg = ParallelConfig(
        batch_size=args.batch, split_size=1, spatial_size=0,
        image_size=args.image_size,
    )
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=args.remat)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((args.batch, args.image_size, args.image_size, 3)),
        dtype,
    )
    y = jnp.asarray(rng.integers(0, 10, size=(args.batch,)), jnp.int32)
    xs, ys = trainer.shard_batch(x, y)
    state = trainer.init(jax.random.PRNGKey(0), x.shape, dtype=dtype)
    for _ in range(2):  # compile + warm
        state, m = trainer.train_step(state, xs, ys)
    float(m["loss"])
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            state, m = trainer.train_step(state, xs, ys)
        float(m["loss"])
    print(f"trace written to {args.out}", file=sys.stderr)


def report(out_dir, top=30):
    """framework_op_stats via the xprof/tensorboard-plugin-profile convert
    API (no TensorBoard UI needed)."""
    from xprof.convert import raw_to_tool_data as rtd

    runs = sorted(glob.glob(os.path.join(out_dir, "plugins/profile/*")))
    assert runs, f"no profile runs under {out_dir}"
    run = runs[-1]
    xspaces = glob.glob(os.path.join(run, "*.xplane.pb"))
    data, _ = rtd.xspace_to_tool_data(xspaces, "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    tbl = json.loads(data)
    tbl = tbl[0] if isinstance(tbl, list) else tbl
    cols = [c["id"] for c in tbl["cols"]]
    rows = [dict(zip(cols, [c["v"] for c in r["c"]])) for r in tbl["rows"]]
    dev = [r for r in rows if r.get("host_or_device") == "Device"]
    # xprof renamed self_time -> total_self_time across versions; take either.
    key = "self_time" if (dev and "self_time" in dev[0]) else "total_self_time"
    for r in dev:
        r["self_time"] = r[key]
    total = sum(r["self_time"] for r in dev)
    print(f"total device self_time: {total / 1e3:.2f} ms (all captured steps)")
    by_type = {}
    for r in dev:
        by_type[r["type"]] = by_type.get(r["type"], 0.0) + r["self_time"]
    print("-- by op type --")
    for t, v in sorted(by_type.items(), key=lambda kv: -kv[1])[:14]:
        print(f"{t:38s} {v / 1e3:9.2f} ms  {100 * v / total:5.1f}%")
    print(f"-- top {top} individual ops --")
    for r in sorted(dev, key=lambda r: -r["self_time"])[:top]:
        print(
            f"{r['self_time'] / 1e3:8.2f} ms {100 * r['self_time'] / total:5.1f}% "
            f"x{r['occurrences']:<4} {r['type']:26s} {r['operation'][:70]}"
        )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet", choices=["resnet", "amoebanet"])
    ap.add_argument("--image-size", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--remat", default="cell_save")
    ap.add_argument("--layout", default="packed", choices=["nhwc", "packed"])
    ap.add_argument("--out", default="/tmp/mpi4dl_trace")
    ap.add_argument("--report-only", action="store_true")
    args = ap.parse_args()
    if not args.report_only:
        capture(args)
    report(args.out)


if __name__ == "__main__":
    main()
