#!/usr/bin/env python
"""selflint — stdlib-ast hygiene lint over this repo's own source.

hlolint (mpi4dl_tpu/analysis) lints the *compiled HLO*; this script lints
the *Python that produces and measures it*, catching the three recurring
hygiene bugs that corrupt measurements or hang CI without ever failing a
functional test:

- ``wallclock-compare``: ``time.time()`` used inside a comparison.
  Wall-clock time jumps under NTP slew; deadline/elapsed comparisons must
  use ``time.monotonic()`` or ``time.perf_counter()``. Timestamps (stored,
  printed, subtracted for display) are fine — only a ``time.time()`` call
  nested inside an ``ast.Compare`` is flagged.
- ``uncataloged-metric``: a direct ``.gauge(`` / ``.counter(`` /
  ``.histogram(`` call. Every metric series must be created through
  ``telemetry.declare(registry, name)`` so the catalog check (name, type,
  labels, docs table) covers it; direct registry calls bypass the catalog
  and rot docs/OBSERVABILITY.md. The telemetry package's own internals
  (the delegators that implement ``declare``) are allowlisted.
- ``unnamed-thread``: ``threading.Thread(...)`` with neither ``name=``
  nor ``daemon=``. An anonymous non-daemon thread is invisible in hang
  dumps and can block interpreter exit — every thread must at least be
  identifiable, and background loops must be daemons.

Scan scope: ``mpi4dl_tpu/``, ``scripts/``, ``bench.py`` (tests are
excluded — they monkeypatch clocks and registries on purpose). Pure
stdlib, no jax import: safe for pre-commit and CI front doors.

Usage::

    python scripts/selflint.py [--root DIR] [--json]

Exit 0 when clean, 1 on any finding, 2 on usage/parse errors.
Tier-1 coverage: ``tests/test_selflint.py`` pins each rule on synthetic
snippets and asserts the real repo scans clean.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

# Paths (relative, "/"-separated) where a rule is intentionally violated.
# Keep this list SHORT and justified: every entry is a hole in the lint.
ALLOWLIST: "dict[str, set[str]]" = {
    # The telemetry internals that IMPLEMENT declare() must call the
    # underlying registry constructors directly.
    "uncataloged-metric": {
        "mpi4dl_tpu/telemetry/catalog.py",
        "mpi4dl_tpu/telemetry/federation.py",
    },
    "wallclock-compare": set(),
    "unnamed-thread": set(),
}

SCAN_ROOTS = ("mpi4dl_tpu", "scripts")
SCAN_FILES = ("bench.py",)
METRIC_METHODS = ("gauge", "counter", "histogram")


def _is_wallclock_call(node: ast.AST) -> bool:
    """``time.time()`` (or a bare ``time()`` imported from time)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "time":
        return isinstance(f.value, ast.Name) and f.value.id == "time"
    return False


def _check_tree(tree: ast.AST, rel: str) -> "list[dict]":
    out: "list[dict]" = []

    def finding(rule: str, node: ast.AST, msg: str):
        if rel in ALLOWLIST.get(rule, ()):
            return
        out.append({
            "rule": rule, "path": rel, "line": node.lineno,
            "message": msg,
        })

    for node in ast.walk(tree):
        # wallclock-compare: time.time() anywhere under a Compare.
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if _is_wallclock_call(sub):
                    finding(
                        "wallclock-compare", sub,
                        "time.time() inside a comparison — wall clock "
                        "jumps under NTP; use time.monotonic() or "
                        "time.perf_counter() for deadlines/elapsed",
                    )
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # uncataloged-metric: direct obj.gauge(/counter(/histogram( call.
        if isinstance(f, ast.Attribute) and f.attr in METRIC_METHODS:
            finding(
                "uncataloged-metric", node,
                f".{f.attr}(...) bypasses the metric catalog — create "
                "series via telemetry.declare(registry, name) so the "
                "catalog/docs checks cover it",
            )
        # unnamed-thread: threading.Thread(...) without name= or daemon=.
        is_thread = (
            isinstance(f, ast.Attribute) and f.attr == "Thread"
            and isinstance(f.value, ast.Name) and f.value.id == "threading"
        ) or (isinstance(f, ast.Name) and f.id == "Thread")
        if is_thread:
            kwargs = {kw.arg for kw in node.keywords}
            if not kwargs & {"name", "daemon"}:
                finding(
                    "unnamed-thread", node,
                    "threading.Thread without name= or daemon= — "
                    "anonymous threads are invisible in hang dumps and "
                    "non-daemons can block interpreter exit",
                )
    return out


def lint_file(path: str, rel: "str | None" = None) -> "list[dict]":
    rel = (rel or path).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    return _check_tree(tree, rel)


def iter_sources(root: str):
    """Yield (abspath, relpath) for every in-scope .py file. Tests are
    excluded by construction: tests/ is not a scan root."""
    for fname in SCAN_FILES:
        p = os.path.join(root, fname)
        if os.path.isfile(p):
            yield p, fname
    for top in SCAN_ROOTS:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fname)
                yield p, os.path.relpath(p, root).replace(os.sep, "/")


def lint_repo(root: str) -> "list[dict]":
    findings: "list[dict]" = []
    for path, rel in iter_sources(root):
        findings.extend(lint_file(path, rel))
    return findings


def main(argv: "list[str] | None" = None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/selflint.py",
        description="stdlib-ast hygiene lint over the repo's own source",
    )
    p.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root to scan (default: this script's repo)",
    )
    p.add_argument("--json", dest="json_out", action="store_true",
                   help="emit the findings as a JSON array on stdout")
    args = p.parse_args(argv)
    try:
        findings = lint_repo(args.root)
    except (OSError, SyntaxError) as e:
        print(f"selflint: {e}", file=sys.stderr)
        return 2
    if args.json_out:
        print(json.dumps(findings, indent=2))
    else:
        for f in findings:
            print(f"{f['path']}:{f['line']}: {f['rule']}: {f['message']}")
        print(
            f"selflint: {len(findings)} finding(s) over "
            f"{sum(1 for _ in iter_sources(args.root))} file(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
