"""Eval / inference path: BN calibration + frozen-statistics evaluation.

The reference framework never evaluates — its benchmarks train only, and
its BatchNorm running buffers are written but never read (there is no eval
or inference entry point anywhere under ``/root/reference/benchmarks``).
This module supplies the missing inference story in the TPU-idiomatic way:

1. **Calibration pass** (:func:`collect_batch_stats`): run a few training
   batches through the model under ``bn_stats_mode("collect")``, summing
   each BN site's per-batch moments into a ``batch_stats`` collection.
   With equal-size batches the averaged moments are the EXACT pooled
   statistics of the calibration set (mean of per-batch E[x] / E[x²] over
   equal counts == pooled E[x] / E[x²]) — no EMA decay error, and the
   train step stays pure (params-only, donated buffers) instead of
   threading mutable state through every trainer/pipeline/GEMS path.
   This is the BN re-estimation recipe used in stochastic-weight-averaging
   practice, and it is *more* faithful than torch's momentum-EMA buffers.

2. **Frozen-stats evaluation** (:func:`make_eval_step` / :func:`evaluate`):
   apply the model under ``bn_stats_mode("running")`` with the calibrated
   ``{mean, var}`` per BN site. Deterministic, batch-size independent.

Works with any cell list whose BNs are :class:`~mpi4dl_tpu.ops.layers.
TrainBatchNorm` or ``PackedTrainBatchNorm`` — i.e. every model the zoo
builds, in stock or packed layout. Evaluate on the *plain* twin of a
spatial model (identical parameter structure — ``partition.init_cells``):
inference has no reason to pay halo exchanges.
"""

from __future__ import annotations

import functools
from collections.abc import Mapping
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from mpi4dl_tpu.ops.layers import bn_stats_mode
from mpi4dl_tpu.train import correct_count, cross_entropy_sum

_STAT_KEYS = frozenset({"count", "mean_sum", "mean_sq_sum"})


def _finalize(tree):
    """Convert accumulated {count, mean_sum, mean_sq_sum} leaf groups into
    the frozen {mean, var} stats the "running" mode reads."""
    if isinstance(tree, Mapping):
        if _STAT_KEYS.issubset(tree.keys()):
            n = tree["count"]
            mean = tree["mean_sum"] / n
            var = tree["mean_sq_sum"] / n - jnp.square(mean)
            return {"mean": mean, "var": var}
        return {k: _finalize(v) for k, v in tree.items()}
    return tree


def collect_batch_stats(
    cells: Sequence[Any], params: Sequence[Any], batches
) -> list:
    """Exact pooled BN statistics over ``batches`` (iterable of input
    arrays, all the same shape). Returns one ``batch_stats`` dict per cell
    (``{}`` for cells with no BN), ready for :func:`make_eval_step`."""

    def one_batch(params, stats, x):
        with bn_stats_mode("collect"):
            out = []
            for cell, p, s in zip(cells, params, stats):
                variables = dict(p)
                if s:
                    variables["batch_stats"] = s
                x, upd = cell.apply(variables, x, mutable=["batch_stats"])
                out.append(upd.get("batch_stats", {}))
            return stats_unfreeze(out), x

    # Two traces total: the first batch initializes the collection (stats
    # arg is all-empty), later batches thread the accumulated structure.
    first = jax.jit(lambda p, x: one_batch(p, [{}] * len(cells), x)[0])
    rest = jax.jit(lambda p, s, x: one_batch(p, s, x)[0])

    stats = shape = None
    for x in batches:
        if shape is None:
            shape = x.shape
        elif x.shape != shape:
            # Unequal batches would be weighted equally, silently breaking
            # the exact-pooled-statistics guarantee — refuse instead (drop
            # or pad the trailing partial batch upstream).
            raise ValueError(
                f"calibration batches must share one shape for exact pooled "
                f"stats; got {shape} then {x.shape}"
            )
        stats = first(params, x) if stats is None else rest(params, stats, x)
    if stats is None:
        raise ValueError("collect_batch_stats needs at least one batch")
    return [_finalize(s) for s in stats]


def stats_unfreeze(stats):
    """Plain-dict view (flax may hand back FrozenDicts from ``mutable``)."""
    return [
        s.unfreeze() if hasattr(s, "unfreeze") else dict(s) for s in stats
    ]


def _apply_running(cells, params, batch_stats, x):
    with bn_stats_mode("running"):
        for cell, p, s in zip(cells, params, batch_stats):
            variables = dict(p)
            if s:
                variables["batch_stats"] = s
            x = cell.apply(variables, x)
    return x


# Memoized per cell tuple (flax modules are frozen/hashable): a trainer
# that evaluates every N steps must reuse ONE jitted callable, not retrace
# the full model per evaluate() call.
@functools.lru_cache(maxsize=None)
def _predict_for(cells: tuple):
    return jax.jit(
        lambda params, batch_stats, x: _apply_running(
            cells, params, batch_stats, x
        )
    )


@functools.lru_cache(maxsize=None)
def _eval_step_for(cells: tuple):
    def step(params, batch_stats, x, y):
        logits = _apply_running(cells, params, batch_stats, x)
        return {
            "loss": cross_entropy_sum(logits, y) / x.shape[0],
            "correct": correct_count(logits, y),
        }

    return jax.jit(step)


def make_predict(cells: Sequence[Any]):
    """Jitted ``(params, batch_stats, x) -> logits`` with frozen BN stats."""
    return _predict_for(tuple(cells))


def make_eval_step(cells: Sequence[Any]):
    """Jitted ``(params, batch_stats, x, y) -> {"loss", "correct"}``.
    loss = mean CE over the batch; correct = count of argmax hits."""
    return _eval_step_for(tuple(cells))


def evaluate(
    cells: Sequence[Any], params: Sequence[Any], batch_stats, batches
) -> dict:
    """Aggregate loss/accuracy over an iterable of ``(x, y)`` batches."""
    step = make_eval_step(cells)
    total = correct = 0
    loss_sum = 0.0
    for x, y in batches:
        m = step(params, batch_stats, x, y)
        b = x.shape[0]
        loss_sum += float(m["loss"]) * b
        correct += int(m["correct"])
        total += b
    if total == 0:
        raise ValueError("evaluate needs at least one batch")
    return {
        "loss": loss_sum / total,
        "accuracy": correct / total,
        "count": total,
    }
