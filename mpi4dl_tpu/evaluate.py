"""Eval / inference path: BN calibration + frozen-statistics evaluation.

The reference framework never evaluates — its benchmarks train only, and
its BatchNorm running buffers are written but never read (there is no eval
or inference entry point anywhere under ``/root/reference/benchmarks``).
This module supplies the missing inference story in the TPU-idiomatic way:

1. **Calibration pass** (:func:`collect_batch_stats`): run a few training
   batches through the model under ``bn_stats_mode("collect")``, summing
   each BN site's per-batch moments into a ``batch_stats`` collection.
   With equal-size batches the averaged moments are the EXACT pooled
   statistics of the calibration set (mean of per-batch E[x] / E[x²] over
   equal counts == pooled E[x] / E[x²]) — no EMA decay error, and the
   train step stays pure (params-only, donated buffers) instead of
   threading mutable state through every trainer/pipeline/GEMS path.
   This is the BN re-estimation recipe used in stochastic-weight-averaging
   practice, and it is *more* faithful than torch's momentum-EMA buffers.

2. **Frozen-stats evaluation** (:func:`make_eval_step` / :func:`evaluate`):
   apply the model under ``bn_stats_mode("running")`` with the calibrated
   ``{mean, var}`` per BN site. Deterministic, batch-size independent.

Works with any cell list whose BNs are :class:`~mpi4dl_tpu.ops.layers.
TrainBatchNorm` or ``PackedTrainBatchNorm`` — i.e. every model the zoo
builds, in stock or packed layout. Evaluate on the *plain* twin of a
spatial model (identical parameter structure — ``partition.init_cells``):
inference has no reason to pay halo exchanges.
"""

from __future__ import annotations

import functools
import time
from collections.abc import Mapping
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from mpi4dl_tpu.compat import axis_size
from mpi4dl_tpu.ops.layers import bn_stats_mode
from mpi4dl_tpu.train import correct_count, cross_entropy_sum

_STAT_KEYS = frozenset({"count", "mean_sum", "mean_sq_sum"})


def _finalize(tree):
    """Convert accumulated {count, mean_sum, mean_sq_sum} leaf groups into
    the frozen {mean, var} stats the "running" mode reads."""
    if isinstance(tree, Mapping):
        if _STAT_KEYS.issubset(tree.keys()):
            n = tree["count"]
            mean = tree["mean_sum"] / n
            var = tree["mean_sq_sum"] / n - jnp.square(mean)
            return {"mean": mean, "var": var}
        return {k: _finalize(v) for k, v in tree.items()}
    return tree


def collect_batch_stats(
    cells: Sequence[Any], params: Sequence[Any], batches
) -> list:
    """Exact pooled BN statistics over ``batches`` (iterable of input
    arrays, all the same shape). Returns one ``batch_stats`` dict per cell
    (``{}`` for cells with no BN), ready for :func:`make_eval_step`."""

    def one_batch(params, stats, x):
        with bn_stats_mode("collect"):
            out = []
            for cell, p, s in zip(cells, params, stats):
                variables = dict(p)
                if s:
                    variables["batch_stats"] = s
                x, upd = cell.apply(variables, x, mutable=["batch_stats"])
                out.append(upd.get("batch_stats", {}))
            return stats_unfreeze(out), x

    # Two traces total: the first batch initializes the collection (stats
    # arg is all-empty), later batches thread the accumulated structure.
    first = jax.jit(lambda p, x: one_batch(p, [{}] * len(cells), x)[0])
    rest = jax.jit(lambda p, s, x: one_batch(p, s, x)[0])

    stats = shape = None
    for x in batches:
        if shape is None:
            shape = x.shape
        elif x.shape != shape:
            # Unequal batches would be weighted equally, silently breaking
            # the exact-pooled-statistics guarantee — refuse instead (drop
            # or pad the trailing partial batch upstream).
            raise ValueError(
                f"calibration batches must share one shape for exact pooled "
                f"stats; got {shape} then {x.shape}"
            )
        stats = first(params, x) if stats is None else rest(params, stats, x)
    if stats is None:
        raise ValueError("collect_batch_stats needs at least one batch")
    return [_finalize(s) for s in stats]


def stats_unfreeze(stats):
    """Plain-dict view (flax may hand back FrozenDicts from ``mutable``)."""
    return [
        s.unfreeze() if hasattr(s, "unfreeze") else dict(s) for s in stats
    ]


def _apply_running(cells, params, batch_stats, x):
    with bn_stats_mode("running"):
        for cell, p, s in zip(cells, params, batch_stats):
            variables = dict(p)
            if s:
                variables["batch_stats"] = s
            x = cell.apply(variables, x)
    return x


# Memoized per cell tuple (flax modules are frozen/hashable): a trainer
# that evaluates every N steps must reuse ONE jitted callable, not retrace
# the full model per evaluate() call. Bounded (ADVICE r3): a long-lived
# process evaluating many DISTINCT models would otherwise pin every jitted
# executable for its lifetime; 8 live model families is far beyond any
# benchmark/eval loop here, and eviction only costs a retrace.
@functools.lru_cache(maxsize=8)
def _predict_for(cells: tuple):
    return jax.jit(
        lambda params, batch_stats, x: _apply_running(
            cells, params, batch_stats, x
        )
    )


@functools.lru_cache(maxsize=8)  # see _predict_for
def _eval_step_for(cells: tuple):
    def step(params, batch_stats, x, y):
        logits = _apply_running(cells, params, batch_stats, x)
        return {
            "loss": cross_entropy_sum(logits, y) / x.shape[0],
            "correct": correct_count(logits, y),
        }

    return jax.jit(step)


def make_predict(cells: Sequence[Any]):
    """Jitted ``(params, batch_stats, x) -> logits`` with frozen BN stats."""
    return _predict_for(tuple(cells))


def make_eval_step(cells: Sequence[Any]):
    """Jitted ``(params, batch_stats, x, y) -> {"loss", "correct"}``.
    loss = mean CE over the batch; correct = count of argmax hits."""
    return _eval_step_for(tuple(cells))


def aot_compile_predict(
    cells: Sequence[Any],
    params: Sequence[Any],
    batch_stats,
    example_shape: Sequence[int],
    buckets: Sequence[int],
    dtype=jnp.float32,
    timings: "dict | None" = None,
) -> dict:
    """AOT-lower the frozen-stats forward once per batch bucket.

    Returns ``{bucket: compiled}`` where each value is a ready
    ``jax.stages.Compiled`` executable for input shape
    ``(bucket, *example_shape)``. Compilation happens here — at serving
    warm-up — and never again: calling a ``Compiled`` object cannot trace
    or compile, so a request loop built on these executables is
    structurally incapable of paying a surprise JIT (the serving engine's
    no-compile-after-warm-up guarantee rests on this).

    When ``timings`` is a dict, each bucket's cold-start facts land in it
    as ``{bucket: {"trace_s", "compile_s", "fingerprint"}}`` — the
    trace/compile split plus the content fingerprint of the LOWERED
    program (:mod:`mpi4dl_tpu.telemetry.coldstart`), destined for the
    footprint ledger and ``compile_seconds{program, phase}``.
    """
    cells = tuple(cells)

    def fwd(p, s, x):
        return _apply_running(cells, p, s, x)

    out = {}
    for b in sorted({int(b) for b in buckets}):
        if b < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {b}")
        xs = jax.ShapeDtypeStruct((b, *tuple(example_shape)), dtype)
        t0 = time.perf_counter()
        lowered = jax.jit(fwd).lower(params, batch_stats, xs)
        t1 = time.perf_counter()
        out[b] = lowered.compile()
        t2 = time.perf_counter()
        if timings is not None:
            from mpi4dl_tpu.telemetry.coldstart import fingerprint_of

            timings[b] = {
                "trace_s": round(t1 - t0, 6),
                "compile_s": round(t2 - t1, 6),
                "fingerprint": fingerprint_of(lowered),
            }
    return out


def aot_compile_tiled_predict(
    cells: Sequence[Any],
    params: Sequence[Any],
    batch_stats,
    split: int,
    window_shape: Sequence[int],
    feature_shape: Sequence[int],
    tile_buckets: Sequence[int],
    dtype=jnp.float32,
    feature_dtype=None,
    timings: "dict | None" = None,
) -> dict:
    """AOT-lower the two halves of the tile-streaming forward
    (:mod:`mpi4dl_tpu.serve.tiled`): the SPATIAL SECTION (``cells[:split]``
    — conv/pool stack up to the head, the part that runs per overlap-read
    tile) once per tile bucket at the fixed ``window_shape``, and the HEAD
    (``cells[split:]`` — the post-gather global section) once at the full
    stitched ``feature_shape``. Returns ``{"tile": {bucket: compiled},
    "head": compiled}``.

    The section executable is the hot loop: a gigapixel request streams
    its tiles through THIS one fixed-shape program, so peak HBM is
    bounded by the window, never the image. Same no-surprise-JIT contract
    as :func:`aot_compile_predict` — compilation happens here, at serving
    warm-up, and a ``Compiled`` object can never trace again.
    """
    cells = tuple(cells)
    split = int(split)
    if not 0 < split < len(cells):
        raise ValueError(
            f"split must cut the cell list in two, got {split} of "
            f"{len(cells)} cells"
        )
    sec, head = cells[:split], cells[split:]
    p_sec, p_head = list(params[:split]), list(params[split:])
    s_sec, s_head = list(batch_stats[:split]), list(batch_stats[split:])

    def sec_fwd(p, s, x):
        return _apply_running(sec, p, s, x)

    def head_fwd(p, s, x):
        return _apply_running(head, p, s, x)

    def _timed(fn, *args):
        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        fp = None
        if timings is not None:
            from mpi4dl_tpu.telemetry.coldstart import fingerprint_of

            fp = fingerprint_of(lowered)
        return compiled, {
            "trace_s": round(t1 - t0, 6),
            "compile_s": round(t2 - t1, 6),
            "fingerprint": fp,
        }

    tile = {}
    for b in sorted({int(b) for b in tile_buckets}):
        if b < 1:
            raise ValueError(f"tile bucket sizes must be >= 1, got {b}")
        xs = jax.ShapeDtypeStruct((b, *tuple(window_shape)), dtype)
        tile[b], t = _timed(jax.jit(sec_fwd), p_sec, s_sec, xs)
        if timings is not None:
            timings[b] = t
    hs = jax.ShapeDtypeStruct(
        (1, *tuple(feature_shape)),
        feature_dtype if feature_dtype is not None else dtype,
    )
    head_c, t = _timed(jax.jit(head_fwd), p_head, s_head, hs)
    if timings is not None:
        timings["head"] = t
    return {"tile": tile, "head": head_c}


def evaluate(
    cells: Sequence[Any], params: Sequence[Any], batch_stats, batches
) -> dict:
    """Aggregate loss/accuracy over an iterable of ``(x, y)`` batches."""
    step = make_eval_step(cells)
    total = correct = 0
    loss_sum = 0.0
    for x, y in batches:
        m = step(params, batch_stats, x, y)
        b = x.shape[0]
        loss_sum += float(m["loss"]) * b
        correct += int(m["correct"])
        total += b
    if total == 0:
        raise ValueError("evaluate needs at least one batch")
    return {
        "loss": loss_sum / total,
        "accuracy": correct / total,
        "count": total,
    }


# -- sharded (spatial) calibration + eval ------------------------------------
#
# The plain-twin path above runs the FULL image on one device — fine for
# every size the framework is *not* needed for, impossible at the ≥2048px
# resolutions it exists for (VERDICT r3 weak #4). These variants run the
# trainer's own spatially-partitioned cells inside ``shard_map`` over its
# mesh: each device holds one image tile (halo exchanges included), the
# SP→LP join gathers tiles exactly like the train step, and BN runs in
# "collect"/"running" mode. Per-device activation footprint is the train
# step's forward — 1/num_tiles of the full image per device.


def _spatial_apply(trainer, params, stats, x, collect: bool):
    """Run the trainer's cells on local tiles (inside shard_map), threading
    ``batch_stats``. Returns (logits, updated_stats_or_None)."""
    from jax import lax

    from mpi4dl_tpu.parallel.halo import gather_tiles

    h = x
    out_stats = []
    for i, (cell, p, s) in enumerate(zip(trainer.cells, params, stats)):
        if i == trainer.n_spatial and trainer.n_spatial > 0:
            h = jax.tree.map(gather_tiles, h)
        variables = dict(p)
        if s:
            variables["batch_stats"] = s
        if collect:
            h, upd = cell.apply(variables, h, mutable=["batch_stats"])
            out_stats.append(upd.get("batch_stats", {}))
        else:
            h = cell.apply(variables, h)
    if not collect:
        return h, None
    # Pool the accumulated moments across the whole mesh: tile-local-stats
    # models (reduce_axes=()) contribute per-tile E[x]/E[x²] whose mean
    # over equal tiles is the global moment; cross-tile-BN models already
    # pmean-ed, making this a no-op. The data axis always needs it (each
    # shard saw different examples). "count" counts batches (identical on
    # every device), and pmean of an identical value is itself.
    from mpi4dl_tpu.config import AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W

    axes = (AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W)
    out_stats = jax.tree.map(lambda a: lax.pmean(a, axes), out_stats)
    return h, stats_unfreeze(out_stats)


def _spatial_metrics(trainer, logits, y):
    """psum-of-contributions loss/correct (the train step's bookkeeping,
    ``train.Trainer._local_loss``): exact regardless of how many tile
    devices redundantly compute the post-join section."""
    from jax import lax

    from mpi4dl_tpu.config import AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W

    replicas = axis_size(AXIS_TILE_H) * axis_size(AXIS_TILE_W)
    axes = (AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W)
    ce = lax.psum(cross_entropy_sum(logits, y) / replicas, axes)
    cc = lax.psum(
        correct_count(logits, y).astype(jnp.float32) / replicas, axes
    )
    return ce, cc


def make_spatial_eval_step(trainer):
    """Jitted sharded ``(params, batch_stats, x, y) -> (ce_sum, correct)``
    running the trainer's spatial forward under frozen BN stats. ``x``/``y``
    must be placed with ``trainer.shard_batch``; loss is the CE *sum* over
    the global batch (callers normalize, as in :func:`spatial_evaluate`).
    Memoized on the trainer (same requirement as ``_eval_step_for``: a
    caller evaluating every N steps must reuse ONE jitted callable, not
    pay a full ≥2048px retrace per eval)."""
    cached = getattr(trainer, "_spatial_eval_step", None)
    if cached is not None:
        return cached
    from mpi4dl_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    def local(params, batch_stats, x, y):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()
        with bn_stats_mode("running"):
            logits, _ = _spatial_apply(trainer, params, batch_stats, x, False)
        ce, cc = _spatial_metrics(trainer, logits, y)
        return ce, cc

    fn = jax.jit(
        shard_map(
            local,
            mesh=trainer.mesh,
            in_specs=(P(), P(), trainer.x_spec, trainer.y_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
    trainer._spatial_eval_step = fn
    return fn


def aot_compile_spatial_predict(
    trainer,
    params,
    batch_stats,
    example_shape: Sequence[int],
    buckets: Sequence[int],
    dtype=jnp.float32,
    timings: "dict | None" = None,
) -> dict:
    """Sharded counterpart of :func:`aot_compile_predict`: AOT-lower the
    trainer's spatially-partitioned frozen-stats forward once per batch
    bucket, over the trainer's own ``tile_h×tile_w`` mesh.

    Each executable runs the :func:`make_spatial_eval_step` forward —
    tile-local spatial cells with halo exchanges, the SP→LP tile merge,
    then the replicated head — and returns the logits instead of metrics,
    so the serving engine can put a model whose single-chip forward does
    not fit one device directly on its request hot loop. ``params`` /
    ``batch_stats`` must already be placed replicated on the mesh
    (``NamedSharding(mesh, P())``); the input bucket is lowered with the
    trainer's ``x_spec`` sharding attached, so the compiled executable
    accepts exactly the staged arrays the sharded predictor produces.

    Same no-surprise-JIT contract as the single-chip path: compilation
    happens here, at serving warm-up, and calling a ``Compiled`` object
    can never trace or compile again.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.compat import shard_map
    from mpi4dl_tpu.config import AXIS_DATA

    mesh = trainer.mesh

    def local(p, s, x):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()
        with bn_stats_mode("running"):
            logits, _ = _spatial_apply(trainer, p, s, x, False)
        return logits

    # Logits come out batch-sharded over the data axis only (size 1 on a
    # serving mesh — the whole bucket on every tile) and replicated over
    # the tile axes: every tile device computes the identical post-join
    # head on the gathered activations.
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), trainer.x_spec),
            out_specs=P(AXIS_DATA),
            check_vma=False,
        )
    )
    x_sharding = NamedSharding(mesh, trainer.x_spec)
    mesh_shape = tuple(mesh.devices.shape)
    out = {}
    for b in sorted({int(b) for b in buckets}):
        if b < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {b}")
        xs = jax.ShapeDtypeStruct(
            (b, *tuple(example_shape)), dtype, sharding=x_sharding
        )
        t0 = time.perf_counter()
        lowered = fn.lower(params, batch_stats, xs)
        t1 = time.perf_counter()
        out[b] = lowered.compile()
        t2 = time.perf_counter()
        if timings is not None:
            from mpi4dl_tpu.telemetry.coldstart import fingerprint_of

            # The mesh shape feeds the fingerprint: the same forward on a
            # 2x2 vs 1x4 tile grid is a different executable to cache.
            timings[b] = {
                "trace_s": round(t1 - t0, 6),
                "compile_s": round(t2 - t1, 6),
                "fingerprint": fingerprint_of(lowered, mesh_shape=mesh_shape),
            }
    return out


def spatial_collect_batch_stats(trainer, params, batches) -> list:
    """Exact pooled BN statistics computed on the trainer's own spatial
    cells over its mesh — the sharded counterpart of
    :func:`collect_batch_stats` for models whose full-image forward does
    not fit one device. ``batches``: iterable of host input arrays (global
    batch shape, like the training inputs)."""
    from mpi4dl_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    def local_first(params, x):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()
        with bn_stats_mode("collect"):
            _, stats = _spatial_apply(
                trainer, params, [{}] * len(trainer.cells), x, True
            )
        return stats

    def local_rest(params, stats, x):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()
        with bn_stats_mode("collect"):
            _, stats = _spatial_apply(trainer, params, stats, x, True)
        return stats

    mesh = trainer.mesh
    cached = getattr(trainer, "_spatial_collect_fns", None)
    if cached is not None:  # memoized like make_spatial_eval_step
        first, rest = cached
    else:
        first = jax.jit(
            shard_map(
                local_first, mesh=mesh, in_specs=(P(), trainer.x_spec),
                out_specs=P(), check_vma=False,
            )
        )
        rest = jax.jit(
            shard_map(
                local_rest, mesh=mesh, in_specs=(P(), P(), trainer.x_spec),
                out_specs=P(), check_vma=False,
            )
        )
        trainer._spatial_collect_fns = (first, rest)

    from mpi4dl_tpu.parallel.multihost import put_global

    stats = shape = None
    for x in batches:
        if shape is None:
            shape = x.shape
        elif x.shape != shape:
            raise ValueError(
                f"calibration batches must share one shape for exact pooled "
                f"stats; got {shape} then {x.shape}"
            )
        (xs,) = put_global(mesh, (trainer.x_spec,), x)
        stats = first(params, xs) if stats is None else rest(params, stats, xs)
    if stats is None:
        raise ValueError("spatial_collect_batch_stats needs at least one batch")
    return [_finalize(s) for s in jax.device_get(stats)]


def spatial_evaluate(trainer, params, batch_stats, batches) -> dict:
    """Sharded counterpart of :func:`evaluate`: aggregate loss/accuracy over
    ``(x, y)`` host batches through the trainer's spatial forward."""
    step = make_spatial_eval_step(trainer)
    total = 0
    correct = 0.0
    loss_sum = 0.0
    for x, y in batches:
        xs, ys = trainer.shard_batch(x, y)
        ce, cc = step(params, batch_stats, xs, ys)
        loss_sum += float(ce)
        correct += float(cc)
        # ce/cc are psum-ed GLOBAL sums; count the assembled global batch
        # (multi-process, x is only this host's shard of it).
        total += int(xs.shape[0])
    if total == 0:
        raise ValueError("spatial_evaluate needs at least one batch")
    return {
        "loss": loss_sum / total,
        "accuracy": correct / total,
        "count": total,
    }
