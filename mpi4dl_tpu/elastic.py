"""In-run fault tolerance: supervised training with restart-from-checkpoint.

The reference has no failure handling at all — a dead or wedged rank hangs
the MPI world until the scheduler kills the job (SURVEY.md §5.3: no
timeout, no health check, no restart anywhere under ``/root/reference``).
This module is the TPU-native answer, shaped by how JAX actually fails:

- The runtime is **single-controller**: after a device fault, a poisoned
  XLA runtime, or a wedged collective, the *process* is unrecoverable —
  there is no rank-level rejoin the way an MPI world might attempt.
  Recovery therefore means **process restart + resume from the last atomic
  checkpoint** (``mpi4dl_tpu/checkpoint.py`` publishes via ``os.replace``,
  so a crash mid-save can never leave a torn checkpoint).
- Failures come in two shapes: the process **exits nonzero** (Python
  exception, runtime abort, OOM kill) — detected by ``wait()`` — or it
  **wedges silently** (deadlocked collective, hung remote compile, stuck
  host callback) — detected by a **heartbeat file** the training loop
  touches every step; staleness beyond ``hang_timeout`` gets the child
  killed and restarted. The reference's failure mode IS the second shape,
  and it has no detector.

The supervisor must run **before the process touches the accelerator**: a
parent holding the TPU would lock its own children out of the device
(TPU access is exclusive per process). ``benchmarks/common.py`` therefore
re-execs under :func:`supervise` at ``build_config`` time, before any
``jax.devices()`` call, when ``--max-restarts`` is set.

Scope: single-host supervision. Multi-host jobs need every host's
supervisor to restart its process for the world to re-form
(``jax.distributed`` barriers at init) — run one supervisor per host under
your scheduler; coordinated multi-host elasticity beyond that is an
orchestrator concern, not a framework one.
"""

from __future__ import annotations

import collections
import os
import random
import subprocess
import sys
import threading
import time

HEARTBEAT_ENV = "MPI4DL_TPU_HEARTBEAT"
CHILD_ENV = "MPI4DL_TPU_SUPERVISED_CHILD"


def full_jitter_backoff(
    attempt: int,
    base_s: float = 0.5,
    max_s: float = 30.0,
    rng=random.random,
) -> float:
    """AWS-style full-jitter exponential backoff: uniform in
    ``[0, min(max_s, base_s * 2**(attempt-1))]``. Full jitter (rather
    than a jittered fraction) is what decorrelates a fleet of
    supervisors all restarting replicas that died of the same cause —
    a thundering herd of synchronized respawns would re-trigger it.
    ``attempt`` counts from 1; 0 or negative means no wait."""
    if attempt <= 0 or base_s <= 0:
        return 0.0
    cap = min(float(max_s), float(base_s) * (2.0 ** (attempt - 1)))
    return cap * rng()


def restart_event(
    attempt: int,
    backoff_s: float,
    reason: str,
    events=None,
    flight=None,
    **attrs,
) -> dict:
    """Emit one schema-valid ``elastic.restart`` event (kind="event")
    into the JSONL event log and/or flight ring; returns the event so
    callers can also surface it inline. Supervisors — the single-process
    :func:`supervise` and the fleet supervisor — share this shape, so
    the postmortem story for "why did this process bounce" is one
    query regardless of which babysitter did the bouncing."""
    from mpi4dl_tpu.telemetry.jsonl import validate_event

    ev = validate_event({
        "ts": time.time(),
        "kind": "event",
        "name": "elastic.restart",
        "attrs": {
            "attempt": int(attempt),
            "backoff_s": float(backoff_s),
            "reason": str(reason),
            **attrs,
        },
    })
    if flight is not None and getattr(flight, "enabled", True):
        flight.record(ev)
    if events is not None and getattr(events, "enabled", True):
        events.write(ev)
    return ev


class RestartBreaker:
    """Max-restarts-per-window circuit breaker.

    ``max_restarts`` failures recorded within ``window_s`` seconds trip
    the breaker: :meth:`allow` answers False until :meth:`reset`.
    ``window_s=None`` degrades to a lifetime budget (the pre-breaker
    behavior of :func:`supervise`). A crash-looping child that fails K
    times in a burst must stop being restarted — each respawn costs a
    cold compile and can re-poison a shared accelerator — while a
    process that fails K times across a week keeps its supervisor."""

    def __init__(
        self,
        max_restarts: int,
        window_s: "float | None" = None,
        clock=time.monotonic,
    ):
        self.max_restarts = int(max_restarts)
        self.window_s = None if window_s is None else float(window_s)
        self._clock = clock
        self._failures: collections.deque = collections.deque()
        self.tripped = False

    def record_failure(self) -> None:
        self._failures.append(self._clock())

    def _in_window(self) -> int:
        if self.window_s is not None:
            cutoff = self._clock() - self.window_s
            while self._failures and self._failures[0] < cutoff:
                self._failures.popleft()
        return len(self._failures)

    def allow(self) -> bool:
        """May the supervisor restart now? Trips (sticky) when the
        windowed failure count exceeds the budget."""
        if self.tripped:
            return False
        if self._in_window() > self.max_restarts:
            self.tripped = True
            return False
        return True

    def reset(self) -> None:
        self._failures.clear()
        self.tripped = False

    def state(self) -> dict:
        return {
            "tripped": self.tripped,
            "failures_in_window": self._in_window(),
            "max_restarts": self.max_restarts,
            "window_s": self.window_s,
        }


def touch(path: str) -> None:
    """Update the heartbeat file's mtime (creating it if needed). Called by
    the training loop once per step — cheap (one utime syscall)."""
    try:
        os.utime(path, None)
    except FileNotFoundError:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a"):
            pass


def heartbeat_path_from_env() -> str | None:
    """The heartbeat file this (child) process should touch, if supervised."""
    return os.environ.get(HEARTBEAT_ENV)


class HeartbeatReporter:
    """Health-gated heartbeat: beats stop the moment the process stops
    being useful, so the supervisor's staleness detector fires.

    The training loop beats inline (:func:`touch` per step — a stalled
    loop stops beating by construction). A SERVING replica has no such
    luck: its submit path and HTTP threads keep running while the batcher
    is wedged, so a naive timer thread would keep the heartbeat fresh
    forever and :func:`supervise` would never fire — the exact
    wedged-but-alive shape the reference suffers from (SURVEY §5.3).
    This reporter closes the loop with the liveness machinery from
    :mod:`mpi4dl_tpu.telemetry.health`: a daemon thread touches ``path``
    every ``interval_s`` ONLY while the :class:`HealthState` says healthy
    and the :class:`Watchdog` (if given) is not tripped. A watchdog trip
    (batcher stalled) or a crash-flipped health state silences the
    heartbeat; after ``hang_timeout`` of silence the supervisor kills and
    restarts the replica. Health recovering (work completing again)
    resumes the beats — a transient stall that self-heals before the
    timeout costs nothing.

    health: a :class:`mpi4dl_tpu.telemetry.HealthState`
        (``engine.health``); None = always considered healthy.
    watchdog: a :class:`mpi4dl_tpu.telemetry.Watchdog`; its tripped
        state gates beats even when no health object is wired.
    """

    def __init__(
        self,
        path: str,
        health=None,
        watchdog=None,
        interval_s: float = 0.5,
    ):
        self.path = path
        self.health = health
        self.watchdog = watchdog
        self.interval_s = float(interval_s)
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    def healthy(self) -> bool:
        if self.health is not None and not self.health.healthy:
            return False
        if self.watchdog is not None and self.watchdog.state()["tripped"]:
            return False
        return True

    def beat_once(self) -> bool:
        """Touch the heartbeat iff the process is healthy; returns
        whether it beat."""
        if not self.healthy():
            return False
        touch(self.path)
        return True

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self.beat_once()  # cover the gap before the first interval
        self._thread = threading.Thread(
            target=self._run, name="mpi4dl-heartbeat", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.beat_once()
            except OSError:
                pass  # a transient fs error must not kill the reporter

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def supervise(
    argv: list[str],
    max_restarts: int = 3,
    hang_timeout: float | None = None,
    heartbeat_path: str | None = None,
    resume_arg: str | None = "--resume",
    poll_interval: float = 0.5,
    backoff_base_s: float = 0.5,
    backoff_max_s: float = 30.0,
    restart_window_s: "float | None" = None,
    events=None,
    flight=None,
    rng=random.random,
    _sleep=time.sleep,
    _print=None,
) -> int:
    """Run ``python argv`` under supervision; restart on failure.

    argv: script + args (``sys.argv`` of the training entry point).
    max_restarts: restarts allowed before giving up with the child's rc.
        With ``restart_window_s`` set this is a per-window budget (a
        :class:`RestartBreaker`): more than ``max_restarts`` failures
        inside the window trips the breaker and gives up, while the same
        count spread over a longer span keeps restarting — the
        crash-loop / occasional-crash distinction. None keeps the
        lifetime-budget behavior.
    hang_timeout: seconds of heartbeat staleness before the child is
        declared wedged and killed (None/0 disables hang detection). Must
        comfortably exceed the longest legitimate gap between steps — the
        first step's XLA compile can take minutes cold.
    heartbeat_path: file the child touches each step (exported via
        ``MPI4DL_TPU_HEARTBEAT``). Required for hang detection.
    resume_arg: appended to restarted children (skipped if already
        present) so they continue from the newest checkpoint instead of
        step 0. Pass None when the entry point auto-resumes.
    backoff_base_s / backoff_max_s: exponential backoff with full jitter
        (:func:`full_jitter_backoff`) before each restart — an
        immediately-fatal environment (bad flag, poisoned device) must
        not be hammered at poll speed, and jitter decorrelates sibling
        supervisors. ``backoff_base_s=0`` restarts immediately.
    events / flight: optional :class:`telemetry.JsonlWriter` /
        :class:`telemetry.FlightRecorder`; every restart emits a
        schema-valid ``elastic.restart`` event into both.
    rng / _sleep: injectable for deterministic tests.

    Returns the final exit code (0 on eventual success).
    """
    if hang_timeout and not heartbeat_path:
        raise ValueError("hang_timeout needs a heartbeat_path")
    say = _print or (lambda m: print(m, flush=True))
    breaker = RestartBreaker(max_restarts, window_s=restart_window_s)
    restarts = 0
    while True:
        cmd = [sys.executable] + list(argv)
        if restarts and resume_arg and resume_arg not in cmd:
            cmd.append(resume_arg)
        env = os.environ.copy()
        env[CHILD_ENV] = "1"
        if heartbeat_path:
            env[HEARTBEAT_ENV] = heartbeat_path
            touch(heartbeat_path)  # fresh epoch — compile time counts from now
        proc = subprocess.Popen(cmd, env=env)
        hung = False
        # Staleness is timed by OUR monotonic clock from the last observed
        # mtime CHANGE — never by comparing mtime against time.time(),
        # which breaks under clock skew between the filesystem and the
        # system clock (observed ~2s on overlay filesystems).
        last_mtime: float | None = None
        last_beat = time.monotonic()  # spawn counts as a beat (compile time)
        try:
            while proc.poll() is None:
                if hang_timeout and heartbeat_path:
                    try:
                        mtime = os.path.getmtime(heartbeat_path)
                    except OSError:
                        mtime = None
                    if mtime != last_mtime:
                        last_mtime = mtime
                        last_beat = time.monotonic()
                    stale = time.monotonic() - last_beat
                    if stale > hang_timeout:
                        say(
                            f"elastic: no heartbeat for {stale:.0f}s "
                            f"(> {hang_timeout}s) — killing wedged child"
                        )
                        proc.kill()
                        proc.wait()
                        hung = True
                        break
                time.sleep(poll_interval)
        except BaseException:
            # The supervisor must NEVER orphan a training process — a
            # KeyboardInterrupt (or any bug here) would otherwise leave a
            # child holding the accelerator.
            proc.kill()
            proc.wait()
            raise
        rc = proc.returncode
        if not hung and rc == 0:
            if restarts:
                say(f"elastic: completed after {restarts} restart(s)")
            return 0
        restarts += 1
        breaker.record_failure()
        if not breaker.allow():
            window = (
                f" within {restart_window_s:g}s"
                if restart_window_s else ""
            )
            say(
                f"elastic: giving up after {max_restarts} restart(s)"
                f"{window} (last rc={rc})"
            )
            return rc if rc not in (None, 0) else 1
        reason = "wedged" if hung else f"rc={rc}"
        backoff = full_jitter_backoff(
            restarts, base_s=backoff_base_s, max_s=backoff_max_s, rng=rng
        )
        restart_event(
            restarts, backoff, reason,
            events=events, flight=flight, max_restarts=max_restarts,
        )
        say(
            f"elastic: child {'wedged' if hung else f'failed rc={rc}'} — "
            f"restarting ({restarts}/{max_restarts})"
            + (f" after {backoff:.2f}s backoff" if backoff > 0 else "")
        )
        if backoff > 0:
            _sleep(backoff)


def maybe_supervise(args) -> None:
    """Re-exec the current process under :func:`supervise` if
    ``--max-restarts`` was requested; no-op in the supervised child (or
    when unset). MUST be called before the process touches the
    accelerator — see module docstring. On supervision, never returns
    (``sys.exit`` with the supervised run's final code)."""
    if not getattr(args, "max_restarts", 0) or os.environ.get(CHILD_ENV):
        return
    ckpt_dir = getattr(args, "checkpoint_dir", None)
    if ckpt_dir:
        hb = os.path.join(ckpt_dir, "heartbeat")
    else:
        print(
            "elastic: --max-restarts without --checkpoint-dir — restarts "
            "will recompute from step 0",
            flush=True,
        )
        # Per-run unique path: a shared ./heartbeat would let two
        # concurrent supervised runs keep each other's wedge detector
        # permanently fresh (neither would ever fire).
        import tempfile

        fd, hb = tempfile.mkstemp(prefix="mpi4dl_tpu_heartbeat_")
        os.close(fd)
    sys.exit(
        supervise(
            sys.argv,
            max_restarts=args.max_restarts,
            hang_timeout=getattr(args, "hang_timeout", None),
            heartbeat_path=hb,
        )
    )
