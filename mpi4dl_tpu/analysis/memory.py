"""Peak-memory extraction from compiled executables + committed baselines.

``compiled.memory_analysis()`` reports the buffer-assignment totals on every
backend of this runtime (CPU included, which is what makes the regression
gate runnable in CI without a TPU). Peak is taken as
``argument + output + temp - alias`` — arguments/outputs that alias
(donated train state) are counted once, matching how the allocator sees the
program. Baselines are committed JSON (``docs/artifacts/hlolint_baseline.json``)
keyed by an explicit config string, so a regression is a diff against a
reviewed number, not against whatever the previous CI run happened to see.
"""

from __future__ import annotations

import json
import os

_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "docs", "artifacts", "hlolint_baseline.json",
)


def memory_summary(compiled) -> dict | None:
    """Byte totals for a compiled executable, or None when the backend
    can't report them (the lint then simply skips the memory rule)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent, absence is fine
        return None
    if ma is None:
        return None
    out = {}
    for f in _FIELDS:
        v = getattr(ma, f, None)
        if v is not None:
            out[f.replace("_size_in_bytes", "_bytes")] = int(v)
    if not out:
        return None
    out["peak_bytes"] = (
        out.get("argument_bytes", 0)
        + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0)
        - out.get("alias_bytes", 0)
    )
    return out


def feasibility(
    peak_bytes: "int | None", limit_bytes: "int | None",
    fit_margin: float = 0.0,
) -> dict:
    """Verdict for one predicted peak against a device limit: does the
    program fit, and with how much headroom. ``fit_margin`` reserves a
    fraction of the limit (0.05 = demand 5% free after the program);
    with either side unknown the verdict is ``fits=None``, never a
    fabricated yes/no."""
    out = {
        "peak_bytes": None if peak_bytes is None else int(peak_bytes),
        "limit_bytes": None if limit_bytes is None else int(limit_bytes),
        "fits": None,
        "headroom_bytes": None,
        "headroom_ratio": None,
    }
    if peak_bytes is None or not limit_bytes:
        return out
    headroom = int(limit_bytes) - int(peak_bytes)
    out["headroom_bytes"] = headroom
    out["headroom_ratio"] = headroom / int(limit_bytes)
    out["fits"] = bool(out["headroom_ratio"] >= float(fit_margin))
    return out


def load_baseline_all(path: str | None = None) -> dict:
    """Every committed peak: ``{key: peak_bytes}`` (the planner's
    artifact mode reads the whole table, not one key)."""
    path = path or DEFAULT_BASELINE_PATH
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001 — absent/corrupt = empty
        return {}
    out = {}
    for key, ent in data.items():
        if isinstance(ent, dict):
            ent = ent.get("peak_bytes")
        if ent is not None:
            out[key] = int(ent)
    return out


def load_baseline(key: str, path: str | None = None) -> int | None:
    path = path or DEFAULT_BASELINE_PATH
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001 — absent/corrupt = no baseline
        return None
    ent = data.get(key)
    if isinstance(ent, dict):
        ent = ent.get("peak_bytes")
    return int(ent) if ent is not None else None


def write_baseline(key: str, peak_bytes: int, path: str | None = None) -> str:
    """Record/refresh one config's committed peak (sorted, stable diffs)."""
    path = path or DEFAULT_BASELINE_PATH
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001
        data = {}
    data[key] = {"peak_bytes": int(peak_bytes)}
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(dict(sorted(data.items())), f, indent=2, sort_keys=True)
        f.write("\n")
    return path
