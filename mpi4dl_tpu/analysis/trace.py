"""Runtime device-time attribution from XProf Chrome traces.

hlolint (:mod:`mpi4dl_tpu.analysis`) statically predicts communication
structure and overlap from scheduled HLO; this module measures what
actually happened at runtime. :func:`mpi4dl_tpu.profiling.capture` wraps
``jax.profiler.trace`` around N annotated steps; the profiler emits a
Chrome-trace JSON (``plugins/profile/<run>/*.trace.json.gz``) that this
parser reads with stdlib ``gzip`` + ``json`` only — no TF/protobuf/xprof
dependency — and turns into:

- a typed event inventory (:class:`TraceEvent`) split into host and
  device timelines by thread identity (CPU: the ``XLATfrtCpuClient``
  executor threads carry per-HLO-op slices; TPU/GPU: ``/device:*``
  process timelines, preferring the ``XLA Ops`` line to avoid counting
  the module/step summary lines twice);
- per-step attribution (:func:`attribute_steps`): device slices are
  joined to the ``StepTraceAnnotation`` windows the train/serve dispatch
  paths already emit (:func:`mpi4dl_tpu.profiling.annotate_step`, the
  same host-side step ids the telemetry span log records), and each
  step's wall time is bucketed into **compute / collective / transfer /
  host_gap**. The buckets are exclusive by construction (priority
  collective > transfer > compute on the merged interval union, host_gap
  = wall − device-busy), so they sum exactly to the step wall time;
- a **measured-overlap** report: for every collective slice, the
  fraction of its duration during which compute was concurrently running
  on another device timeline — the runtime counterpart of the static
  start→done ``compute_between`` rule, per T3 (arXiv:2401.16677) / FLUX
  (arXiv:2406.06858) the quantity that decides spatial-parallel
  performance;
- :func:`crosscheck_overlap`: static verdict vs measured verdict on the
  same executable; disagreement ("schedule says the window is covered,
  the trace shows exposed latency") is a new lint finding
  (rule ``trace-overlap-crosscheck``).

Degradation contract (tier-1 tested): a missing/empty trace directory
raises :class:`TraceError` at the reader — never a KeyError three layers
down — and a trace with no step annotations still yields a whole-range
attribution (``n_steps == 0``) instead of failing.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import re

from mpi4dl_tpu.analysis.rules import Finding

#: Substrings (hyphenated HLO opcode stems) that mark a device slice as
#: collective traffic. Fusion kernel names use underscores, so an
#: ``all_reduce_fusion`` compute kernel does not false-positive here.
COLLECTIVE_MARKERS = (
    "collective-permute",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "reduce-scatter",
    "collective-broadcast",
    "ragged-all-to-all",
)

#: Case-insensitive substrings marking host<->device / device<->device
#: data movement (the "h2d" bucket; includes d2h and d2d).
TRANSFER_MARKERS = (
    "transfertodevice",
    "transferfromdevice",
    "transferraw",
    "d2d dispatch",
    "h2d",
    "d2h",
    "infeed",
    "outfeed",
    "copy-start",
    "copy-done",
    "bufferfromhost",
    "buffertohost",
)

#: Thread-name substrings that mark a CPU-backend device timeline: the
#: per-device TfrtCpuClient executor threads AND the shared XLAEigen
#: intra-op pool — XLA's thunk executor schedules op thunks onto either,
#: and which one a given op lands on varies run to run.
_CPU_DEVICE_THREAD_MARKERS = (
    "XLATfrtCpuClient",
    "TfrtCpuDevice",
    "XLAEigen",
)

#: Runtime bookkeeping that shows up on device executor threads but is
#: not op execution (waits, region markers, executable wrappers). Counting
#: the ``ExecuteHelper`` wrapper would double every op under it.
_INFRA_PREFIXES = (
    "ThreadpoolListener",
    "ThunkExecutor",
    "TfrtCpu",
    "ParseArguments",
    "PjitFunction",
    "ExecuteThunks",
    "$",  # python-source host slices
)

_TRAILING_ID = re.compile(r"\.\d+$")

CATEGORIES = ("compute", "collective", "transfer", "host_gap")

#: Measured overlap ratio at/above which a trace's collective time counts
#: as "overlapped" (hidden behind compute) rather than "exposed".
OVERLAPPED_MIN = 0.5


class TraceError(RuntimeError):
    """The trace directory is missing, empty, or unreadable."""


@dataclasses.dataclass
class TraceEvent:
    """One complete ("X") slice from the Chrome trace, times in seconds."""

    name: str
    pid: int
    tid: int
    start_s: float
    end_s: float
    category: str  # "compute" | "collective" | "transfer"

    @property
    def duration_s(self) -> float:
        return self.end_s - self.start_s


def categorize(name: str) -> "str | None":
    """Device-slice category for an event name, or None for runtime
    bookkeeping that must not count as device busy time."""
    if any(m in name for m in COLLECTIVE_MARKERS):
        return "collective"
    low = name.lower()
    if any(m in low for m in TRANSFER_MARKERS):
        return "transfer"
    if any(name.startswith(p) for p in _INFRA_PREFIXES):
        return None
    return "compute"


def read_trace_events(trace_dir: str) -> "list[dict]":
    """Raw ``traceEvents`` of the NEWEST profiler run under ``trace_dir``
    (``plugins/profile/<run>/*.trace.json[.gz]``), all hosts merged.
    Raises :class:`TraceError` when there is nothing to read."""
    if not os.path.isdir(trace_dir):
        raise TraceError(f"trace directory {trace_dir!r} does not exist")
    runs = sorted(glob.glob(os.path.join(trace_dir, "plugins", "profile", "*")))
    if not runs:
        raise TraceError(
            f"no profiler runs under {trace_dir!r} (expected "
            "plugins/profile/<run>/ — did the capture actually trace?)"
        )
    run = runs[-1]
    files = sorted(
        glob.glob(os.path.join(run, "*.trace.json.gz"))
        + glob.glob(os.path.join(run, "*.trace.json"))
    )
    if not files:
        raise TraceError(f"profiler run {run!r} has no *.trace.json[.gz]")
    events: list[dict] = []
    for path in files:
        opener = gzip.open if path.endswith(".gz") else open
        try:
            with opener(path, "rb") as f:
                data = json.loads(f.read())
        except (OSError, ValueError) as e:
            raise TraceError(f"unreadable trace file {path!r}: {e}") from e
        events.extend(data.get("traceEvents") or [])
    return events


def _name_tables(events) -> "tuple[dict, dict]":
    """(process names by pid, thread names by (pid, tid)) from "M" events."""
    procs: dict = {}
    threads: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", "")
            )
    return procs, threads


def device_slices(events) -> "list[TraceEvent]":
    """Device-timeline op slices, categorized; host threads and runtime
    bookkeeping excluded.

    CPU: XLA runs op thunks on the per-device ``XLATfrtCpuClient``
    executor threads and the shared ``XLAEigen`` intra-op pool — both are
    device timelines here. TPU/GPU: each device is a ``/device:*``
    process whose ``XLA Ops`` thread carries the op timeline — when that
    named line exists only it is used, since the ``XLA
    Modules``/``Steps`` lines cover the same wall time again.
    """
    procs, threads = _name_tables(events)
    dev_pids = {
        pid for pid, name in procs.items()
        if str(name).startswith("/device:")
    }
    # Per accelerator pid: restrict to the "XLA Ops" line when present.
    ops_threads: dict = {}
    for (pid, tid), tname in threads.items():
        if pid in dev_pids and "XLA Ops" in str(tname):
            ops_threads.setdefault(pid, set()).add(tid)

    out: list[TraceEvent] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        pid, tid = e.get("pid"), e.get("tid")
        tname = str(threads.get((pid, tid), ""))
        if pid in dev_pids:
            allowed = ops_threads.get(pid)
            if allowed is not None and tid not in allowed:
                continue
            if any(k in tname for k in ("Steps", "Modules", "Framework",
                                        "Scope", "Source")):
                continue
        elif not any(m in tname for m in _CPU_DEVICE_THREAD_MARKERS):
            continue  # host thread
        cat = categorize(str(e.get("name", "")))
        if cat is None:
            continue
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        out.append(TraceEvent(
            name=str(e.get("name")), pid=pid, tid=tid,
            start_s=ts / 1e6, end_s=(ts + dur) / 1e6, category=cat,
        ))
    out.sort(key=lambda ev: ev.start_s)
    return out


def step_windows(events, step_name: str) -> "list[tuple[float, float, str]]":
    """``(start_s, end_s, step_num)`` for every X event named exactly
    ``step_name`` — the ``StepTraceAnnotation`` windows."""
    out = []
    for e in events:
        if e.get("ph") != "X" or e.get("name") != step_name:
            continue
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        num = str((e.get("args") or {}).get("step_num", len(out)))
        out.append((ts / 1e6, (ts + dur) / 1e6, num))
    out.sort()
    return out


# -- interval algebra (merged, half-open [s, e) second intervals) -------------


def _merged(intervals) -> "list[tuple[float, float]]":
    out: list[list[float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _total(merged) -> float:
    return sum(e - s for s, e in merged)


def _clip(intervals, lo: float, hi: float):
    return [
        (max(s, lo), min(e, hi))
        for s, e in intervals
        if min(e, hi) > max(s, lo)
    ]


def _intersect(a_merged, b_merged) -> "list[tuple[float, float]]":
    out, i, j = [], 0, 0
    while i < len(a_merged) and j < len(b_merged):
        s = max(a_merged[i][0], b_merged[j][0])
        e = min(a_merged[i][1], b_merged[j][1])
        if e > s:
            out.append((s, e))
        if a_merged[i][1] <= b_merged[j][1]:
            i += 1
        else:
            j += 1
    return out


def _subtract(a_merged, b_merged) -> "list[tuple[float, float]]":
    out = []
    j = 0
    for s, e in a_merged:
        cur = s
        while j < len(b_merged) and b_merged[j][1] <= cur:
            j += 1
        k = j
        while k < len(b_merged) and b_merged[k][0] < e:
            bs, be = b_merged[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            k += 1
        if cur < e:
            out.append((cur, e))
    return out


# -- attribution --------------------------------------------------------------


def _bucket(slices, lo: float, hi: float) -> dict:
    """Exclusive category times over [lo, hi): collective > transfer >
    compute on the merged union, host_gap = wall − device-busy. The four
    buckets sum to ``hi - lo`` exactly."""
    by_cat = {c: [] for c in ("collective", "transfer", "compute")}
    for ev in slices:
        by_cat[ev.category].append((ev.start_s, ev.end_s))
    coll = _merged(_clip(by_cat["collective"], lo, hi))
    tran = _merged(_clip(by_cat["transfer"], lo, hi))
    comp = _merged(_clip(by_cat["compute"], lo, hi))
    collective_s = _total(coll)
    transfer_s = _total(_subtract(tran, coll))
    comm = _merged(list(coll) + list(tran))
    compute_s = _total(_subtract(comp, comm))
    busy_s = collective_s + transfer_s + compute_s
    wall_s = hi - lo
    return {
        "wall_s": wall_s,
        "compute_s": compute_s,
        "collective_s": collective_s,
        "transfer_s": transfer_s,
        "host_gap_s": max(0.0, wall_s - busy_s),
        "device_busy_s": busy_s,
    }


def attribute_steps(slices, windows) -> "list[dict]":
    """Per-step attribution: device slices joined (clipped) to each
    annotation window."""
    steps = []
    for lo, hi, num in windows:
        rec = {"step": num, "start_s": lo, "end_s": hi}
        rec.update(_bucket(slices, lo, hi))
        steps.append(rec)
    return steps


def measured_overlap(slices) -> dict:
    """Per-collective-slice overlap with concurrent compute on OTHER
    device timelines: the runtime analogue of the static
    ``compute_between`` count. Returns totals, the overall ratio, a
    per-op-stem breakdown, and a verdict ("no-collectives" /
    "overlapped" / "exposed", threshold 0.5)."""
    comp_by_thread: dict = {}
    for ev in slices:
        if ev.category == "compute":
            comp_by_thread.setdefault((ev.pid, ev.tid), []).append(
                (ev.start_s, ev.end_s)
            )
    comp_by_thread = {k: _merged(v) for k, v in comp_by_thread.items()}
    total = overlapped = 0.0
    by_op: dict = {}
    for ev in slices:
        if ev.category != "collective":
            continue
        other = _merged([
            iv
            for key, merged in comp_by_thread.items()
            if key != (ev.pid, ev.tid)
            for iv in merged
        ])
        got = _total(_intersect([(ev.start_s, ev.end_s)], other))
        total += ev.duration_s
        overlapped += got
        stem = _TRAILING_ID.sub("", ev.name)
        rec = by_op.setdefault(stem, {"n": 0, "total_s": 0.0,
                                      "overlapped_s": 0.0})
        rec["n"] += 1
        rec["total_s"] += ev.duration_s
        rec["overlapped_s"] += got
    ratio = overlapped / total if total > 0 else None
    if total == 0:
        verdict = "no-collectives"
    else:
        # Epsilon absorbs the us->s float conversion so an exactly-half
        # overlapped trace doesn't flap between verdicts.
        verdict = (
            "overlapped" if ratio >= OVERLAPPED_MIN - 1e-9 else "exposed"
        )
    return {
        "total_s": total,
        "overlapped_s": overlapped,
        "overlap_ratio": ratio,
        "by_op": by_op,
        "verdict": verdict,
    }


def analyze_events(events, step_name: str) -> dict:
    """Full attribution summary over raw ``traceEvents``. Works with zero
    step annotations (``n_steps == 0``; the whole-range bucket still
    answers "where did device time go")."""
    slices = device_slices(events)
    windows = step_windows(events, step_name)
    steps = attribute_steps(slices, windows)
    keys = ("wall_s", "compute_s", "collective_s", "transfer_s",
            "host_gap_s", "device_busy_s")
    totals = {k: sum(s[k] for s in steps) for k in keys}
    mean = (
        {k: totals[k] / len(steps) for k in keys} if steps else None
    )
    if slices:
        lo = min(ev.start_s for ev in slices)
        hi = max(ev.end_s for ev in slices)
        rng = _bucket(slices, lo, hi)
        rng["span_s"] = rng.pop("wall_s")
    else:
        rng = {"span_s": 0.0, "compute_s": 0.0, "collective_s": 0.0,
               "transfer_s": 0.0, "host_gap_s": 0.0, "device_busy_s": 0.0}
    return {
        "step_name": step_name,
        "n_steps": len(steps),
        "n_device_slices": len(slices),
        "steps": steps,
        "totals": totals,
        "per_step_mean": mean,
        "range": rng,
        "collective": measured_overlap(slices),
    }


def analyze_trace_dir(trace_dir: str, step_name: str = "mpi4dl_capture") -> dict:
    """Read + attribute one capture directory. The default ``step_name``
    matches :func:`mpi4dl_tpu.profiling.capture`; pass
    ``"mpi4dl_train_step"`` / ``"mpi4dl_serve_batch"`` to attribute the
    annotations the train/serve dispatch paths emit on their own."""
    summary = analyze_events(read_trace_events(trace_dir), step_name)
    summary["trace_dir"] = trace_dir
    return summary


# -- pipeline lens -------------------------------------------------------------
#
# Per-stage attribution + measured bubble fraction for the scan-over-ticks
# pipeline engine (mpi4dl_tpu/parallel/pipeline.py). The engine compiles
# each tick's stage dispatch to ONE `conditional` with S+1 branch
# computations — branches 0..S-1 are the per-pipe-device stage bodies,
# branch S is the idle branch a device takes on fill/drain ticks. Joining
# the compiled module's branch->instruction closure to the trace's op
# slices gives, per stage: its device seconds (time-weighted) and its
# executed slot count; the idle branch's count IS the bubble, measured on
# the real timeline. This is deliberately slot-counted rather than
# wall-clock-idle: on the CPU test mesh every virtual device multiplexes
# onto one shared XLAEigen pool, so per-device wall idle is unobservable
# (measured: summed busy exceeds n_devices x wall) while branch executions
# are exact. On a real TPU the same join works off the per-device
# timelines.

_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_CALLED_RE = re.compile(
    r"(?:to_apply|calls|branch_computations|body|condition)="
    r"(?:%?([\w.\-]+)|\{([^}]*)\})"
)


def _called_computations(instr) -> "list[str]":
    out: list[str] = []
    for m in _CALLED_RE.finditer(instr.attrs):
        if m.group(1):
            out.append(m.group(1))
        else:
            out.extend(p.strip().lstrip("%") for p in m.group(2).split(","))
    return out


def _closure_names(module, comp_name: str) -> "set[str]":
    """All instruction names reachable from ``comp_name`` through
    to_apply/calls/branch/body/condition references (transitive)."""
    seen: set[str] = set()
    names: set[str] = set()
    todo = [comp_name]
    while todo:
        c = todo.pop()
        if c in seen:
            continue
        seen.add(c)
        comp = module.computations.get(c)
        if comp is None:
            continue
        for instr in comp.instructions:
            names.add(instr.name)
            todo.extend(_called_computations(instr))
    return names


def stage_switches(hlo_text_or_module, n_stages: int) -> "list[dict]":
    """The pipeline stage switches of a compiled module: ``conditional``
    instructions with exactly ``n_stages + 1`` branch computations. For
    each, the per-branch instruction-name closure with names shared
    between branches of the same conditional dropped — a slice on a
    shared name cannot be attributed to one stage. Branch order is stage
    order (the engine builds the switch as ``[stage_0..stage_{S-1},
    idle]``; the AD transpose and remat replays keep it)."""
    from mpi4dl_tpu.analysis.hlo import parse_hlo_text

    module = (
        hlo_text_or_module
        if hasattr(hlo_text_or_module, "computations")
        else parse_hlo_text(hlo_text_or_module)
    )
    out = []
    for comp in module.computations.values():
        for instr in comp.instructions:
            if instr.opcode != "conditional":
                continue
            m = _BRANCHES_RE.search(instr.attrs)
            if not m:
                continue
            branches = [b.strip().lstrip("%") for b in m.group(1).split(",")]
            if len(branches) != n_stages + 1:
                continue
            closures = [_closure_names(module, b) for b in branches]
            unique = []
            for i, cl in enumerate(closures):
                others: set = set()
                for j, other in enumerate(closures):
                    if j != i:
                        others |= other
                unique.append(cl - others)
            out.append({
                "name": instr.name,
                "branches": branches,
                "unique_names": unique,  # [stage_0..stage_{S-1}, idle]
            })
    return out


def pipeline_attribution(
    events,
    hlo_text_or_module,
    n_stages: int,
    step_name: str = "mpi4dl_capture",
    analytic_bubble: "float | None" = None,
    schedule: "str | None" = None,
) -> dict:
    """Join a pipeline capture to its compiled program's stage switches:
    per-stage device seconds + executed slot counts, the idle branch's
    slot count, and the fleet ``bubble_fraction`` =
    ``idle_slots / (idle_slots + active_slots)`` — for the gated GPipe
    schedule this measures ``(S-1)/(S-1+M)`` on a live run, the number the
    ROADMAP said nothing measured. Raises :class:`TraceError` when the
    module has no ``n_stages + 1``-branch conditional (not a pipeline
    program, or the wrong stage count)."""
    switches = stage_switches(hlo_text_or_module, n_stages)
    if not switches:
        raise TraceError(
            f"compiled module has no conditional with {n_stages + 1} "
            "branches — not a PipelineTrainer program, or n_stages does "
            "not match its pipe depth"
        )
    slices = device_slices(events)
    windows = step_windows(events, step_name)
    if windows:
        lo = min(w[0] for w in windows)
        hi = max(w[1] for w in windows)
    elif slices:
        lo = min(ev.start_s for ev in slices)
        hi = max(ev.end_s for ev in slices)
    else:
        lo = hi = 0.0
    counts: dict = {}
    durs: dict = {}
    permute_s = 0.0
    for ev in slices:
        mid = (ev.start_s + ev.end_s) / 2
        if not (lo <= mid < hi):
            continue
        counts[ev.name] = counts.get(ev.name, 0) + 1
        durs[ev.name] = durs.get(ev.name, 0.0) + ev.duration_s
        if ev.category == "collective" and "collective-permute" in ev.name:
            permute_s += ev.duration_s

    def branch_count(unique_names) -> int:
        # Every instruction unique to the branch executes exactly once per
        # taken branch; the max absorbs instructions the runtime did not
        # emit slices for (elided/zero-duration thunks undercount).
        return max((counts.get(n, 0) for n in unique_names), default=0)

    def branch_seconds(unique_names) -> float:
        return sum(durs.get(n, 0.0) for n in unique_names)

    per_switch = []
    active_by_stage = [0] * n_stages
    seconds_by_stage = [0.0] * n_stages
    idle_slots = 0
    for sw in switches:
        active = [branch_count(u) for u in sw["unique_names"][:n_stages]]
        idle = branch_count(sw["unique_names"][n_stages])
        for s in range(n_stages):
            active_by_stage[s] += active[s]
            seconds_by_stage[s] += branch_seconds(sw["unique_names"][s])
        idle_slots += idle
        per_switch.append({
            "conditional": sw["name"],
            "active_slots": active,
            "idle_slots": idle,
        })
    active_slots = sum(active_by_stage)
    total_slots = active_slots + idle_slots
    bubble = idle_slots / total_slots if total_slots else None
    # Per-device idle share: each switch runs total/S/n_switches ticks per
    # device (replication-invariant), so device s idled 1 - active_s*S/total
    # of its slots.
    idle_share = [
        (1.0 - active_by_stage[s] * n_stages / total_slots)
        if total_slots else None
        for s in range(n_stages)
    ]
    out = {
        "n_stages": n_stages,
        "schedule": schedule,
        "n_steps": len(windows),
        "n_switches": len(switches),
        "per_switch": per_switch,
        "active_slots_by_stage": active_by_stage,
        "idle_slots": idle_slots,
        "total_slots": total_slots,
        "bubble_fraction": bubble,
        "idle_share_by_stage": idle_share,
        "stage_device_seconds": seconds_by_stage,
        "permute_seconds": permute_s,
    }
    if analytic_bubble is not None:
        out["analytic_bubble_fraction"] = float(analytic_bubble)
    return out


def analyze_pipeline_trace_dir(
    trace_dir: str,
    hlo_text: str,
    n_stages: int,
    step_name: str = "mpi4dl_capture",
    analytic_bubble: "float | None" = None,
    schedule: "str | None" = None,
) -> dict:
    """Read one capture directory and attribute it through the pipeline
    lens (:func:`pipeline_attribution`)."""
    return pipeline_attribution(
        read_trace_events(trace_dir), hlo_text, n_stages,
        step_name=step_name, analytic_bubble=analytic_bubble,
        schedule=schedule,
    )


#: |measured - analytic| beyond ``max(abs, rel * analytic)`` disagrees.
BUBBLE_TOL_ABS = 0.02
BUBBLE_TOL_REL = 0.15


def crosscheck_bubble(
    analytic: float,
    summary: dict,
    tol_abs: float = BUBBLE_TOL_ABS,
    tol_rel: float = BUBBLE_TOL_REL,
) -> "list[Finding]":
    """The schedule model says the bubble is ``(S-1)/(S-1+M)``; the trace
    says what fraction of slots the devices actually idled. Disagreement
    on the same executable is a lint finding (rule
    ``pipeline-bubble-crosscheck``) — the PR-4 static-vs-measured pattern,
    now for pipeline bubbles. ``summary`` is a
    :func:`pipeline_attribution` result."""
    measured = summary.get("bubble_fraction")
    rule = "pipeline-bubble-crosscheck"
    if measured is None:
        return [Finding(rule, "warn",
                        "the capture recorded no stage-switch slots at all "
                        "— wrong program, empty trace, or the idle branch "
                        "was folded away (the bubble is unmeasurable).")]
    if abs(measured - analytic) <= max(tol_abs, tol_rel * analytic):
        return []
    direction = "above" if measured > analytic else "below"
    return [Finding(rule, "warn",
                    f"measured pipeline bubble {measured:.4f} is {direction} "
                    f"the schedule-model {analytic:.4f} beyond tolerance: "
                    "the compiled schedule does not execute the idle "
                    "structure the model predicts (gating regressed, wrong "
                    "parts/stages, or the capture mixed programs).")]


def publish_pipeline_attribution(summary: dict, registry, program: str):
    """Publish one pipeline-lens summary under the cataloged
    ``pipeline_*`` gauges (docs/OBSERVABILITY.md), labeled by ``program``
    so schedule arms coexist in one registry."""
    from mpi4dl_tpu import telemetry

    if summary.get("bubble_fraction") is not None:
        telemetry.declare(registry, "pipeline_bubble_fraction").set(
            summary["bubble_fraction"], program=program
        )
    for s, secs in enumerate(summary.get("stage_device_seconds") or []):
        telemetry.declare(registry, "pipeline_stage_device_seconds").set(
            secs, program=program, stage=str(s)
        )
    if summary.get("img_per_s") is not None:
        telemetry.declare(registry, "pipeline_img_per_s").set(
            summary["img_per_s"], program=program
        )
    return registry


# -- telemetry + static cross-check -------------------------------------------


def publish_attribution(summary: dict, registry, program: str = "capture"):
    """Publish one attribution summary under the cataloged ``trace_*``
    gauges (docs/OBSERVABILITY.md), labeled by ``program`` so train and
    serve captures coexist in one registry. Per-step means when the
    capture had annotated steps, whole-range totals otherwise."""
    from mpi4dl_tpu import telemetry

    src = summary["per_step_mean"] or summary["range"]
    attr = telemetry.declare(registry, "trace_attribution_seconds")
    for cat in CATEGORIES:
        attr.set(src.get(f"{cat}_s", 0.0), program=program, category=cat)
    if summary["per_step_mean"] is not None:
        telemetry.declare(registry, "trace_step_wall_seconds").set(
            summary["per_step_mean"]["wall_s"], program=program
        )
    ratio = summary["collective"]["overlap_ratio"]
    if ratio is not None:
        telemetry.declare(registry, "trace_overlap_ratio").set(
            ratio, program=program
        )
    return registry


def static_overlap_verdict(overlap: dict) -> str:
    """Collapse a static ``Report.overlap`` summary into one verdict:
    "no-collectives", "sync" (collectives but no async start/done pairs —
    the schedule makes no overlap claim), "exposed" (async pairs with
    zero compute between), or "overlapped"."""
    if overlap.get("n_collectives", 0) == 0:
        return "no-collectives"
    if overlap.get("async_pairs", 0) == 0:
        return "sync"
    return "exposed" if overlap.get("zero_overlap") else "overlapped"


def crosscheck_overlap(report, summary: dict) -> "list[Finding]":
    """Static says "should overlap"; the trace says "did". Disagreement
    between the two verdicts on the same executable is a lint finding
    (rule ``trace-overlap-crosscheck``) — the closed loop between
    hlolint's schedule prediction and runtime reality. ``report`` is a
    :class:`mpi4dl_tpu.analysis.report.Report` or any dict carrying its
    ``overlap`` summary."""
    overlap = report["overlap"] if isinstance(report, dict) else report.overlap
    static = static_overlap_verdict(overlap)
    meas = summary["collective"]
    measured = meas["verdict"]
    rule = "trace-overlap-crosscheck"
    if static == "no-collectives" and measured != "no-collectives":
        return [Finding(rule, "warn",
                        f"static analysis saw zero collectives but the trace "
                        f"recorded {meas['total_s'] * 1e3:.3f} ms of "
                        "collective slices: the captured program is not the "
                        "analyzed one, or communication crept in at runtime.")]
    if static != "no-collectives" and measured == "no-collectives":
        return [Finding(rule, "warn",
                        f"static analysis counts "
                        f"{overlap.get('n_collectives')} collectives but the "
                        "trace recorded none: capture too short, wrong "
                        "program, or the runtime elided them.")]
    if static == "overlapped" and measured == "exposed":
        return [Finding(rule, "warn",
                        "static schedule places compute inside every "
                        "collective start->done window, but the measured "
                        f"overlap ratio is {meas['overlap_ratio']:.2f}: the "
                        "communication window is exposed latency at runtime "
                        "(T3/FLUX lost-overlap, invisible to the static "
                        "rule).")]
    if static == "exposed" and measured == "overlapped":
        return [Finding(rule, "info",
                        "static analysis flags zero-overlap collectives but "
                        "the runtime overlapped "
                        f"{meas['overlap_ratio']:.0%} of collective time "
                        "anyway (asynchronous progress outside the schedule).")]
    return []
