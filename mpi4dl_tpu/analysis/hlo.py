"""Text parser for compiled HLO modules.

``compiled.as_text()`` (post-optimization, post-scheduling HLO) is the one
artifact every backend of this runtime can produce — including the
tunneled remote-compile helper, which can't hand back a stable protobuf
across versions. The grammar actually needed for analysis is small and
stable: one instruction per line, ``%name = shape opcode(operands), attrs``,
computations delimited by ``{``/``}``, with the entry computation marked
``ENTRY``. Within a scheduled module (``is_scheduled=true`` in the header)
the listed instruction order IS the schedule, which is what makes
start→done distance a real overlap measurement rather than a guess.

Parsing is deliberately tolerant: unknown attributes are kept raw, unknown
dtypes get itemsize 0 (they count as 0 bytes instead of crashing the lint),
and malformed lines are skipped — a lint must degrade to "less information",
never to a parse crash on a new compiler version's output.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable

# Bytes per element for HLO primitive types. Unlisted types (token, opaque,
# tuple placeholders) contribute 0 bytes.
DTYPE_BYTES = {
    "pred": 1,
    "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
    "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e5m2": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e8m0fnu": 1,
}

_SHAPE_RE = re.compile(r"^([a-zA-Z0-9]+)\[([0-9,]*)\](\{[^}]*\})?")
_INSTR_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
# ``%computation (params) -> shape {``  /  ``ENTRY %main.1 ... {``
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(")


@dataclasses.dataclass(frozen=True)
class HloShape:
    """A (possibly tuple) HLO shape. ``dims`` is empty for scalars."""

    dtype: str | None
    dims: tuple[int, ...] = ()
    elements: tuple["HloShape", ...] = ()

    @property
    def is_tuple(self) -> bool:
        return self.dtype is None

    def byte_size(self) -> int:
        if self.is_tuple:
            return sum(e.byte_size() for e in self.elements)
        n = 1
        for d in self.dims:
            n *= d
        return n * DTYPE_BYTES.get(self.dtype, 0)


@dataclasses.dataclass
class HloInstruction:
    name: str
    opcode: str
    shape: HloShape
    operands: tuple[str, ...]  # operand instruction names, %-stripped
    attrs: str  # raw trailing attribute text
    index: int  # position within its computation (schedule order)
    is_root: bool = False

    @property
    def channel_id(self) -> int | None:
        m = re.search(r"channel_id=(\d+)", self.attrs)
        return int(m.group(1)) if m else None


@dataclasses.dataclass
class HloComputation:
    name: str
    instructions: list[HloInstruction]
    is_entry: bool = False

    def __iter__(self):
        return iter(self.instructions)


@dataclasses.dataclass
class HloModule:
    name: str
    computations: dict[str, HloComputation]
    header: str = ""

    @property
    def is_scheduled(self) -> bool:
        return "is_scheduled=true" in self.header

    @property
    def entry(self) -> HloComputation | None:
        for c in self.computations.values():
            if c.is_entry:
                return c
        return None

    def all_instructions(self) -> Iterable[HloInstruction]:
        for comp in self.computations.values():
            yield from comp.instructions


def _match_paren(s: str, start: int) -> int:
    """Index just past the ``)`` closing the ``(`` at ``start``; respects
    nesting but not quotes (operand lists never contain quoted parens)."""
    depth = 0
    for i in range(start, len(s)):
        if s[i] == "(":
            depth += 1
        elif s[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(s)


def _split_top_commas(s: str) -> list[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


def parse_shape(s: str) -> tuple[HloShape | None, str]:
    """Parse one shape at the head of ``s``; returns (shape, rest)."""
    s = s.lstrip()
    if s.startswith("("):
        end = _match_paren(s, 0)
        inner = s[1 : end - 1]
        elems = []
        for part in _split_top_commas(inner):
            shp, _ = parse_shape(part)
            if shp is not None:
                elems.append(shp)
        return HloShape(None, (), tuple(elems)), s[end:]
    m = _SHAPE_RE.match(s)
    if not m:
        return None, s
    dtype = m.group(1)
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    return HloShape(dtype, dims), s[m.end():]


def _operand_names(operand_text: str) -> tuple[str, ...]:
    """Instruction names referenced in an operand list — each operand is
    ``[shape] %name`` (typed form) or just ``name``; constants/literals
    have no name and are skipped."""
    names = []
    for part in _split_top_commas(operand_text):
        m = re.search(r"%([\w.\-]+)\s*$", part)
        if m:
            names.append(m.group(1))
            continue
        # Untyped compact form: a bare identifier that isn't a literal.
        bare = part.strip()
        if re.fullmatch(r"[A-Za-z_][\w.\-]*", bare) and not _SHAPE_RE.match(bare):
            names.append(bare)
    return tuple(names)


def parse_instruction(line: str, index: int) -> HloInstruction | None:
    m = _INSTR_RE.match(line)
    if not m:
        return None
    is_root = bool(m.group(1))
    name = m.group(2)
    rhs = m.group(3)
    shape, rest = parse_shape(rhs)
    if shape is None:
        return None
    om = re.match(r"\s*([\w\-]+)\s*\(", rest)
    if not om:
        return None
    opcode = om.group(1)
    open_at = om.end() - 1
    close_at = _match_paren(rest, open_at)
    operand_text = rest[open_at + 1 : close_at - 1]
    attrs = rest[close_at:].lstrip(", ")
    # Operands of call-like ops (fusion/call/while) are still value names;
    # computation references live in attrs (to_apply=..., calls=...).
    return HloInstruction(
        name=name,
        opcode=opcode,
        shape=shape,
        operands=_operand_names(operand_text),
        attrs=attrs,
        index=index,
        is_root=is_root,
    )


def parse_hlo_text(text: str) -> HloModule:
    """Parse a full ``compiled.as_text()`` dump into an :class:`HloModule`."""
    lines = text.splitlines()
    header = ""
    name = ""
    for line in lines:
        if line.startswith("HloModule"):
            header = line
            parts = line.split(None, 2)
            name = parts[1].rstrip(",") if len(parts) > 1 else ""
            break

    computations: dict[str, HloComputation] = {}
    current: HloComputation | None = None
    for line in lines:
        stripped = line.strip()
        if not stripped or stripped.startswith("//") or stripped.startswith("HloModule"):
            continue
        if stripped.endswith("{") and "=" not in stripped.split("(")[0]:
            m = _COMP_RE.match(stripped)
            if m:
                current = HloComputation(
                    name=m.group(2), instructions=[], is_entry=bool(m.group(1))
                )
                computations[current.name] = current
                continue
        if stripped == "}" or stripped.startswith("}"):
            current = None
            continue
        if current is not None and "=" in stripped:
            instr = parse_instruction(stripped, len(current.instructions))
            if instr is not None:
                current.instructions.append(instr)
    return HloModule(name=name, computations=computations, header=header)
