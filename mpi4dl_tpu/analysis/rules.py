"""Rule engine: severities, findings, and the standing lint rules.

Rules consume a :class:`LintContext` (parsed module + inventory/records +
partition-math expectations + memory/remat metadata) and emit
:class:`Finding`\\ s at ``error`` / ``warn`` / ``info`` severity. The tier-1
lint gate fails on ``error``; ``warn`` is advisory (printed, recorded in the
JSON report, never fatal by default).

The point of deriving expectations from partition math (tile grid, counted
halo shifts) instead of hand-pinned op counts: an INTENTIONAL engine change
moves the derived bound with it, while a regression (doubled per-layer halo
traffic, a stray resharding) still lands outside. Hand pins remain useful
as exact-value regression tests — they live in
``tests/test_collective_inventory.py`` on top of these rules.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

from mpi4dl_tpu.analysis.hlo import HloModule
from mpi4dl_tpu.analysis.inventory import CollectiveRecord

SEVERITY_ORDER = {"info": 0, "warn": 1, "error": 2}


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str  # "info" | "warn" | "error"
    message: str
    location: str | None = None  # instruction or computation name

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Expectations:
    """Partition-math inputs for the structural rules. ``None`` disables
    the rule that needs the value (an analyzer run on a bare HLO snippet
    can still lint overlap without knowing the mesh)."""

    # Tile grid of the spatial stage, e.g. (2, 2); (1, 1) = no tiling.
    tile_shape: tuple[int, int] | None = None
    # Counted forward halo shift ppermutes (Trainer.halo_shift_count):
    # each is one collective-permute; the backward re-runs the transposed
    # shifts, partially deduped by XLA — hence the [n, 2n] window.
    halo_shifts: int | None = None
    # EXACT permutes legitimately present beyond halo traffic — the
    # pipeline engine's stage-boundary wire shifts
    # (PipelineTrainer.stage_permute_count(): fwd scan body + AD
    # transpose, 2*(n_virtual-1)). Unlike halo traffic these have no
    # dedupe slack, so the value shifts BOTH window bounds: a pure-LP
    # pipeline (halo_shifts=0) is gated at exactly this count.
    extra_permutes: int = 0
    # True when the program is expected to have NO spatial/model sharding
    # (pure DP): any permute/gather/scatter then means resharding crept in.
    pure_dp: bool = False
    # True for a program that must run entirely on one chip (the serving
    # forward): ANY collective — all-reduce included — is then XLA
    # resharding/replicating something that regressed off the single
    # device, turning every request into cross-chip traffic.
    single_chip: bool = False
    # EXACT all-gather entitlement (the SP→LP tile join into the
    # replicated head: fwd gather + backward re-gather on a train step).
    # None disables the rule — only composed stacks that CLAIM the join
    # (analysis.expectations.spatial_join_delta) are gated on it.
    join_gathers: int | None = None


@dataclasses.dataclass
class LintContext:
    module: HloModule
    inventory: dict
    records: Sequence[CollectiveRecord]
    expected: Expectations = dataclasses.field(default_factory=Expectations)
    # memory_summary() output (+ "baseline_bytes"/"tolerance" when a
    # committed baseline exists for this config).
    memory: dict | None = None
    # {"policy": str, "store_budget_mb": float, "granted_bytes": int,
    #  "grants": {run_key: bytes}} — remat/store-budget effectiveness.
    remat: dict | None = None
    platform: str = ""
    # Collectives smaller than this are noise for overlap purposes.
    overlap_min_bytes: int = 1 << 20


@dataclasses.dataclass
class Rule:
    id: str
    doc: str
    check: Callable[[LintContext], "list[Finding]"]


def _rule_stray_all_to_all(ctx: LintContext) -> list[Finding]:
    out = []
    for op in ("all-to-all", "ragged-all-to-all"):
        n = ctx.inventory.get(op, 0)
        if n:
            out.append(Finding(
                "stray-all-to-all", "error",
                f"{n} {op} op(s) in the compiled step: nothing in the "
                "SP/DP/LP engine legitimately emits all-to-all — this is "
                "XLA resharding an activation or gradient whose sharding "
                "regressed (check in_specs/out_specs and param specs).",
            ))
    return out


def _rule_stray_resharding(ctx: LintContext) -> list[Finding]:
    if not ctx.expected.pure_dp:
        return []
    out = []
    for op in ("collective-permute", "all-gather", "reduce-scatter"):
        n = ctx.inventory.get(op, 0)
        if n:
            out.append(Finding(
                "stray-resharding", "error",
                f"pure-DP program contains {n} {op} op(s): gradient/metric "
                "all-reduces are the only expected collectives — input or "
                "parameter sharding regressed.",
            ))
    return out


def _rule_single_chip_collectives(ctx: LintContext) -> list[Finding]:
    if not ctx.expected.single_chip:
        return []
    present = {op: n for op, n in ctx.inventory.items() if n}
    if not present:
        return []
    ops = ", ".join(f"{n} {op}" for op, n in sorted(present.items()))
    return [Finding(
        "single-chip-collectives", "error",
        f"single-chip program contains collectives ({ops}): the serving "
        "forward must compile to a one-device executable — a collective "
        "here means an input/param landed sharded or a mesh leaked into "
        "the eval path, and every request would pay cross-chip latency.",
    )]


def _rule_halo_permute_count(ctx: LintContext) -> list[Finding]:
    exp = ctx.expected
    if exp.halo_shifts is None:
        return []
    actual = ctx.inventory.get("collective-permute", 0)
    lo = exp.halo_shifts + exp.extra_permutes
    hi = 2 * exp.halo_shifts + exp.extra_permutes
    if lo <= actual <= hi:
        return []
    if actual < lo:
        msg = (
            f"{actual} collective-permutes but partition math derives "
            f">= {lo} (= {exp.halo_shifts} forward halo shifts"
            + (f" + a pipeline permute budget of {exp.extra_permutes} "
               "stage-boundary shifts" if exp.extra_permutes else "")
            + "): exchanges were elided or moved off the permute path "
            "(Pallas DMA halo? wrong mesh? a dropped pipeline wire?)."
        )
    else:
        msg = (
            f"{actual} collective-permutes exceed the derived ceiling {hi} "
            f"(= 2 x {exp.halo_shifts} fwd shifts"
            + (f" + a pipeline permute budget of {exp.extra_permutes}" if
               exp.extra_permutes else "")
            + "): per-layer halo traffic multiplied (lost XLA fwd/bwd "
            "dedupe, doubled exchanges, or resharding riding the "
            "permute class)."
        )
    return [Finding("halo-permute-count", "error", msg)]


def _rule_join_gather_count(ctx: LintContext) -> list[Finding]:
    exp = ctx.expected
    if exp.join_gathers is None:
        return []
    actual = ctx.inventory.get("all-gather", 0)
    if actual == exp.join_gathers:
        return []
    if actual < exp.join_gathers:
        msg = (
            f"{actual} all-gather op(s) but the composed stack claims "
            f"exactly {exp.join_gathers} SP→LP join gathers: the tile join "
            "was elided or moved off the gather path (head no longer "
            "replicated? join fused into a reshard?)."
        )
    else:
        msg = (
            f"{actual} all-gather op(s) exceed the composed join budget of "
            f"{exp.join_gathers}: gathers beyond the tile join mean an "
            "activation or gradient is being re-replicated mid-program "
            "(sharding regressed between layers)."
        )
    return [Finding("join-gather-count", "error", msg)]


def _rule_zero_overlap(ctx: LintContext) -> list[Finding]:
    out = []
    for r in ctx.records:
        if not r.is_async or r.distance is None:
            continue
        if r.compute_between == 0:
            big = r.bytes_moved >= ctx.overlap_min_bytes
            out.append(Finding(
                "zero-overlap-collective",
                "error" if big else "warn",
                f"{r.opcode} {r.name} ({r.bytes_moved} B) completes with "
                "no compute scheduled between -start and -done "
                f"(distance {r.distance}): the communication window is "
                "pure exposed latency (T3/FLUX lost-overlap signature).",
                location=f"{r.computation}::{r.name}",
            ))
    return out


def _rule_peak_memory(ctx: LintContext) -> list[Finding]:
    mem = ctx.memory
    if not mem or mem.get("peak_bytes") is None:
        return []
    baseline = mem.get("baseline_bytes")
    if baseline is None:
        return [Finding(
            "peak-memory-regression", "info",
            f"peak memory {mem['peak_bytes']} B; no committed baseline for "
            "this config — run the CLI with --write-baseline to pin it.",
        )]
    tol = float(mem.get("tolerance", 0.05))
    peak = mem["peak_bytes"]
    if peak > baseline * (1 + tol):
        return [Finding(
            "peak-memory-regression", "error",
            f"peak memory {peak} B exceeds committed baseline {baseline} B "
            f"by more than {tol:.0%}: a remat/layout change grew the live "
            "set — re-derive the baseline only if the growth is intentional.",
        )]
    if peak < baseline * (1 - tol):
        return [Finding(
            "peak-memory-regression", "info",
            f"peak memory {peak} B is >{tol:.0%} BELOW the committed "
            f"baseline {baseline} B — refresh the baseline to lock in "
            "the improvement.",
        )]
    return []


def _rule_remat_effectiveness(ctx: LintContext) -> list[Finding]:
    rem = ctx.remat
    if not rem:
        return []
    budget_mb = float(rem.get("store_budget_mb") or 0)
    if budget_mb <= 0:
        return []
    granted = int(rem.get("granted_bytes") or 0)
    budget_bytes = budget_mb * 1e6
    out = []
    if granted == 0:
        out.append(Finding(
            "remat-effectiveness", "warn",
            f"store budget {budget_mb:g} MB granted nothing under policy "
            f"{rem.get('policy')!r}: every run's carry/save set exceeds the "
            "budget, so the setting only costs planning time — raise it or "
            "drop it.",
        ))
    elif granted > budget_bytes:
        out.append(Finding(
            "remat-effectiveness", "error",
            f"granted stores ({granted} B) exceed the configured budget "
            f"({int(budget_bytes)} B): the grant accounting is broken — "
            "live ranges will blow past the planned peak.",
        ))
    peak = (ctx.memory or {}).get("peak_bytes")
    if granted and peak and granted > 0.5 * peak:
        out.append(Finding(
            "remat-effectiveness", "warn",
            f"granted stores ({granted} B) are >50% of peak memory "
            f"({peak} B): grants dominate the live set, so early-run "
            "grants stay live through the whole backward (ADVICE r5 "
            "front-to-back liveness hazard) — prefer granting late runs.",
        ))
    return out


DEFAULT_RULES: tuple[Rule, ...] = (
    Rule("stray-all-to-all",
         "any all-to-all is a resharding bug", _rule_stray_all_to_all),
    Rule("stray-resharding",
         "pure-DP programs may only all-reduce", _rule_stray_resharding),
    Rule("single-chip-collectives",
         "single-chip (serving) programs may not communicate at all",
         _rule_single_chip_collectives),
    Rule("halo-permute-count",
         "collective-permute count must sit in the partition-math window",
         _rule_halo_permute_count),
    Rule("join-gather-count",
         "all-gather count must equal the composed SP→LP join claim",
         _rule_join_gather_count),
    Rule("zero-overlap-collective",
         "async collectives must overlap compute", _rule_zero_overlap),
    Rule("peak-memory-regression",
         "peak memory vs committed baseline", _rule_peak_memory),
    Rule("remat-effectiveness",
         "store-budget grants vs live ranges", _rule_remat_effectiveness),
)


def run_rules(ctx: LintContext, rules: Sequence[Rule] = DEFAULT_RULES) -> list[Finding]:
    findings: list[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: -SEVERITY_ORDER.get(f.severity, 0))
    return findings


def max_severity(findings) -> str | None:
    best = None
    for f in findings:
        if best is None or SEVERITY_ORDER[f.severity] > SEVERITY_ORDER[best]:
            best = f.severity
    return best
