"""Sharded-serving halo/compute-overlap A/B harness.

``python -m mpi4dl_tpu.analyze serving-sharded`` runs a spatially-sharded
:class:`~mpi4dl_tpu.serve.ServingEngine` (serve/sharded.py) TWICE — once
with the monolithic spatial conv and once with the PR-9 decomposed impl
(``overlap_decompose``: interior conv with no halo dependency + boundary
strips) — and measures, per arm, ON THE SERVING HOT PATH:

- the **measured** ``trace_overlap_ratio`` of a live XProf capture over a
  closed-loop load run (the engine's own ``mpi4dl_serve_batch`` step
  annotations): the fraction of collective-permute time hidden behind
  concurrent compute — the number the decomposition exists to raise
  (T3 arXiv:2401.16677 / FLUX arXiv:2406.06858), now with per-request
  latency attached instead of train-step wall time;
- per-request latency (p50/p99) and throughput of the same load run;
- the **static** hlolint verdict with the MESH-DERIVED expectations
  (tile grid + counted halo shifts — the engine's own ``lint_report``);
- the ``trace-overlap-crosscheck`` findings joining static and measured;
- the PR-9 **bit-identity crosscheck**: both arms' logits for one probe
  example must be byte-equal (the decomposition changes the schedule,
  never the numbers).

Trials interleave across arms (mono, dec, mono, dec, ...) so slow host
drift hits both alike, and each arm's ratio pools overlapped/total
collective time over its captures. Run from bench.py as the
``serving_sharded`` extra subprocess (the 4-device CPU mesh must exist
regardless of the bench headline's backend); the CPU mesh proves
scheduling freedom, not wall-clock — the flag is the TPU lever.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile


def _build_arm_engine(impl, size, depth, spatial_cells, mesh, bucket):
    from mpi4dl_tpu.serve.sharded import synthetic_sharded_engine

    return synthetic_sharded_engine(
        mesh, image_size=size, depth=depth, spatial_cells=spatial_cells,
        conv_overlap=impl, buckets=(bucket,), max_queue=512,
        default_deadline_s=60.0, watchdog_factor=None,
        memory_monitor=False, tail_capacity=0,
    )


def run_serving_sharded_ab(
    size: int = 32,
    depth: int = 8,
    spatial_cells: int = 3,
    mesh=(2, 2),
    bucket: int = 4,
    requests: int = 48,
    concurrency: int = 8,
    trials: int = 1,
    arms=("monolithic", "decomposed"),
    registry=None,
) -> dict:
    """Both serving arms + the A/B verdict; see the module docstring.
    Requires enough devices for the tile mesh; raises the underlying
    config error otherwise."""
    import numpy as np

    from mpi4dl_tpu import profiling
    from mpi4dl_tpu.analysis.trace import (
        analyze_trace_dir,
        crosscheck_overlap,
        publish_attribution,
    )
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    th, tw = (int(d) for d in mesh)
    out = {
        "config": {
            "size": size, "depth": depth, "spatial_cells": spatial_cells,
            "mesh": f"{th}x{tw}", "bucket": bucket, "requests": requests,
            "concurrency": concurrency, "trials": trials,
        },
        "arms": {},
    }
    engines = {
        impl: _build_arm_engine(impl, size, depth, spatial_cells,
                                (th, tw), bucket)
        for impl in arms
    }
    try:
        # PR-9 bit-identity crosscheck on the serving forward: the two
        # arms compile DIFFERENT schedules of the SAME function.
        probe = np.asarray(
            np.random.default_rng(7).standard_normal((size, size, 3)),
            np.float32,
        )
        probe_logits = {
            impl: eng.predict_one(probe) for impl, eng in engines.items()
        }
        vals = list(probe_logits.values())
        bit_identical = all(
            np.array_equal(vals[0], v) for v in vals[1:]
        )

        pooled = {
            impl: {
                "total_s": 0.0, "overlapped_s": 0.0, "per_trial": [],
                "lat_p50": [], "lat_p99": [], "rps": [],
                "deadline_misses": 0, "n_steps": 0, "crosscheck": None,
                "report": engines[impl].lint_report(bucket=bucket),
            }
            for impl in arms
        }
        for impl in arms:
            engines[impl].start()
        for _ in range(max(1, int(trials))):
            for impl in arms:
                eng, acc = engines[impl], pooled[impl]
                logdir = tempfile.mkdtemp(
                    prefix=f"mpi4dl-serving-sharded-{impl}-"
                )
                try:
                    with profiling.trace(logdir):
                        rep = run_closed_loop(
                            eng, requests, concurrency=concurrency,
                            deadline_s=60.0,
                        )
                    summary = analyze_trace_dir(
                        logdir, step_name="mpi4dl_serve_batch"
                    )
                finally:
                    shutil.rmtree(logdir, ignore_errors=True)
                if registry is not None:
                    publish_attribution(
                        summary, registry,
                        program=f"serving_sharded_{impl}",
                    )
                coll = summary["collective"]
                acc["total_s"] += coll["total_s"]
                acc["overlapped_s"] += coll["overlapped_s"]
                acc["per_trial"].append(coll["overlap_ratio"])
                acc["n_steps"] += summary["n_steps"]
                acc["lat_p50"].append(rep["latency_s"]["p50"])
                acc["lat_p99"].append(rep["latency_s"]["p99"])
                acc["rps"].append(rep["throughput_rps"])
                acc["deadline_misses"] += rep["deadline_misses"]
                if acc["crosscheck"] is None:
                    acc["crosscheck"] = [
                        f.as_dict()
                        for f in crosscheck_overlap(acc["report"], summary)
                    ]
    finally:
        for eng in engines.values():
            try:
                eng.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def _mean(xs):
        return sum(xs) / len(xs) if xs else None

    for impl in arms:
        acc, eng = pooled[impl], engines[impl]
        report = acc["report"]
        total = acc["total_s"]
        out["arms"][impl] = {
            "conv_impl": impl,
            "trace_overlap_ratio": (
                acc["overlapped_s"] / total if total > 0 else None
            ),
            "overlap_ratio_per_trial": acc["per_trial"],
            "latency_ms": {
                "p50": round(_mean(acc["lat_p50"]) * 1e3, 3),
                "p99": round(_mean(acc["lat_p99"]) * 1e3, 3),
            },
            "throughput_rps": round(_mean(acc["rps"]), 2),
            "deadline_misses": acc["deadline_misses"],
            "n_steps": acc["n_steps"],
            "halo_shifts": eng._predictor.halo_shifts(),
            "permutes": report.inventory.get("collective-permute", 0),
            "hlolint_errors": [
                f for f in report.findings if f["severity"] == "error"
            ],
            "crosscheck": acc["crosscheck"] or [],
        }
    out["bit_identical_arms"] = bool(bit_identical)
    mono = out["arms"].get("monolithic")
    dec = out["arms"].get("decomposed")
    if mono and dec:
        out["halo_shifts_equal"] = (
            mono["halo_shifts"] == dec["halo_shifts"]
        )
        rm, rd = mono["trace_overlap_ratio"], dec["trace_overlap_ratio"]
        out["overlap_improved"] = (
            rm is not None and rd is not None and rd > rm
        )
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze serving-sharded",
        description="Sharded-serving halo/compute overlap A/B: monolithic "
                    "vs decomposed spatial conv on the serving hot path, "
                    "measured + mesh-lint gated",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--spatial-cells", type=int, default=3)
    p.add_argument("--mesh", default="2x2",
                   help="serving tile mesh HxW (square, 1xW, or Hx1)")
    p.add_argument("--bucket", type=int, default=4,
                   help="the single batch bucket both arms warm")
    p.add_argument("--requests", type=int, default=48,
                   help="closed-loop requests per capture")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--trials", type=int, default=1,
                   help="captures per arm, interleaved across arms")
    p.add_argument("--arm", action="append", dest="arms", default=None,
                   choices=("monolithic", "decomposed"),
                   help="restrict to one arm (repeatable); default both")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the A/B record here ('-' = stdout)")
    p.add_argument("--require-improvement", action="store_true",
                   help="exit 1 unless the decomposed arm's measured "
                        "overlap ratio strictly beats the monolithic one")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.serve.sharded import parse_mesh
    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()
    mesh = parse_mesh(args.mesh)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # The tile mesh needs virtual devices before backend init — the
        # same 8-device simulation the test suite runs on.
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(max(8, mesh[0] * mesh[1]))
    enable_compilation_cache()
    # Each arm pins its own impl at compile; an inherited process-wide
    # override would collapse the A/B into one arm measured twice.
    os.environ.pop("MPI4DL_TPU_CONV_OVERLAP", None)

    out = run_serving_sharded_ab(
        size=args.size, depth=args.depth,
        spatial_cells=args.spatial_cells, mesh=mesh, bucket=args.bucket,
        requests=args.requests, concurrency=args.concurrency,
        trials=args.trials,
        arms=tuple(args.arms) if args.arms else ("monolithic", "decomposed"),
    )
    for impl, arm in out["arms"].items():
        ratio = arm["trace_overlap_ratio"]
        print(
            f"# {impl}: overlap_ratio="
            f"{ratio if ratio is None else round(ratio, 4)} "
            f"p99={arm['latency_ms']['p99']}ms "
            f"rps={arm['throughput_rps']} permutes={arm['permutes']} "
            f"halo_shifts={arm['halo_shifts']} "
            f"lint_errors={len(arm['hlolint_errors'])} "
            f"crosscheck={len(arm['crosscheck'])}",
            file=sys.stderr, flush=True,
        )
    payload = json.dumps(out)
    if args.json_out == "-" or args.json_out is None:
        print(payload, flush=True)
    else:
        with open(args.json_out, "w") as f:
            f.write(payload + "\n")
    rc = 0
    if any(a["hlolint_errors"] for a in out["arms"].values()):
        rc = 1
    if not out.get("bit_identical_arms", True):
        rc = 1
    if args.require_improvement and not out.get("overlap_improved"):
        rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
