"""``python -m mpi4dl_tpu.analyze bench-history BENCH_r*.json`` — the
perf-trajectory comparator over committed bench round files.

The repo accumulates one ``BENCH_rNN.json`` per round (driver shape:
``{"n": round, "rc": exit, "parsed": <last bench.py result line | null>,
...}``), but nothing *read* the trajectory — hlolint got a baseline gate
in PR 1 while throughput regressions could only be spotted by eyeballing
JSON. This module is the reader: it extracts every measured series from
every round (headline + extras values, peak-pixels capability), prints a
per-key trend table, and renders a regression verdict for the latest
round against the most recent previous round that measured the same key,
with a relative tolerance band. CI-friendly: exit 1 on any regression
(or when the latest round produced no parsed result at all), 0 otherwise.

Keys whose history ends before the latest round ("gone" — a renamed
metric or a skipped extra) are reported but do not fail by default;
``--strict`` makes them regressions too.
"""

from __future__ import annotations

import argparse
import glob as globmod
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze bench-history",
        description="Perf-trajectory comparison over BENCH_r*.json rounds",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("files", nargs="+",
                   help="bench round files (BENCH_r*.json), any order — "
                        "sorted by their recorded round number")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="relative band: latest < previous * (1 - tol) "
                        "is a regression, > previous * (1 + tol) an "
                        "improvement, else flat")
    p.add_argument("--strict", action="store_true",
                   help="also fail on keys measured previously but "
                        "absent from the latest round")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the machine-readable comparison here")
    return p


def _load_round(path: str) -> dict:
    """One round file → {"n": int|None, "rc": int|None, "result": dict|None}.
    Accepts either the driver wrapper ({"n", "rc", "parsed", ...}) or a
    bare bench.py result line ({"metric", "value", ...})."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "parsed" in data or "n" in data:
        return {
            "path": path,
            "n": data.get("n"),
            "rc": data.get("rc"),
            "result": data.get("parsed") or None,
        }
    if "metric" in data:
        return {"path": path, "n": None, "rc": None, "result": data}
    raise ValueError(
        f"{path}: neither a bench round wrapper nor a result line"
    )


def extract_series(result: dict) -> "dict[str, float]":
    """Comparable numeric series of one parsed result line: the headline
    throughput under its metric name, every extra's ``value``, the
    peak-pixels capability point, and the memory series — the headline
    ``hlo`` block's peak HBM and the serving extra's per-bucket predicted
    peaks (keys carrying ``peak_hbm_bytes`` are lower-is-better: the
    regression verdict inverts for them)."""
    out: dict[str, float] = {}
    if result.get("metric") and isinstance(result.get("value"), (int, float)):
        out[result["metric"]] = float(result["value"])
    hlo = result.get("hlo")
    if isinstance(hlo, dict) and isinstance(
        hlo.get("peak_hbm_bytes"), (int, float)
    ):
        out["hlo.peak_hbm_bytes"] = float(hlo["peak_hbm_bytes"])
    # Static cost model (analysis/costmodel.py): the predicted overlap
    # ceiling per interconnect prior trends with the normal sign (a
    # falling ceiling means the compiled schedule lost hideability), and
    # predicted comms seconds with the INVERTED one (the program started
    # moving more bytes or lost async pairs).
    if isinstance(hlo, dict) and isinstance(hlo.get("costmodel"), dict):
        for ic, pred in hlo["costmodel"].items():
            if not isinstance(pred, dict):
                continue
            ratio = pred.get("predicted_overlap_ratio")
            if isinstance(ratio, (int, float)):
                out[f"costmodel.predicted_overlap_ratio[{ic}]"] = float(
                    ratio
                )
            comms = pred.get("comms_s")
            if isinstance(comms, (int, float)):
                out[f"costmodel.predicted_comms_s[{ic}]"] = float(comms)
    # Headline measured overlap: the fraction of collective time hidden
    # behind compute in the train-step capture. Falling = regression
    # (the inverse sign of the latency/memory series below).
    attr = result.get("attribution")
    if isinstance(attr, dict):
        ratio = (attr.get("overlap") or {}).get("overlap_ratio")
        if isinstance(ratio, (int, float)):
            out["attribution.trace_overlap_ratio"] = float(ratio)
        # Predicted-vs-measured overlap drift (only recorded when the
        # model makes an overlap claim — null on the sync-only CPU mesh,
        # populated from the first ICI round on). INVERTED sign: growing
        # drift means the cost model is diverging from reality and fails.
        drift = (attr.get("costmodel") or {}).get("overlap_drift")
        if isinstance(drift, (int, float)):
            out["costmodel.overlap_drift"] = float(drift)
    for name, entry in (result.get("extras") or {}).items():
        if not isinstance(entry, dict):
            continue
        if isinstance(entry.get("value"), (int, float)):
            out[name] = float(entry["value"])
        peak = entry.get("peak_trainable_px_per_chip")
        if isinstance(peak, (int, float)):
            out[f"{name}.peak_px"] = float(peak)
        # Tiled-gigapixel extra (shape-gated on peak_px so the serving
        # extra's own latency_ms — deliberately trended only as the
        # p99/p50 RATIO, absolute latency being box noise — stays out):
        # the capability point (largest image the one-chip tile stream
        # served this round) trends with the normal sign, the fixed-size
        # per-request p99 with the INVERTED one.
        peak = entry.get("peak_px")
        if isinstance(peak, (int, float)):
            out[f"{name}.peak_px"] = float(peak)
            lat = entry.get("latency_ms")
            if isinstance(lat, dict) and isinstance(
                lat.get("p99"), (int, float)
            ):
                out[f"{name}.latency_p99_ms"] = float(lat["p99"])
        by_bucket = entry.get("peak_hbm_bytes_by_bucket")
        if isinstance(by_bucket, dict):
            for b, v in by_bucket.items():
                if isinstance(v, (int, float)):
                    out[f"{name}.peak_hbm_bytes[b{b}]"] = float(v)
        # Fleet extra: death-to-replacement latency, trended so a
        # slower recovery (a grown number) reads as the regression.
        # A plain float is the pre-HA shape; the HA drill records one
        # per failure domain ({"replica": ..., "router": ...}).
        recovery = entry.get("recovery_s")
        if isinstance(recovery, (int, float)):
            out[f"{name}.recovery_s"] = float(recovery)
        elif isinstance(recovery, dict):
            for kind, v in recovery.items():
                if isinstance(v, (int, float)):
                    out[f"{name}.recovery_s.{kind}"] = float(v)
        # Cold-start extra: the per-phase recovery decomposition per arm
        # ({"cold": {"compile": ...}, "promote": {...}}), trended with
        # the INVERTED sign — a grown compile (or any other) phase is
        # the regression the compile-cache work must not reintroduce.
        phases = entry.get("phases")
        if isinstance(phases, dict):
            for arm, rec in phases.items():
                if not isinstance(rec, dict):
                    continue
                for ph, v in rec.items():
                    if isinstance(v, (int, float)):
                        out[f"{name}.phase_s.{arm}.{ph}"] = float(v)
        # Serving extra: tail shape (p99/p50), trended with the
        # inverted sign — a growing tail is the regression even when
        # mean throughput holds.
        tail = entry.get("tail")
        if isinstance(tail, dict) and isinstance(
            tail.get("p99_p50_ratio"), (int, float)
        ):
            out[f"{name}.tail_p99_p50_ratio"] = float(tail["p99_p50_ratio"])
        # Scheduler A/B (serving extra, sched_ab): per-arm tight-class
        # p99 under the fixed mixed-class load — trended with the
        # INVERTED sign (a growing tight-class p99 under the EDF arm is
        # the regression the continuous scheduler exists to prevent) —
        # plus per-arm aggregate throughput with the normal sign.
        ab = entry.get("sched_ab")
        if isinstance(ab, dict):
            for arm, rec in (ab.get("arms") or {}).items():
                if not isinstance(rec, dict):
                    continue
                p99 = rec.get("tight_p99_ms")
                if isinstance(p99, (int, float)):
                    out[f"{name}.sched_tight_p99_ms[{arm}]"] = float(p99)
                rps = rec.get("rps")
                if isinstance(rps, (int, float)):
                    out[f"{name}.sched_rps[{arm}]"] = float(rps)
        # Multi-tenant QoS extra: victim p99 inflation under the 10:1
        # noisy-neighbor flood (INVERTED sign — a growing ratio means
        # tenant isolation regressed) and Jain's fairness index over
        # per-tenant served/offered (normal sign — falling fairness
        # fails). The tenancy-on throughput rides the generic ``value``.
        vr = entry.get("victim_p99_ratio")
        if isinstance(vr, (int, float)):
            out[f"{name}.victim_p99_ratio"] = float(vr)
        fi = entry.get("fairness_index")
        if isinstance(fi, (int, float)):
            out[f"{name}.fairness_index"] = float(fi)
        # Numerics sentinel extra: corrupt-drill detection latency and
        # the canary-on throughput tax vs the off baseline, both with
        # the INVERTED sign — slower detection or a grown overhead is
        # the regression (docs target: ≤2% rps). Old rounds without the
        # extra contribute nothing (absent-not-zero).
        det = entry.get("detect_s")
        if isinstance(det, (int, float)):
            out[f"{name}.detect_s"] = float(det)
        ov = entry.get("rps_overhead_pct")
        if isinstance(ov, (int, float)):
            out[f"{name}.rps_overhead_pct"] = float(ov)
        # Incident-engine drill: page→open and open→close latency, both
        # INVERTED — a slower-detected or slower-closed incident is the
        # regression. A round that never detected (or never closed)
        # omits the field entirely and contributes nothing
        # (absent-not-zero: no flattering 0 s MTTR).
        for k in ("mttd_s", "mttr_s"):
            v = entry.get(k)
            if isinstance(v, (int, float)):
                out[f"{name}.{k}"] = float(v)
        # Overlap A/B extras (sp2x2_overlap, serving_sharded): per-arm
        # measured overlap ratio (falling fails), SP train-step time
        # (growing fails), and — serving arms only — per-request p99
        # latency with the INVERTED sign plus throughput with the
        # normal sign. The pipeline schedule A/B rides the same shape:
        # per-arm measured bubble fraction (INVERTED — a grown bubble
        # regresses) + img/s (normal). Old rounds without the extra
        # contribute nothing (absent-not-zero).
        arms = entry.get("arms")
        if isinstance(arms, dict):
            for arm, rec in arms.items():
                if not isinstance(rec, dict):
                    continue
                ratio = rec.get("trace_overlap_ratio")
                if isinstance(ratio, (int, float)):
                    out[f"{name}.trace_overlap_ratio[{arm}]"] = float(ratio)
                st = rec.get("step_time_s")
                if isinstance(st, (int, float)):
                    out[f"{name}.step_time_s[{arm}]"] = float(st)
                lat = rec.get("latency_ms")
                if isinstance(lat, dict) and isinstance(
                    lat.get("p99"), (int, float)
                ):
                    out[f"{name}.latency_p99_ms[{arm}]"] = float(lat["p99"])
                rps = rec.get("throughput_rps")
                if isinstance(rps, (int, float)):
                    out[f"{name}.rps[{arm}]"] = float(rps)
                bubble = rec.get("bubble_fraction")
                if isinstance(bubble, (int, float)):
                    out[f"{name}.bubble_fraction[{arm}]"] = float(bubble)
                ips = rec.get("img_per_s")
                if isinstance(ips, (int, float)):
                    out[f"{name}.img_per_s[{arm}]"] = float(ips)
    return out


def lower_is_better(key: str) -> bool:
    """Memory, latency, step-time, tail-shape, and bubble series regress
    UPWARD: a grown footprint, a slower death-to-replacement (whole or
    any single recovery phase — ``.phase_s.`` series), a slower SP
    train step, a fatter p99/p50 tail, a grown pipeline bubble, grown
    predicted comms time, or growing predicted-vs-measured cost-model
    drift is the failure, a shrunk one the improvement — the inverse of
    every throughput/capability/overlap-ratio series
    (``trace_overlap_ratio`` and ``predicted_overlap_ratio`` keep the
    normal direction: FALLING overlap fails CI). The multitenant
    ``victim_p99_ratio`` is inverted too — a growing victim tail under
    the flood is lost isolation — while ``fairness_index`` keeps the
    normal direction. The numerics sentinel's ``detect_s``
    (corruption-to-fence latency) and ``rps_overhead_pct`` (canary-on
    throughput tax) both regress upward, as do the incident drill's
    ``mttd_s`` (page→incident-open) and ``mttr_s`` (open→close)."""
    return (
        "peak_hbm_bytes" in key
        or key.endswith(".detect_s")
        or key.endswith(".rps_overhead_pct")
        or key.endswith(".mttd_s")
        or key.endswith(".mttr_s")
        or ".recovery_s" in key
        or ".phase_s." in key
        or ".step_time_s" in key
        or key.endswith(".tail_p99_p50_ratio")
        or ".sched_tight_p99_ms" in key
        or ".latency_p99_ms" in key
        or ".bubble_fraction[" in key
        or ".predicted_comms_s[" in key
        or key.endswith(".overlap_drift")
        or key.endswith(".victim_p99_ratio")
    )


def compare(rounds: "list[dict]", tolerance: float, strict: bool) -> dict:
    """Trend + verdicts over loaded rounds (sorted by round number, file
    order breaking ties). ``rounds`` entries are ``_load_round`` outputs."""
    ordered = sorted(
        enumerate(rounds), key=lambda it: (
            it[1]["n"] if isinstance(it[1]["n"], int) else it[0], it[0]
        )
    )
    rounds = [r for _, r in ordered]
    labels = [
        f"r{r['n']:02d}" if isinstance(r["n"], int) else f"#{i}"
        for i, r in enumerate(rounds)
    ]
    history: "dict[str, list]" = {}
    for i, r in enumerate(rounds):
        if not r["result"]:
            continue
        for key, val in extract_series(r["result"]).items():
            history.setdefault(key, [None] * len(rounds))
            history[key][i] = val

    latest = len(rounds) - 1
    keys = []
    n_regressed = 0
    for key in sorted(history):
        vals = history[key]
        cur = vals[latest]
        prev = next(
            (v for v in reversed(vals[:latest]) if v is not None), None
        )
        lo, hi = prev, prev
        if prev is not None:
            lo, hi = prev * (1 - tolerance), prev * (1 + tolerance)
        if cur is None:
            verdict = "gone" if prev is not None else "never"
            regressed = strict and prev is not None
        elif prev is None:
            verdict, regressed = "new", False
        elif cur < lo:
            # Below the band: a throughput/capability drop is the
            # regression; a memory-footprint drop is the improvement.
            if lower_is_better(key):
                verdict, regressed = "improved", False
            else:
                verdict, regressed = "regressed", True
        elif cur > hi:
            if lower_is_better(key):
                verdict, regressed = "regressed", True
            else:
                verdict, regressed = "improved", False
        else:
            verdict, regressed = "flat", False
        n_regressed += bool(regressed)
        keys.append({
            "key": key,
            "values": vals,
            "latest": cur,
            "previous": prev,
            "delta_pct": (
                (cur - prev) / prev * 100.0
                if cur is not None and prev else None
            ),
            "verdict": verdict,
            "regressed": bool(regressed),
        })

    latest_ok = bool(rounds and rounds[latest]["result"])
    return {
        "rounds": labels,
        "files": [r["path"] for r in rounds],
        "tolerance": tolerance,
        "latest_has_result": latest_ok,
        "keys": keys,
        "regressions": n_regressed,
        "ok": latest_ok and n_regressed == 0,
    }


def render_table(cmp: dict) -> str:
    labels = cmp["rounds"]
    width = max([len(k["key"]) for k in cmp["keys"]] + [4])
    head = (
        f"{'key':<{width}}  "
        + "  ".join(f"{lb:>9}" for lb in labels)
        + f"  {'Δ prev':>8}  verdict"
    )
    lines = [head, "-" * len(head)]
    for k in cmp["keys"]:
        cells = "  ".join(
            f"{v:>9.3f}" if v is not None else f"{'-':>9}"
            for v in k["values"]
        )
        delta = (
            f"{k['delta_pct']:>+7.1f}%" if k["delta_pct"] is not None
            else f"{'-':>8}"
        )
        lines.append(f"{k['key']:<{width}}  {cells}  {delta}  {k['verdict']}")
    lines.append(
        f"{cmp['regressions']} regression(s) at tolerance "
        f"{cmp['tolerance']:.0%}"
        + ("" if cmp["latest_has_result"]
           else " — and the latest round has NO parsed result")
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    paths = []
    for pat in args.files:
        hits = sorted(globmod.glob(pat))
        paths.extend(hits if hits else [pat])  # unmatched: open() reports
    rounds = [_load_round(p) for p in paths]
    if not rounds:
        print("no round files", file=sys.stderr)
        return 2
    cmp = compare(rounds, args.tolerance, args.strict)
    print(render_table(cmp))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(cmp, f, indent=2)
            f.write("\n")
    return 0 if cmp["ok"] else 1


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
