"""The expectations algebra: per-layer collective deltas + ``compose()``.

The ROADMAP's composition item: "hlolint expectations must compose too —
halo-permute window × stage-permute budget derived from the stacked
predictor, not hand-summed." Before this module, the lint gates were four
hand-wired special cases (``Expectations(single_chip=True)``, the spatial
halo window, the pipeline ``extra_permutes`` budget, ``pure_dp``) and any
NEW stack — SP front × LP pipeline, tiled serving over a sharded bucket —
needed someone to re-derive the window by hand and keep it in sync with
three engines.

Here every parallelism layer contributes one typed
:class:`CollectiveDelta` describing the collectives it is ENTITLED to add
to a compiled program:

======================  ====================================================
delta                    entitlement
======================  ====================================================
``spatial_delta``        halo-shift ppermutes in the windowed class
                         (``[n, 2n]``: forward count ``n`` from
                         ``Trainer.halo_shift_count`` partition math, the
                         backward's transposed shifts partially deduped by
                         XLA), plus the tile grid.
``pipeline_delta``       stage-boundary wire ppermutes in the EXACT class
                         (``PipelineTrainer.stage_permute_count()``:
                         forward scan body + AD transpose — no dedupe
                         slack, shifts BOTH window bounds).
``spatial_join_delta``   the SP→LP join ``all-gather``\\ s (tile join into
                         the replicated head; exact count — fwd gather +
                         its backward re-gather on a train step).
``data_parallel_delta``  gradient/metric all-reduces only — any permute,
                         gather, or all-to-all is then a resharding bug.
``single_chip_delta``    NOTHING: a one-device program (serving forward)
                         with any collective regressed off the chip.
``tiled_delta``          NOTHING: a tile executable is a one-chip section
                         of a streamed program (same zero entitlement,
                         distinct provenance).
======================  ====================================================

``compose(*deltas)`` folds any stack of deltas into the
:class:`~mpi4dl_tpu.analysis.rules.Expectations` the rule engine consumes:
windowed permute entitlements sum into ``halo_shifts``, exact ones into
``extra_permutes``, join gathers into ``join_gathers``, and the degenerate
cases (all-zero-collective → ``single_chip``; all-DP → ``pure_dp``) fall
out instead of being special-cased at call sites. Composition is total on
meaningful stacks and LOUD on meaningless ones: a zero-collective section
composed with a communicating layer is a contradiction (the program cannot
both communicate and not), as are two different tile grids.

Derived budgets are byte-for-byte equal to the hand-built ``Expectations``
they replaced on every existing config — ``compose(single_chip_delta())``
*is* ``Expectations(single_chip=True)``, ``compose(pipeline_delta(2))``
*is* ``Expectations(halo_shifts=0, extra_permutes=2)`` — so the switch is
pure refactoring for today's gates and new capability only for stacks
(see ``tests/test_expectations_algebra.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from mpi4dl_tpu.analysis.rules import Expectations

__all__ = [
    "CollectiveDelta",
    "compose",
    "data_parallel_delta",
    "pipeline_delta",
    "single_chip_delta",
    "spatial_delta",
    "spatial_join_delta",
    "tiled_delta",
]


@dataclasses.dataclass(frozen=True)
class CollectiveDelta:
    """One parallelism layer's collective entitlement.

    Constructed via the ``*_delta`` helpers (which carry the layer
    semantics), summed by :func:`compose`. ``layer`` is provenance — it
    names which engine vouches for the entitlement in messages and
    reports, and never affects the composed budget beyond the flags.
    """

    # Provenance tag: "spatial" | "pipeline" | "spatial_join" |
    # "data_parallel" | "single_chip" | "tiled".
    layer: str
    # Tile grid this layer shards H/W over (spatial only).
    tile_shape: tuple[int, int] | None = None
    # Windowed-class permutes: forward count n, compiled window [n, 2n].
    halo_shifts: int = 0
    # Exact-class permutes: shift both window bounds (no dedupe slack).
    exact_permutes: int = 0
    # Exact all-gather entitlement (SP->LP join); None = no claim.
    join_gathers: int | None = None
    # False for zero-collective sections (single-chip / tile executables).
    communicates: bool = True
    # True when the layer's ONLY collectives are grad/metric all-reduces.
    data_parallel_only: bool = False

    def describe(self) -> str:
        """One-line provenance for reports and error messages."""
        bits = []
        if not self.communicates:
            bits.append("zero-collective")
        if self.halo_shifts:
            bits.append(f"halo window [{self.halo_shifts}, "
                        f"{2 * self.halo_shifts}]")
        if self.exact_permutes:
            bits.append(f"{self.exact_permutes} exact permutes")
        if self.join_gathers is not None:
            bits.append(f"{self.join_gathers} join gathers")
        if self.data_parallel_only:
            bits.append("all-reduce only")
        return f"{self.layer}({', '.join(bits) or 'none'})"


def spatial_delta(
    tile_shape: "tuple[int, int]", halo_shifts: int
) -> CollectiveDelta:
    """Spatial (SP) layer: ``halo_shifts`` counted forward shift
    ppermutes (``Trainer.halo_shift_count`` / the sharded predictor's
    cached count) over ``tile_shape`` tiles — the ``[n, 2n]`` window."""
    if halo_shifts < 0:
        raise ValueError(f"halo_shifts must be >= 0, got {halo_shifts}")
    return CollectiveDelta(
        layer="spatial",
        tile_shape=tuple(tile_shape),
        halo_shifts=int(halo_shifts),
    )


def pipeline_delta(stage_permutes: int) -> CollectiveDelta:
    """Pipeline (LP/PP) layer: the EXACT stage-boundary wire-permute
    budget (``PipelineTrainer.stage_permute_count()``,
    ``2*(n_virtual-1)``)."""
    if stage_permutes < 0:
        raise ValueError(
            f"stage_permutes must be >= 0, got {stage_permutes}"
        )
    return CollectiveDelta(layer="pipeline", exact_permutes=int(stage_permutes))


def spatial_join_delta(gathers: int = 2) -> CollectiveDelta:
    """The SP→LP join: tile ``all-gather`` into the replicated head.
    Exact count — 2 on a train step (forward join + backward re-gather),
    1 on a forward-only program."""
    if gathers < 0:
        raise ValueError(f"gathers must be >= 0, got {gathers}")
    return CollectiveDelta(layer="spatial_join", join_gathers=int(gathers))


def data_parallel_delta() -> CollectiveDelta:
    """Data-parallel layer: gradient/metric all-reduces only."""
    return CollectiveDelta(layer="data_parallel", data_parallel_only=True)


def single_chip_delta() -> CollectiveDelta:
    """A one-device program (the serving forward): zero entitlement —
    ANY collective means an input/param landed sharded or a mesh leaked
    into the eval path."""
    return CollectiveDelta(layer="single_chip", communicates=False)


def tiled_delta() -> CollectiveDelta:
    """A tile executable of the streamed gigapixel path: a one-chip
    section, same zero entitlement as ``single_chip_delta`` with its own
    provenance tag."""
    return CollectiveDelta(layer="tiled", communicates=False)


def compose(*deltas: "CollectiveDelta | Iterable[CollectiveDelta]") -> Expectations:
    """Fold layer deltas into the rule engine's ``Expectations``.

    Accepts deltas as positional args or iterables of deltas (so a
    provider returning a tuple composes directly:
    ``compose(*trainer.collective_deltas(...))`` or
    ``compose(trainer.collective_deltas(...))``).

    Laws (pinned by ``tests/test_expectations_algebra.py``):

    - zero-collective ∘ zero-collective = zero-collective
      (``single_chip=True`` — a stack of silent sections stays silent);
    - zero-collective ∘ communicating = ⊥ (``ValueError`` — a program
      cannot both communicate and be single-chip);
    - DP-only ∘ DP-only = ``pure_dp``;
    - any structured layer in the stack → windowed ``halo_shifts`` sum,
      exact ``exact_permutes`` sum into ``extra_permutes``, join-gather
      claims sum into ``join_gathers`` (``None`` when no layer claims);
    - two spatial layers with DIFFERENT tile grids = ⊥ (one program has
      one H/W sharding).
    """
    flat: list[CollectiveDelta] = []
    for d in deltas:
        if isinstance(d, CollectiveDelta):
            flat.append(d)
        else:
            flat.extend(d)
    if not flat:
        raise ValueError("compose() needs at least one CollectiveDelta")
    for d in flat:
        if not isinstance(d, CollectiveDelta):
            raise TypeError(f"compose() takes CollectiveDelta, got {d!r}")

    silent = [d for d in flat if not d.communicates]
    talking = [d for d in flat if d.communicates]
    if silent and talking:
        raise ValueError(
            "cannot compose a zero-collective section with a communicating "
            f"layer: {[d.describe() for d in silent]} vs "
            f"{[d.describe() for d in talking]} — a program is either "
            "single-chip or it communicates"
        )
    if not talking:
        return Expectations(single_chip=True)
    if all(d.data_parallel_only for d in talking):
        return Expectations(pure_dp=True)

    grids = {d.tile_shape for d in talking if d.tile_shape is not None}
    if len(grids) > 1:
        raise ValueError(
            f"conflicting tile grids in one stack: {sorted(grids)} — a "
            "compiled program has one H/W sharding"
        )
    joins = [d.join_gathers for d in talking if d.join_gathers is not None]
    return Expectations(
        tile_shape=next(iter(grids)) if grids else None,
        halo_shifts=sum(d.halo_shifts for d in talking),
        extra_permutes=sum(d.exact_permutes for d in talking),
        join_gathers=sum(joins) if joins else None,
    )
