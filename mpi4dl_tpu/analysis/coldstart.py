"""``python -m mpi4dl_tpu.analyze coldstart`` — where does cold-start go?

Joins three kinds of committed evidence — footprint-ledger dumps (per-
executable fingerprints + trace/compile/warm seconds + predicted peaks,
``FootprintLedger.dump()`` / the worker's ``*.ready.*.ledger.json``),
JSONL telemetry event logs (``elastic.restart`` events: who died, why,
how often), and fleet state reports (``FleetSupervisor.state()``:
``fleet_recovery_seconds`` + its phase decomposition) — into one ranked
"top executables by compile seconds" manifest: exactly the prioritized
warm list the ROADMAP's compile-cache service will serialize first.

Pure JSON by design: no jax import anywhere on this path, so it runs on
artifacts from a dead machine and dispatches in ``analysis/cli.py``
before any backend setup (pinned by tests/test_artifact_dispatch.py).

``--artifact OUT.json`` writes the manifest; ``--budget-s S`` is the CI
gate — exit 1 when total compile seconds exceed the budget (the
falsifiable A/B the jax-upgrade / executable-serialization PR will be
judged against).
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(path: str) -> dict:
    """Classify one input file: a ledger dump (``{"entries": [...]}``), a
    fleet state report (``last_recovery_s``/``slots``), or a JSONL event
    log (anything that isn't a single JSON object)."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, dict) and isinstance(data.get("entries"), list):
        return {"kind": "ledger", "path": path, "entries": data["entries"]}
    if isinstance(data, dict) and (
        "last_recovery_s" in data or "slots" in data
    ):
        return {"kind": "fleet", "path": path, "state": data}
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if isinstance(ev, dict) and ev.get("name") == "elastic.restart":
            events.append(ev)
    return {"kind": "events", "path": path, "restarts": events}


def build_manifest(paths, top: int = 10) -> dict:
    """The joined cold-start manifest over every input artifact."""
    groups: "dict[str, dict]" = {}
    restarts: "list[dict]" = []
    fleet: "dict | None" = None
    counts = {"ledger": 0, "events": 0, "fleet": 0}
    for path in paths:
        loaded = _load(path)
        counts[loaded["kind"]] += 1
        if loaded["kind"] == "ledger":
            for e in loaded["entries"]:
                if not isinstance(e, dict) or "program" not in e:
                    continue
                program = str(e["program"])
                bucket = e.get("bucket")
                name = (
                    program if bucket is None else f"{program}[{bucket}]"
                )
                # Group by content fingerprint — replicas that compiled
                # the SAME executable merge, and the group's total is
                # what a fleet-shared artifact store would have saved.
                key = e.get("fingerprint") or name
                g = groups.setdefault(key, {
                    "fingerprint": e.get("fingerprint"),
                    "executable": name,
                    "count": 0,
                    "trace_s": 0.0,
                    "compile_s": 0.0,
                    "warm_s": 0.0,
                    "peak_bytes": None,
                    "sources": [],
                })
                g["count"] += 1
                for ph in ("trace_s", "compile_s", "warm_s"):
                    # rollup entries (the tiled engine's per-image-bucket
                    # aggregate) duplicate the fine-grained serve_tiled_*
                    # seconds — count only their unique warm_s.
                    if e.get("rollup") and ph != "warm_s":
                        continue
                    v = e.get(ph)
                    if isinstance(v, (int, float)):
                        g[ph] += float(v)
                peak = e.get("peak_bytes")
                if isinstance(peak, (int, float)):
                    g["peak_bytes"] = max(g["peak_bytes"] or 0, int(peak))
                if path not in g["sources"]:
                    g["sources"].append(path)
        elif loaded["kind"] == "events":
            restarts.extend(loaded["restarts"])
        else:
            fleet = loaded["state"]

    ranked = sorted(
        groups.values(),
        key=lambda g: (-g["compile_s"], -g["trace_s"], g["executable"]),
    )
    for g in ranked:
        g["total_s"] = round(
            g["trace_s"] + g["compile_s"] + g["warm_s"], 6
        )
        for ph in ("trace_s", "compile_s", "warm_s"):
            g[ph] = round(g[ph], 6)
    totals = {
        ph: round(sum(g[ph] for g in ranked), 6)
        for ph in ("trace_s", "compile_s", "warm_s", "total_s")
    }

    by_reason: "dict[str, int]" = {}
    for ev in restarts:
        reason = str((ev.get("attrs") or {}).get("reason", "unknown"))
        by_reason[reason] = by_reason.get(reason, 0) + 1

    recovery = None
    if fleet is not None:
        phases = fleet.get("last_recovery_phases")
        recovery = {
            "last_recovery_s": fleet.get("last_recovery_s"),
            "phases": phases,
            "phase_sum_s": (
                round(sum(phases.values()), 6)
                if isinstance(phases, dict) else None
            ),
            "promotions": fleet.get("promotions"),
            "restarts": fleet.get("restarts"),
        }

    return {
        "inputs": counts,
        "executables": ranked[: top if top and top > 0 else None],
        "executables_total": len(ranked),
        "totals": totals,
        "restarts": {"count": len(restarts), "by_reason": by_reason},
        "recovery": recovery,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze coldstart",
        description=(
            "Rank executables by compile seconds across ledger dumps; "
            "join elastic.restart events and fleet recovery phases."
        ),
    )
    ap.add_argument(
        "paths", nargs="+",
        help="ledger dump JSONs, JSONL telemetry logs, and/or fleet "
             "state report JSONs (kind is sniffed per file)",
    )
    ap.add_argument("--top", type=int, default=10,
                    help="executables to list (default 10)")
    ap.add_argument("--artifact", default=None,
                    help="write the full manifest JSON here")
    ap.add_argument(
        "--budget-s", type=float, default=None,
        help="CI gate: exit 1 when total compile seconds (the XLA "
             "phase, the part a compile cache would erase) exceed this",
    )
    args = ap.parse_args(argv)

    manifest = build_manifest(args.paths, top=args.top)
    over = (
        args.budget_s is not None
        and manifest["totals"]["compile_s"] > args.budget_s
    )
    manifest["budget_s"] = args.budget_s
    manifest["over_budget"] = over

    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(manifest, f, indent=2)
            f.write("\n")

    t = manifest["totals"]
    print(
        f"# coldstart: {manifest['executables_total']} executables, "
        f"compile {t['compile_s']:.3f}s + trace {t['trace_s']:.3f}s + "
        f"warm {t['warm_s']:.3f}s = {t['total_s']:.3f}s"
    )
    for i, g in enumerate(manifest["executables"], 1):
        fp = g["fingerprint"] or "-"
        print(
            f"  {i}. {g['executable']} {fp} compile {g['compile_s']:.3f}s "
            f"x{g['count']} (trace {g['trace_s']:.3f}s, "
            f"warm {g['warm_s']:.3f}s)"
        )
    r = manifest["restarts"]
    if r["count"]:
        reasons = ", ".join(
            f"{k}={v}" for k, v in sorted(r["by_reason"].items())
        )
        print(f"# restarts: {r['count']} ({reasons})")
    rec = manifest["recovery"]
    if rec is not None and rec.get("phases"):
        parts = " + ".join(
            f"{p} {v:.3f}" for p, v in rec["phases"].items() if v
        ) or "none"
        print(
            f"# recovery: {rec['last_recovery_s']:.3f}s = {parts} "
            f"(phase sum {rec['phase_sum_s']:.3f}s)"
        )
    if over:
        print(
            f"# OVER BUDGET: compile {t['compile_s']:.3f}s > "
            f"{args.budget_s:.3f}s",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via analyze
    sys.exit(main())
