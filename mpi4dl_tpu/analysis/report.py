"""Report assembly: one JSON-serializable record per analyzed program.

:func:`analyze_hlo_text` is the pure-text entry (unit tests, canned
snippets); :func:`analyze_compiled` adds what only the live executable
knows (memory totals). Both run the full rule set and embed the findings,
so one artifact answers "what does this program do on the wire, how much
does it hold, and is any of that a regression".
"""

from __future__ import annotations

import dataclasses
import json

from mpi4dl_tpu.analysis.hlo import parse_hlo_text
from mpi4dl_tpu.analysis.inventory import (
    collective_inventory,
    collective_records,
    overlap_summary,
)
from mpi4dl_tpu.analysis.memory import memory_summary
from mpi4dl_tpu.analysis.rules import (
    DEFAULT_RULES,
    Expectations,
    LintContext,
    max_severity,
    run_rules,
)


@dataclasses.dataclass
class Report:
    module_name: str
    is_scheduled: bool
    platform: str
    config: dict
    inventory: dict
    collectives: list  # CollectiveRecord.as_dict() entries
    overlap: dict
    memory: dict | None
    findings: list  # Finding.as_dict() entries
    max_severity: str | None

    @property
    def ok(self) -> bool:
        return self.max_severity != "error"

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d

    def to_json(self, **kw) -> str:
        kw.setdefault("indent", 2)
        return json.dumps(self.as_dict(), **kw)

    def summary_line(self) -> str:
        n_err = sum(1 for f in self.findings if f["severity"] == "error")
        n_warn = sum(1 for f in self.findings if f["severity"] == "warn")
        mem = (
            f", peak {self.memory['peak_bytes'] / 1e6:.1f} MB"
            if self.memory and self.memory.get("peak_bytes") is not None
            else ""
        )
        return (
            f"hlolint {self.module_name or '<module>'}: "
            f"{self.overlap['n_collectives']} collectives "
            f"({self.overlap['total_bytes'] / 1e6:.2f} MB moved, "
            f"{self.overlap['async_pairs']} async pairs{mem}) — "
            f"{n_err} error(s), {n_warn} warning(s)"
        )


def analyze_hlo_text(
    text: str,
    expected: Expectations | None = None,
    memory: dict | None = None,
    remat: dict | None = None,
    platform: str = "",
    config: dict | None = None,
    rules=DEFAULT_RULES,
) -> Report:
    module = parse_hlo_text(text)
    inventory = collective_inventory(module)
    records = collective_records(module)
    ctx = LintContext(
        module=module,
        inventory=inventory,
        records=records,
        expected=expected or Expectations(),
        memory=memory,
        remat=remat,
        platform=platform,
    )
    findings = run_rules(ctx, rules)
    return Report(
        module_name=module.name,
        is_scheduled=module.is_scheduled,
        platform=platform,
        config=config or {},
        inventory=inventory,
        collectives=[r.as_dict() for r in records],
        overlap=overlap_summary(records),
        memory=memory,
        findings=[f.as_dict() for f in findings],
        max_severity=max_severity(findings),
    )


def analyze_compiled(
    compiled,
    expected: Expectations | None = None,
    remat: dict | None = None,
    platform: str = "",
    config: dict | None = None,
    baseline_bytes: int | None = None,
    tolerance: float = 0.05,
    rules=DEFAULT_RULES,
) -> Report:
    """Analyze a live ``.lower(...).compile()`` executable: HLO text rules
    plus the memory totals (+ committed-baseline comparison when given)."""
    memory = memory_summary(compiled)
    if memory is not None and baseline_bytes is not None:
        memory["baseline_bytes"] = int(baseline_bytes)
        memory["tolerance"] = tolerance
    return analyze_hlo_text(
        compiled.as_text(),
        expected=expected,
        memory=memory,
        remat=remat,
        platform=platform,
        config=config,
        rules=rules,
    )
