"""``python -m mpi4dl_tpu.analyze memory-plan`` — the HBM feasibility planner.

Answers "will this config fit?" *before* anything executes — the question
the bench walk could only answer by dying at 8192² with an unparsed
RESOURCE_EXHAUSTED, and the question every scale-out item on the ROADMAP
(gigapixel tiled inference, multi-chip serving, the replica fleet) needs
a number for. Two modes:

**Artifact mode** (pure JSON — dispatched in ``analysis/cli.py`` before
any jax/backend setup, like ``bench-history``): read committed predicted
peaks — the hlolint baseline (``docs/artifacts/hlolint_baseline.json``)
and/or a :class:`~mpi4dl_tpu.telemetry.memory.FootprintLedger` dump —
and render a fits/doesn't verdict per key against ``--limit-gb`` /
``--limit-bytes``::

    python -m mpi4dl_tpu.analyze memory-plan --limit-gb 15.48
    python -m mpi4dl_tpu.analyze memory-plan --ledger ledger.json \
        --limit-bytes 16106127360 --json plan.json

**Compile mode** (``--program serve|train``): AOT-lower the requested
config WITHOUT executing it and predict its peak from the compiled
buffer assignment (:func:`mpi4dl_tpu.analysis.memory.memory_summary`) —
the number the allocator will actually request, exact by construction
(the admission guard in :class:`mpi4dl_tpu.serve.ServingEngine` reads
the same summary off the same executables). The serve path is lowered
fully abstractly (``jax.eval_shape`` params + batch-stats structure, a
``ShapeDtypeStruct`` input) — zero device arrays are ever materialized.
``--bisect px|bucket`` binary-searches the candidate ladder for the
largest feasible value::

    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.analyze memory-plan \
        --program serve --size 1024 --bucket 8 --limit-gb 15.48
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.analyze memory-plan \
        --program serve --bucket 1 --bisect px --limit-gb 15.48
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.analyze memory-plan \
        --program train --model resnet --size 2048 --batch 1 \
        --remat scan --limit-gb 15.48

Exit status: 0 when everything asked about fits (or the bisect found a
feasible value), 1 when something does not fit, 2 on unusable input.
"""

from __future__ import annotations

import argparse
import json
import sys

from mpi4dl_tpu.analysis.memory import (
    DEFAULT_BASELINE_PATH,
    feasibility,
    load_baseline_all,
)

DEFAULT_PX_LADDER = "256,512,1024,1536,2048,3072,4096,6144,8192"
DEFAULT_TILE_LADDER = "64,128,256,512,1024,2048,4096"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze memory-plan",
        description="Predict peak HBM vs device limit; bisect feasibility",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    # -- limit (both modes) --------------------------------------------------
    p.add_argument("--limit-bytes", type=int, default=None,
                   help="device memory limit in bytes")
    p.add_argument("--limit-gb", type=float, default=None,
                   help="device memory limit in GiB (e.g. 15.48)")
    p.add_argument("--fit-margin", type=float, default=0.0,
                   help="required post-fit headroom fraction of the limit")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the machine-readable plan here")
    # -- artifact mode (pure JSON, no jax) -----------------------------------
    p.add_argument("--baseline", default=None,
                   help="hlolint baseline JSON of committed peaks "
                        f"(default {DEFAULT_BASELINE_PATH})")
    p.add_argument("--ledger", default=None,
                   help="a FootprintLedger dump "
                        "(telemetry.FootprintLedger.dump / "
                        "engine stats()['memory']['programs'])")
    p.add_argument("--key", action="append", default=None,
                   help="restrict artifact mode to these keys "
                        "(repeatable; substring match)")
    # -- compile mode --------------------------------------------------------
    p.add_argument("--program", choices=("serve", "train"), default=None,
                   help="AOT-lower this program instead of reading "
                        "artifacts (needs jax; nothing is executed)")
    p.add_argument("--model", choices=("resnet", "amoebanet"),
                   default="resnet")
    p.add_argument("--size", type=int, default=512,
                   help="square image size (px)")
    p.add_argument("--bucket", type=int, default=1,
                   help="serve: batch bucket to lower")
    p.add_argument("--batch", type=int, default=1,
                   help="train: global batch size")
    p.add_argument("--depth", type=int, default=11,
                   help="resnet depth (9n+2 for serve's v2, v1 for train)")
    p.add_argument("--layers", type=int, default=6,
                   help="amoebanet layer count")
    p.add_argument("--filters", type=int, default=64,
                   help="amoebanet filter count")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--dtype", default="float32",
                   choices=("float32", "bfloat16"))
    p.add_argument("--dp", type=int, default=0,
                   help="train: data-parallel replicas (0 = cli default)")
    p.add_argument("--spatial-parts", type=int, default=0,
                   help="train: spatial tiles (resnet; 0 = pure DP)")
    p.add_argument("--remat", default="none",
                   choices=("none", "cell", "sqrt", "scan", "scan2",
                            "scanlog", "scanq", "scan_save", "cell_save",
                            "group_save"))
    p.add_argument("--bisect", choices=("px", "bucket", "tile"),
                   default=None,
                   help="binary-search the largest feasible value on the "
                        "candidate ladder (needs a limit). 'tile' "
                        "answers the gigapixel question: the largest "
                        "tile core whose tile-streaming executables "
                        "(section window + stitched-feature head, "
                        "serve/tiled.py) both fit the chip at --size")
    p.add_argument("--px-candidates", default=DEFAULT_PX_LADDER,
                   help="comma-separated px ladder for --bisect px")
    p.add_argument("--max-bucket", type=int, default=64,
                   help="largest power-of-two bucket for --bisect bucket")
    p.add_argument("--tile", type=int, default=None,
                   help="serve: predict the TILED forward's peaks at "
                        "this tile core instead of the monolithic "
                        "forward (a stride-aligned px count)")
    p.add_argument("--tile-candidates", default=DEFAULT_TILE_LADDER,
                   help="comma-separated stride-aligned tile-core "
                        "ladder for --bisect tile")
    p.add_argument("--tile-bucket", type=int, default=8,
                   help="TILE bucket the tiled section executable is "
                        "lowered at (the runtime's largest tile batch)")
    return p


def _resolve_limit(args, device_limit=None) -> "int | None":
    if args.limit_bytes is not None:
        return int(args.limit_bytes)
    if args.limit_gb is not None:
        return int(args.limit_gb * 2**30)
    return device_limit


# -- artifact mode (NO jax import anywhere on this path) ----------------------


def _artifact_entries(args) -> "list[dict]":
    entries = []
    if args.ledger:
        with open(args.ledger) as f:
            data = json.load(f)
        rows = data.get("entries", data) if isinstance(data, dict) else data
        for e in rows:
            key = e.get("program", "?")
            if e.get("bucket") is not None:
                key = f"{key}[{e['bucket']}]"
            entries.append({"key": key, "peak_bytes": e.get("peak_bytes")})
    if args.baseline or not args.ledger:
        for key, peak in sorted(load_baseline_all(args.baseline).items()):
            entries.append({"key": key, "peak_bytes": peak})
    if args.key:
        entries = [
            e for e in entries
            if any(k in e["key"] for k in args.key)
        ]
    return entries


def _artifact_mode(args) -> int:
    entries = _artifact_entries(args)
    limit = _resolve_limit(args)
    rows = []
    for e in entries:
        verdict = feasibility(e["peak_bytes"], limit, args.fit_margin)
        rows.append({"key": e["key"], **verdict})
    plan = {
        "mode": "artifact",
        "limit_bytes": limit,
        "fit_margin": args.fit_margin,
        "entries": rows,
        "ok": all(r["fits"] is not False for r in rows) if rows else None,
    }
    _render(plan, args)
    if not rows:
        print("no committed peaks found", file=sys.stderr)
        return 2
    return 0 if plan["ok"] else 1


def _render(plan: dict, args) -> None:
    rows = plan.get("entries") or []
    width = max([len(r["key"]) for r in rows] + [4])
    limit = plan.get("limit_bytes")
    print(
        f"memory-plan ({plan['mode']}): limit "
        + (f"{limit / 2**30:.2f} GiB" if limit else "unknown")
        + (f", margin {plan['fit_margin']:.0%}"
           if plan.get("fit_margin") else "")
    )
    for r in rows:
        peak = r.get("peak_bytes")
        peak_s = f"{peak / 2**30:7.3f}G" if peak is not None else "      ?"
        if r.get("fits") is None:
            verdict = "?"
        else:
            verdict = "fits" if r["fits"] else "DOES NOT FIT"
        head = (
            f" ({r['headroom_ratio']:+.1%} headroom)"
            if r.get("headroom_ratio") is not None else ""
        )
        print(f"  {r['key']:<{width}}  {peak_s}  {verdict}{head}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(plan, f, indent=2)
            f.write("\n")


# -- compile mode -------------------------------------------------------------


def _setup_backend() -> None:
    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache
    import os

    apply_platform_env()
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(8)
    enable_compilation_cache()


def _serve_cells(args, px: int):
    if args.model == "resnet":
        from mpi4dl_tpu.models.resnet import get_resnet_v2

        return get_resnet_v2(
            depth=args.depth, num_classes=args.classes,
            pool_kernel=max(1, px // 4),
        )
    from mpi4dl_tpu.models.amoebanet import amoebanetd

    return amoebanetd(
        num_classes=args.classes, num_layers=args.layers,
        num_filters=args.filters,
    )


def _abstract_serve_state(cells, px: int, dtype):
    """Fully abstract ``(params, batch_stats)`` structures of a cell list
    at ``px`` — ``jax.eval_shape`` end to end, zero device arrays. The
    shared substrate of the monolithic and tiled compile-only peaks."""
    import jax

    from mpi4dl_tpu.evaluate import stats_unfreeze, _finalize
    from mpi4dl_tpu.ops.layers import bn_stats_mode
    from mpi4dl_tpu.parallel.partition import init_cells

    cells = tuple(cells)
    x1 = jax.ShapeDtypeStruct((1, px, px, 3), dtype)
    params_s = jax.eval_shape(
        lambda k, x: init_cells(list(cells), k, x),
        jax.random.PRNGKey(0), x1,
    )

    def collect_one(p, x):
        with bn_stats_mode("collect"):
            out, h = [], x
            for cell, pp in zip(cells, p):
                h, upd = cell.apply(dict(pp), h, mutable=["batch_stats"])
                out.append(upd.get("batch_stats", {}))
        return [_finalize(s) for s in stats_unfreeze(out)]

    stats_s = jax.eval_shape(collect_one, params_s, x1)
    return params_s, stats_s


def predict_serve_peak(cells, px: int, bucket: int, dtype=None) -> "dict | None":
    """Compile-only peak of the frozen-stats serve forward for one
    bucket — lowered FULLY abstractly (eval_shape params + batch-stats
    structure, ShapeDtypeStruct input), so nothing executes and no
    device array is materialized. The result is bit-identical to
    ``memory_summary`` of the executable the engine's AOT warm-up
    builds for the same config (tier-1-asserted)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.analysis.memory import memory_summary
    from mpi4dl_tpu.evaluate import _apply_running

    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
    cells = tuple(cells)
    params_s, stats_s = _abstract_serve_state(cells, px, dtype)

    def fwd(p, s, x):
        return _apply_running(cells, p, s, x)

    xb = jax.ShapeDtypeStruct((int(bucket), px, px, 3), dtype)
    compiled = jax.jit(fwd).lower(params_s, stats_s, xb).compile()
    return memory_summary(compiled)


def predict_tiled_peak(
    cells, px: int, tile: int, tile_bucket: int = 8, dtype=None
) -> "dict | None":
    """Compile-only peaks of the TILED forward (serve/tiled.py) at one
    tile core: the section executable at its ``tile_bucket × window ×
    window`` shape plus the head at the stitched-feature shape — both
    lowered abstractly, nothing executed. ``peak_bytes`` is the max of
    the two (both must fit the chip at run time); the per-executable
    breakdown and the derived geometry ride alongside. This is how
    "what tile size fits this chip" is answered BEFORE a gigapixel
    request exists."""
    import jax.numpy as jnp

    from mpi4dl_tpu.analysis.memory import memory_summary
    from mpi4dl_tpu.evaluate import aot_compile_tiled_predict
    from mpi4dl_tpu.serve.tiled import tile_geometry

    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
    cells = tuple(cells)
    params_s, stats_s = _abstract_serve_state(cells, px, dtype)
    g = tile_geometry(
        cells, params_s, stats_s, (px, px, 3), tile, dtype=dtype
    )
    exe = aot_compile_tiled_predict(
        cells, params_s, stats_s, g.split,
        (*g.window_hw, 3), (*g.feat_hw, g.feat_channels),
        [int(tile_bucket)], dtype=dtype, feature_dtype=g.feat_dtype,
    )
    tile_sum = memory_summary(exe["tile"][int(tile_bucket)])
    head_sum = memory_summary(exe["head"])
    if tile_sum is None or head_sum is None:
        return None
    return {
        "peak_bytes": max(tile_sum["peak_bytes"], head_sum["peak_bytes"]),
        "tile_peak_bytes": tile_sum["peak_bytes"],
        "head_peak_bytes": head_sum["peak_bytes"],
        "geometry": g.describe(),
    }


def predict_train_peak(args, px: int, batch: int) -> "dict | None":
    """Compile-only peak of the full train step (fwd+bwd+update) for
    the requested config, via the same Trainer build the hlolint CLI
    uses. Parameter init executes (tiny, size-independent); the step
    itself is lowered and compiled but NEVER run."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.analysis.cli import _build_trainer
    from mpi4dl_tpu.analysis.memory import memory_summary

    ns = argparse.Namespace(
        model=args.model, size=px, batch=batch, depth=args.depth,
        layers=args.layers, filters=args.filters,
        spatial_parts=args.spatial_parts, spatial_cells=3,
        slice_method="square", dp=args.dp, remat=args.remat,
    )
    trainer, _, _ = _build_trainer(ns)
    dtype = jnp.dtype(args.dtype)
    x_shape = (batch, px, px, 3)
    state = trainer.init(jax.random.PRNGKey(0), x_shape, dtype=dtype)
    xs, ys = trainer.shard_batch(
        jnp.zeros(x_shape, dtype), jnp.zeros((batch,), jnp.int32)
    )
    compiled = trainer._jit_step.lower(state, xs, ys).compile()
    return memory_summary(compiled)


def _predict(args, px: int, bucket: int, tile: "int | None" = None) -> "dict | None":
    if args.program == "serve":
        if tile is not None:
            return predict_tiled_peak(
                _serve_cells(args, px), px, tile,
                tile_bucket=args.tile_bucket, dtype=args.dtype,
            )
        return predict_serve_peak(
            _serve_cells(args, px), px, bucket, dtype=args.dtype
        )
    return predict_train_peak(args, px, args.batch)


def _bisect(args, limit: int) -> dict:
    """Largest feasible value on the candidate ladder (binary search —
    peak is monotone in px, bucket, and tile core). Every compiled
    candidate is reported; refusals on RESOURCE_EXHAUSTED (the CPU
    backend can itself OOM lowering a huge program) count as
    infeasible. The ``tile`` axis predicts BOTH tiled executables
    (section window + head) and requires both to fit — when even the
    smallest tile's head is too big, nothing fits and the exit is 1."""
    from mpi4dl_tpu.telemetry.memory import is_oom_error

    if args.bisect == "px":
        ladder = sorted(
            int(v) for v in str(args.px_candidates).split(",") if v.strip()
        )
    elif args.bisect == "tile":
        if args.program != "serve":
            raise SystemExit("--bisect tile needs --program serve")
        ladder = sorted(
            int(v) for v in str(args.tile_candidates).split(",")
            if v.strip()
        )
    else:
        ladder, b = [], 1
        while b <= args.max_bucket:
            ladder.append(b)
            b *= 2
    candidates = []
    lo, hi = 0, len(ladder) - 1
    best = None
    first_bad = None
    while lo <= hi:
        mid = (lo + hi) // 2
        val = ladder[mid]
        px = val if args.bisect == "px" else args.size
        bucket = val if args.bisect == "bucket" else args.bucket
        tile = val if args.bisect == "tile" else None
        try:
            summary = _predict(args, px, bucket, tile=tile)
            peak = summary["peak_bytes"] if summary else None
        except Exception as e:  # noqa: BLE001 — a compile that OOMs IS
            if not is_oom_error(e):  # the infeasibility verdict
                raise
            summary, peak = None, None
        verdict = feasibility(peak, limit, args.fit_margin)
        fits = bool(verdict["fits"]) if peak is not None else False
        entry = {args.bisect: val, **verdict, "fits": fits}
        if summary and "tile_peak_bytes" in summary:
            entry["tile_peak_bytes"] = summary["tile_peak_bytes"]
            entry["head_peak_bytes"] = summary["head_peak_bytes"]
        candidates.append(entry)
        if fits:
            best = val
            lo = mid + 1
        else:
            first_bad = val
            hi = mid - 1
    candidates.sort(key=lambda c: c[args.bisect])
    return {
        "axis": args.bisect,
        "max_feasible": best,
        "first_infeasible": first_bad,
        "candidates": candidates,
    }


def _compile_mode(args) -> int:
    _setup_backend()
    from mpi4dl_tpu.telemetry.memory import device_memory_limit

    limit = _resolve_limit(args, device_memory_limit())
    config = {
        "program": args.program, "model": args.model, "size": args.size,
        "dtype": args.dtype,
    }
    if args.program == "serve":
        config["bucket"] = args.bucket
        if args.tile is not None or args.bisect == "tile":
            config["tile_bucket"] = args.tile_bucket
        if args.tile is not None:
            config["tile"] = args.tile
    else:
        config.update(batch=args.batch, remat=args.remat, dp=args.dp,
                      spatial_parts=args.spatial_parts)

    if args.bisect:
        if not limit:
            print("--bisect needs --limit-bytes/--limit-gb (or a device "
                  "that reports one)", file=sys.stderr)
            return 2
        bisect = _bisect(args, limit)
        plan = {
            "mode": "compile", "config": config, "limit_bytes": limit,
            "fit_margin": args.fit_margin, "bisect": bisect,
            "entries": [
                {"key": f"{args.bisect}={c[args.bisect]}", **{
                    k: c[k] for k in (
                        "peak_bytes", "limit_bytes", "fits",
                        "headroom_bytes", "headroom_ratio",
                    )
                }}
                for c in bisect["candidates"]
            ],
            "ok": bisect["max_feasible"] is not None,
        }
        _render(plan, args)
        print(
            f"max feasible {args.bisect}: {bisect['max_feasible']}"
            + (f" (first infeasible: {bisect['first_infeasible']})"
               if bisect["first_infeasible"] is not None else "")
        )
        return 0 if plan["ok"] else 1

    tile = args.tile if args.program == "serve" else None
    summary = _predict(args, args.size, args.bucket, tile=tile)
    peak = summary["peak_bytes"] if summary else None
    verdict = feasibility(peak, limit, args.fit_margin)
    key = (
        f"{args.program}_{args.model}_{args.size}px"
        + (f"_tile{tile}" if tile is not None else "")
        + (f"_b{args.bucket}" if args.program == "serve" and tile is None
           else "" if args.program == "serve"
           else f"_bs{args.batch}_{args.remat}")
    )
    plan = {
        "mode": "compile", "config": config, "limit_bytes": limit,
        "fit_margin": args.fit_margin, "predicted": summary,
        "entries": [{"key": key, **verdict}],
        "ok": verdict["fits"] is not False,
    }
    _render(plan, args)
    return 0 if plan["ok"] else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.program is None:
        # Artifact mode: pure JSON over committed peaks — no jax import
        # anywhere on this path (dispatched pre-backend, like
        # bench-history).
        return _artifact_mode(args)
    return _compile_mode(args)


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
