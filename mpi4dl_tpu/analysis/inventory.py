"""Typed collective inventory + start→done overlap measurement.

Two views over a parsed :class:`~mpi4dl_tpu.analysis.hlo.HloModule`:

- :func:`collective_inventory` — per-class def counts (``all-reduce`` and
  ``all-reduce-start`` are one class; ``-done`` ops and operand *uses* are
  never counted). Exactly the semantics the hand-pinned regression test
  used, now shared.
- :func:`collective_records` — one record per collective def with
  bytes-moved and, for async (``-start``/``-done``) pairs in a scheduled
  module, the schedule distance and how much *compute* XLA actually placed
  inside the communication window. Zero compute between start and done is
  the statically-visible signature of lost overlap (T3, arXiv:2401.16677).
"""

from __future__ import annotations

import dataclasses

from mpi4dl_tpu.analysis.hlo import HloModule, parse_hlo_text

# Collective classes tracked by the inventory (base opcodes; ``-start``
# variants fold into the base class).
COLLECTIVE_OPS = (
    "collective-permute",
    "all-gather",
    "all-reduce",
    "all-to-all",
    "reduce-scatter",
    "collective-broadcast",
    "ragged-all-to-all",
)

# Opcodes that represent real work for overlap purposes. In optimized HLO
# nearly all elementwise/dot/conv work lives inside ``fusion`` ops;
# ``custom-call`` covers Pallas kernels and library calls.
COMPUTE_OPCODES = frozenset({
    "fusion", "convolution", "dot", "custom-call", "while", "conditional",
    "reduce", "reduce-window", "select-and-scatter", "scatter", "sort",
    "cholesky", "triangular-solve", "fft",
})


def _as_module(hlo) -> HloModule:
    return hlo if isinstance(hlo, HloModule) else parse_hlo_text(str(hlo))


def base_opcode(opcode: str) -> str | None:
    """Collective class of an opcode: ``all-reduce-start`` → ``all-reduce``;
    ``-done`` ops and non-collectives → None."""
    if opcode.endswith("-done"):
        return None
    stem = opcode[: -len("-start")] if opcode.endswith("-start") else opcode
    return stem if stem in COLLECTIVE_OPS else None


def collective_inventory(hlo, ops=None) -> dict:
    """Def count per collective class over the whole module (all
    computations — fused/while bodies included, like the regex pin the
    tier-1 inventory test originally hand-rolled)."""
    module = _as_module(hlo)
    ops = tuple(ops) if ops is not None else COLLECTIVE_OPS
    counts = {op: 0 for op in ops}
    for instr in module.all_instructions():
        op = base_opcode(instr.opcode)
        if op in counts:
            counts[op] += 1
    return counts


@dataclasses.dataclass
class CollectiveRecord:
    """One collective def. ``bytes_moved`` is the payload byte size (the
    done-op result for async pairs, the result shape otherwise — tuple
    results of sync ops count each element once)."""

    name: str
    opcode: str  # base class, e.g. "all-reduce"
    computation: str
    bytes_moved: int
    is_async: bool = False
    done_name: str | None = None
    # Async pairs only (scheduled modules): instruction count strictly
    # between start and done, and how many of those are compute ops.
    distance: int | None = None
    compute_between: int | None = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def collective_records(hlo) -> list[CollectiveRecord]:
    module = _as_module(hlo)
    records: list[CollectiveRecord] = []
    for comp in module.computations.values():
        instrs = comp.instructions
        for instr in instrs:
            op = base_opcode(instr.opcode)
            if op is None:
                continue
            if instr.opcode.endswith("-start"):
                done = None
                for cand in instrs[instr.index + 1 :]:
                    if (
                        cand.opcode == op + "-done"
                        and instr.name in cand.operands
                    ):
                        done = cand
                        break
                if done is not None:
                    between = instrs[instr.index + 1 : done.index]
                    records.append(CollectiveRecord(
                        name=instr.name,
                        opcode=op,
                        computation=comp.name,
                        bytes_moved=done.shape.byte_size(),
                        is_async=True,
                        done_name=done.name,
                        distance=len(between),
                        compute_between=sum(
                            1 for i in between if i.opcode in COMPUTE_OPCODES
                        ),
                    ))
                    continue
                # Unpaired start (done in another computation / truncated
                # dump): record as async with unknown distance.
                records.append(CollectiveRecord(
                    name=instr.name,
                    opcode=op,
                    computation=comp.name,
                    bytes_moved=instr.shape.byte_size(),
                    is_async=True,
                ))
                continue
            records.append(CollectiveRecord(
                name=instr.name,
                opcode=op,
                computation=comp.name,
                bytes_moved=instr.shape.byte_size(),
            ))
    return records


def overlap_summary(records) -> dict:
    """Aggregate overlap/bytes metrics for reports and BENCH entries."""
    bytes_by_op: dict[str, int] = {}
    for r in records:
        bytes_by_op[r.opcode] = bytes_by_op.get(r.opcode, 0) + r.bytes_moved
    async_pairs = [r for r in records if r.is_async and r.distance is not None]
    zero = [r.name for r in async_pairs if r.compute_between == 0]
    return {
        "n_collectives": len(records),
        "total_bytes": sum(r.bytes_moved for r in records),
        "bytes_by_op": bytes_by_op,
        "async_pairs": len(async_pairs),
        "zero_overlap": zero,
        "min_compute_between": min(
            (r.compute_between for r in async_pairs), default=None
        ),
        "mean_distance": (
            sum(r.distance for r in async_pairs) / len(async_pairs)
            if async_pairs else None
        ),
    }
