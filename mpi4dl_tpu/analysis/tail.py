"""``python -m mpi4dl_tpu.analyze tail`` — why was this request slow?

The answer lives in three artifacts no single tool joined before:

- **histogram exemplars** — ``metrics`` events (``/snapshotz`` payloads,
  flight dumps, bench lines) whose histogram series carry per-bucket
  ``{trace_id, value, ts}`` exemplars: the scrape-side pointer from "the
  p99 bucket" to a concrete request;
- **span segments** — ``span`` events from every process that touched
  the request (client, router, replica engine), joined by trace id;
- **tail.sample events** — the engine-side slow-request captures
  (:mod:`mpi4dl_tpu.telemetry.tail`): queue depth at admission,
  bucket/batch/pad-waste, dispatch seq, watchdog state, attribution.

This module is the join. Pure JSON — no jax, no devices, dispatched in
:mod:`mpi4dl_tpu.analysis.cli` before any backend setup, so it runs on
logs copied off a dead machine.

``--trace-id ID`` renders one request's cross-process lifetime: every
segment's phases with durations, each phase compared against the log
window's p50 for that phase (the "vs baseline" column), the dominant
phase named (largest share of the slowest segment's end-to-end time),
plus whatever tail.sample / exemplar context exists for the id. A
fleet-requeued request renders end to end: client segment, the router's
per-attempt dispatch spans (dead replica included), the survivor's
engine spans.

``--top N`` lists the N worst requests in the logs by end-to-end
latency with their dominant phase — the "which requests made p99
regress" table. ``--list-exemplars`` dumps the exemplar index (metric,
bucket, trace id) so an operator can go from a scrape to an id without
scripting.
"""

from __future__ import annotations

import json
import sys

from mpi4dl_tpu.profiling import percentiles


def collect(paths) -> "list[dict]":
    from mpi4dl_tpu.telemetry.federation import _collect_events

    return _collect_events(paths)


def exemplar_index(events) -> "dict[str, list[dict]]":
    """trace_id → exemplar sightings across every ``metrics`` event:
    ``{"metric", "labels", "le", "value", "ts"}``, newest metrics event
    winning per (metric, labels, le) slot."""
    slots: "dict[tuple, dict]" = {}
    for ev in events:
        if ev.get("kind") != "metrics":
            continue
        for name, m in ev.get("metrics", {}).items():
            if m.get("type") != "histogram":
                continue
            for s in m.get("series", ()):
                for le, ex in (s.get("exemplars") or {}).items():
                    key = (name, tuple(sorted(s["labels"].items())), le)
                    have = slots.get(key)
                    if have is None or ex["ts"] >= have["ts"]:
                        slots[key] = {
                            "metric": name,
                            "labels": dict(s["labels"]),
                            "le": le,
                            "trace_id": ex["trace_id"],
                            "value": ex["value"],
                            "ts": ex["ts"],
                        }
    out: "dict[str, list[dict]]" = {}
    for rec in slots.values():
        out.setdefault(rec["trace_id"], []).append(rec)
    for recs in out.values():
        recs.sort(key=lambda r: (r["metric"], r["le"]))
    return out


def tail_samples(events) -> "dict[str, list[dict]]":
    """trace_id → ``tail.sample`` events (a trace can trip more than
    once across processes)."""
    out: "dict[str, list[dict]]" = {}
    for ev in events:
        if ev.get("kind") == "event" and ev.get("name") == "tail.sample":
            tid = ev.get("attrs", {}).get("trace_id")
            if tid:
                out.setdefault(tid, []).append(ev)
    return out


def phase_baselines(events) -> "dict[tuple, dict]":
    """(event name, phase) → ``{"p50", "n"}`` across every span event in
    the logs — the window each slow request is compared against. Keyed
    by the emitting event name too: the router's ``route_queue`` and the
    engine's ``queue_wait`` are different populations."""
    vals: "dict[tuple, list[float]]" = {}
    for ev in events:
        if ev.get("kind") != "span":
            continue
        for s in ev["spans"]:
            vals.setdefault((ev["name"], s["phase"]), []).append(
                s["duration_s"]
            )
    return {
        key: {"p50": percentiles(v, (50,))["p50"], "n": len(v)}
        for key, v in vals.items()
    }


def _segment_e2e(ev: dict) -> float:
    attrs = ev.get("attrs", {})
    if isinstance(attrs.get("e2e_latency_s"), (int, float)):
        return float(attrs["e2e_latency_s"])
    return ev["spans"][-1]["end_s"] - ev["spans"][0]["start_s"]


def trace_report(events, trace_id: str) -> "dict | None":
    """The joined forensics record for one trace id (None when the logs
    hold no span segment for it)."""
    from mpi4dl_tpu.telemetry.spans import group_spans_by_trace

    groups = group_spans_by_trace(events)
    segments = groups.get(trace_id)
    if not segments:
        return None
    baselines = phase_baselines(events)
    seg_out = []
    # The request's end-to-end time is the slowest segment's span (the
    # outermost observer: the client when present, else the router, else
    # the engine); its phases are the breakdown the dominant phase is
    # named from.
    slowest = max(segments, key=_segment_e2e)
    for ev in segments:
        phases = []
        for s in ev["spans"]:
            base = baselines.get((ev["name"], s["phase"]), {})
            p50 = base.get("p50")
            phases.append({
                "phase": s["phase"],
                "duration_s": s["duration_s"],
                "window_p50_s": p50,
                "vs_p50": (
                    s["duration_s"] / p50 if p50 else None
                ),
            })
        seg_out.append({
            "name": ev["name"],
            "pid": ev.get("attrs", {}).get("pid"),
            "role": ev.get("attrs", {}).get("role"),
            "attrs": {
                k: v for k, v in ev.get("attrs", {}).items()
                if k not in ("pid", "role")
            },
            "e2e_s": _segment_e2e(ev),
            "phases": phases,
        })
    dominant = max(
        slowest["spans"], key=lambda s: s["duration_s"]
    )["phase"]
    e2e = _segment_e2e(slowest)
    return {
        "trace_id": trace_id,
        "e2e_s": e2e,
        "segments": seg_out,
        "processes": sorted({
            s["pid"] for s in seg_out if s["pid"] is not None
        }),
        "dominant_phase": dominant,
        "dominant_share": (
            max(s["duration_s"] for s in slowest["spans"]) / e2e
            if e2e > 0 else None
        ),
        "tail_samples": tail_samples(events).get(trace_id, []),
        "exemplars": exemplar_index(events).get(trace_id, []),
    }


def worst_traces(events, n: int = 10) -> "list[dict]":
    """The ``--top`` table: traces ranked by end-to-end latency (slowest
    segment per trace), with the dominant phase named per row."""
    from mpi4dl_tpu.telemetry.spans import group_spans_by_trace

    groups = group_spans_by_trace(events)
    samples = tail_samples(events)
    exemplars = exemplar_index(events)
    rows = []
    for tid, segments in groups.items():
        slowest = max(segments, key=_segment_e2e)
        e2e = _segment_e2e(slowest)
        dominant = max(
            slowest["spans"], key=lambda s: s["duration_s"]
        )
        # The request's SLO class: whichever segment names one (the
        # engine and router both stamp it) — a straggler row names the
        # class as well as the phase.
        slo_class = next(
            (
                ev.get("attrs", {}).get("slo_class")
                for ev in segments
                if ev.get("attrs", {}).get("slo_class")
            ),
            None,
        )
        # Likewise the tenant (multi-tenant fleets stamp it on engine
        # and router segments): a noisy-neighbor row names WHO was slow.
        tenant = next(
            (
                ev.get("attrs", {}).get("tenant")
                for ev in segments
                if ev.get("attrs", {}).get("tenant")
            ),
            None,
        )
        rows.append({
            "trace_id": tid,
            "e2e_s": e2e,
            "dominant_phase": dominant["phase"],
            "dominant_s": dominant["duration_s"],
            "segments": len(segments),
            "outcome": slowest.get("attrs", {}).get("outcome"),
            "slo_class": slo_class,
            "tenant": tenant,
            "tail_sampled": tid in samples,
            "exemplar": tid in exemplars,
        })
    rows.sort(key=lambda r: r["e2e_s"], reverse=True)
    return rows[: int(n)]


# -- rendering ----------------------------------------------------------------


def _fmt_ms(v: "float | None") -> str:
    return "-" if v is None else f"{v * 1e3:.3f}ms"


def _print_trace(rep: dict) -> None:
    print(
        f"trace {rep['trace_id']}: e2e {_fmt_ms(rep['e2e_s'])} across "
        f"{len(rep['segments'])} segment(s), "
        f"{len(rep['processes'])} process(es)"
    )
    print(
        f"  dominant phase: {rep['dominant_phase']} "
        f"({rep['dominant_share']:.0%} of e2e)"
        if rep["dominant_share"] is not None
        else f"  dominant phase: {rep['dominant_phase']}"
    )
    for seg in rep["segments"]:
        role = f" role={seg['role']}" if seg.get("role") else ""
        out = seg["attrs"].get("outcome")
        out = f" outcome={out}" if out else ""
        print(
            f"  {seg['name']} pid={seg['pid']}{role}{out} "
            f"e2e={_fmt_ms(seg['e2e_s'])}"
        )
        for p in seg["phases"]:
            vs = (
                f"  ({p['vs_p50']:.1f}x window p50 {_fmt_ms(p['window_p50_s'])})"
                if p["vs_p50"] is not None else ""
            )
            print(f"    {p['phase']:<16} {_fmt_ms(p['duration_s'])}{vs}")
    for ts in rep["tail_samples"]:
        a = ts["attrs"]
        print(
            "  tail.sample: "
            f"slo_class={a.get('slo_class')} "
            f"tenant={a.get('tenant')} "
            f"threshold={_fmt_ms(a.get('threshold_s'))} "
            f"queue_depth_at_submit={a.get('queue_depth_at_submit')} "
            f"bucket={a.get('bucket')} batch_size={a.get('batch_size')} "
            f"dispatch_seq={a.get('dispatch_seq')} "
            f"pad_waste={a.get('pad_waste_ratio')}"
        )
    for ex in rep["exemplars"]:
        labels = (
            "{" + ",".join(f"{k}={v}" for k, v in ex["labels"].items()) + "}"
            if ex["labels"] else ""
        )
        print(
            f"  exemplar: {ex['metric']}{labels} le={ex['le']} "
            f"value={_fmt_ms(ex['value'])}"
        )


def main(argv=None) -> int:
    """``python -m mpi4dl_tpu.analyze tail LOGS... [--trace-id ID]
    [--top N] [--list-exemplars] [--json]`` — see the module doc."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze tail",
        description="Join exemplars, span segments, and tail.sample "
                    "events to explain slow requests per trace id",
    )
    p.add_argument("logs", nargs="+",
                   help="JSONL telemetry logs / flight dumps / snapshotz "
                        "captures, or directories of them")
    p.add_argument("--trace-id", default=None,
                   help="render one request's cross-process forensics")
    p.add_argument("--top", type=int, default=None, metavar="N",
                   help="table of the N slowest requests in the logs")
    p.add_argument("--list-exemplars", action="store_true",
                   help="dump the exemplar index (metric/bucket -> "
                        "trace id) instead of a report")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit machine-readable JSON instead of text")
    args = p.parse_args(argv)

    events = collect(args.logs)
    if args.list_exemplars:
        idx = exemplar_index(events)
        if args.as_json:
            print(json.dumps(idx))
        else:
            for tid in sorted(idx):
                for ex in idx[tid]:
                    print(
                        f"{ex['metric']} le={ex['le']} "
                        f"value={ex['value']:.6f} {tid}"
                    )
            print(f"# {len(idx)} exemplar trace id(s)", file=sys.stderr)
        return 0 if idx else 1

    if args.trace_id is not None:
        rep = trace_report(events, args.trace_id)
        if rep is None:
            print(
                f"tail: no span segments for trace id {args.trace_id!r} "
                "in the given logs",
                file=sys.stderr,
            )
            return 1
        if args.as_json:
            print(json.dumps(rep))
        else:
            _print_trace(rep)
        return 0

    n = args.top if args.top is not None else 10
    rows = worst_traces(events, n)
    if not rows:
        print("tail: no span events in the given logs", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(rows))
        return 0
    print(
        f"{'e2e':>12} {'dominant phase':<16} {'dom time':>12} "
        f"{'class':<10} {'tenant':<10} {'seg':>3} {'tail?':>5} "
        f"{'exemplar?':>9}  trace_id"
    )
    for r in rows:
        print(
            f"{_fmt_ms(r['e2e_s']):>12} {r['dominant_phase']:<16} "
            f"{_fmt_ms(r['dominant_s']):>12} "
            f"{(r['slo_class'] or '-'):<10} "
            f"{(r['tenant'] or '-'):<10} {r['segments']:>3} "
            f"{'yes' if r['tail_sampled'] else '-':>5} "
            f"{'yes' if r['exemplar'] else '-':>9}  {r['trace_id']}"
        )
    print(f"# {len(rows)} trace(s) shown", file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
