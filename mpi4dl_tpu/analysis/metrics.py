"""hlolint verdicts as telemetry gauges.

A lint report is a point-in-time fact about one compiled program, so every
series is a gauge labeled by ``program`` (the report config's ``program``
key when present — e.g. ``serve_predict`` — else the HLO module name).
Publishing per-severity finding counts explicitly at zero keeps a
previously-red program visibly green instead of silently absent.
"""

from __future__ import annotations

from mpi4dl_tpu import telemetry

SEVERITIES = ("error", "warn", "info")


def publish_report(report, registry) -> None:
    """Publish one :class:`mpi4dl_tpu.analysis.report.Report` into
    ``registry`` under the cataloged ``hlolint_*`` gauges."""
    program = str(
        report.config.get("program") or report.module_name or "unknown"
    )
    telemetry.declare(registry, "hlolint_ok").set(
        1.0 if report.ok else 0.0, program=program
    )
    counts = dict.fromkeys(SEVERITIES, 0)
    for f in report.findings:
        counts[f["severity"]] = counts.get(f["severity"], 0) + 1
    findings = telemetry.declare(registry, "hlolint_findings")
    for sev, n in counts.items():
        findings.set(n, program=program, severity=sev)
    telemetry.declare(registry, "hlolint_collectives").set(
        report.overlap["n_collectives"], program=program
    )
    telemetry.declare(registry, "hlolint_collective_bytes").set(
        report.overlap["total_bytes"], program=program
    )
    peak = (report.memory or {}).get("peak_bytes")
    telemetry.declare(registry, "hlolint_peak_hbm_bytes").set(
        peak if peak is not None else 0, program=program
    )
