"""SP 2×2 halo/compute-overlap A/B harness.

``python -m mpi4dl_tpu.analyze sp-overlap`` runs the spatially-partitioned
(2×2 square tiles) ResNet train step TWICE — once with the monolithic
spatial conv (one VALID conv over the halo-extended tile) and once with
the decomposed impl (``MPI4DL_TPU_CONV_OVERLAP=decomposed``: interior
conv with no halo dependency + boundary-strip convs,
:func:`mpi4dl_tpu.ops.layers.overlap_decompose`) — and measures, per arm:

- the **measured** ``trace_overlap_ratio`` of a live XProf capture
  (:meth:`Trainer.capture_trace_attribution`): the fraction of
  collective-permute time hidden behind concurrent compute, the number
  the decomposition exists to raise (T3 arXiv:2401.16677 / FLUX
  arXiv:2406.06858);
- the mean annotated step wall time (``step_time_s``);
- the **static** hlolint verdict with partition-math expectations
  (tile grid + counted halo shifts — the halo-window rule must hold for
  the decomposed program too, since the permute inventory is unchanged:
  ``halo_exchange`` runs exactly once per windowed op either way);
- the ``trace-overlap-crosscheck`` findings joining the two.

Run from bench.py as a subprocess (the ``sp2x2_overlap`` extra) so the
4-device CPU mesh exists regardless of what backend the bench headline
initialized, and callable in-process (:func:`run_overlap_ab`) from tests
that already sit on the 8-virtual-CPU mesh.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys


@contextlib.contextmanager
def _conv_overlap_env(impl: str):
    """Set MPI4DL_TPU_CONV_OVERLAP for the duration of one arm's tracing
    (the selector is read at trace time, per spatial windowed op)."""
    prev = os.environ.get("MPI4DL_TPU_CONV_OVERLAP")
    os.environ["MPI4DL_TPU_CONV_OVERLAP"] = impl
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("MPI4DL_TPU_CONV_OVERLAP", None)
        else:
            os.environ["MPI4DL_TPU_CONV_OVERLAP"] = prev


def _build_arm(impl, size, batch, depth, spatial_cells, warmup):
    """One arm's context: the SP 2×2 trainer built (and warmed) under
    ``impl``, plus the static lint of its compiled step against the
    partition-math expectations."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.analysis import analyze_compiled
    from mpi4dl_tpu.analysis.expectations import compose, spatial_delta
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.train import Trainer

    with _conv_overlap_env(impl):
        cfg = ParallelConfig(
            batch_size=batch, split_size=1, spatial_size=1,
            num_spatial_parts=(4,), slice_method="square",
            image_size=size, data_parallel=1,
        )
        plain = get_resnet_v1(depth=depth)
        n_sp = min(spatial_cells, len(plain) - 1)
        cells = get_resnet_v1(depth=depth, spatial_cells=n_sp)
        trainer = Trainer(
            cells, num_spatial_cells=n_sp, config=cfg, plain_cells=plain
        )
        x_shape = (batch, size, size, 3)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(x_shape), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)
        xs, ys = trainer.shard_batch(x, y)
        state = trainer.init(jax.random.PRNGKey(0), x_shape)

        halo_shifts = trainer.halo_shift_count(state.params, x_shape)
        compiled = trainer._jit_step.lower(state, xs, ys).compile()
        report = analyze_compiled(
            compiled,
            expected=compose(spatial_delta(cfg.tile_shape, halo_shifts)),
            platform=jax.devices()[0].platform,
            config={"program": f"sp2x2_train_{impl}", "conv_overlap": impl},
        )
        for _ in range(warmup):
            state, metrics = trainer.train_step(state, xs, ys)
        float(metrics["loss"])  # force execution before any capture
    return {
        "impl": impl, "trainer": trainer, "state": state,
        "xs": xs, "ys": ys, "halo_shifts": halo_shifts, "report": report,
    }


def run_overlap_ab(
    size: int = 32,
    batch: int = 4,
    depth: int = 8,
    spatial_cells: int = 3,
    steps: int = 3,
    warmup: int = 1,
    trials: int = 1,
    arms=("monolithic", "decomposed"),
    registry=None,
) -> dict:
    """Both arms + the A/B verdict. ``trials`` captures per arm run
    INTERLEAVED (mono, dec, mono, dec, ...) so slow host drift hits both
    arms alike, and the arm ratio pools overlapped/total collective time
    across its captures rather than averaging per-capture ratios.
    Requires ≥4 devices (the 2×2 tile mesh); raises the underlying
    config error otherwise."""
    from mpi4dl_tpu.analysis.trace import crosscheck_overlap

    out = {
        "config": {
            "size": size, "batch": batch, "depth": depth,
            "spatial_cells": spatial_cells, "steps": steps,
            "trials": trials, "mesh": "2x2 square tiles",
        },
        "arms": {},
    }
    ctxs = {
        impl: _build_arm(impl, size, batch, depth, spatial_cells, warmup)
        for impl in arms
    }
    pooled = {
        impl: {"total_s": 0.0, "overlapped_s": 0.0, "per_trial": [],
               "walls": [], "coll": [], "n_steps": 0, "crosscheck": None}
        for impl in arms
    }
    for _ in range(max(1, int(trials))):
        for impl in arms:
            import shutil
            import tempfile

            ctx, acc = ctxs[impl], pooled[impl]
            logdir = tempfile.mkdtemp(prefix=f"mpi4dl-sp-overlap-{impl}-")
            try:
                with _conv_overlap_env(impl):
                    ctx["state"], summary = (
                        ctx["trainer"].capture_trace_attribution(
                            ctx["state"], ctx["xs"], ctx["ys"], steps=steps,
                            logdir=logdir, registry=registry,
                            program=f"sp2x2_{impl}",
                        )
                    )
            finally:
                shutil.rmtree(logdir, ignore_errors=True)
            coll = summary["collective"]
            acc["total_s"] += coll["total_s"]
            acc["overlapped_s"] += coll["overlapped_s"]
            acc["per_trial"].append(coll["overlap_ratio"])
            acc["n_steps"] += summary["n_steps"]
            mean = summary["per_step_mean"] or {}
            if mean.get("wall_s") is not None:
                acc["walls"].append(mean["wall_s"])
            if mean.get("collective_s") is not None:
                acc["coll"].append(mean["collective_s"])
            if acc["crosscheck"] is None:
                acc["crosscheck"] = [
                    f.as_dict()
                    for f in crosscheck_overlap(ctx["report"], summary)
                ]
    for impl in arms:
        ctx, acc = ctxs[impl], pooled[impl]
        report = ctx["report"]
        total = acc["total_s"]
        ratio = acc["overlapped_s"] / total if total > 0 else None
        out["arms"][impl] = {
            "conv_impl": impl,
            "trace_overlap_ratio": ratio,
            "overlap_ratio_per_trial": acc["per_trial"],
            "collective_s": (
                sum(acc["coll"]) / len(acc["coll"]) if acc["coll"] else None
            ),
            "step_time_s": (
                round(sum(acc["walls"]) / len(acc["walls"]), 6)
                if acc["walls"] else None
            ),
            "n_steps": acc["n_steps"],
            "halo_shifts": ctx["halo_shifts"],
            "permutes": report.inventory.get("collective-permute", 0),
            "hlolint_errors": [
                f for f in report.findings if f["severity"] == "error"
            ],
            "crosscheck": acc["crosscheck"] or [],
        }
    mono = out["arms"].get("monolithic")
    dec = out["arms"].get("decomposed")
    if mono and dec:
        out["halo_shifts_equal"] = mono["halo_shifts"] == dec["halo_shifts"]
        rm, rd = mono["trace_overlap_ratio"], dec["trace_overlap_ratio"]
        out["overlap_improved"] = (
            rm is not None and rd is not None and rd > rm
        )
        sm, sd = mono["step_time_s"], dec["step_time_s"]
        out["step_time_speedup"] = (
            round(sm / sd, 4) if sm and sd else None
        )
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze sp-overlap",
        description="SP 2x2 halo/compute overlap A/B: monolithic vs "
                    "decomposed spatial conv, measured + linted",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--spatial-cells", type=int, default=3)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--trials", type=int, default=1,
                   help="captures per arm, interleaved across arms; the "
                        "arm ratio pools collective time over all of them")
    p.add_argument("--arm", action="append", dest="arms", default=None,
                   choices=("monolithic", "decomposed"),
                   help="restrict to one arm (repeatable); default both")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the A/B record here ('-' = stdout)")
    p.add_argument("--require-improvement", action="store_true",
                   help="exit 1 unless the decomposed arm's measured "
                        "overlap ratio strictly beats the monolithic one")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # The 2x2 tile mesh needs virtual devices before backend init —
        # the same 8-device simulation the test suite runs on.
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(8)
    enable_compilation_cache()

    out = run_overlap_ab(
        size=args.size, batch=args.batch, depth=args.depth,
        spatial_cells=args.spatial_cells, steps=args.steps,
        warmup=args.warmup, trials=args.trials,
        arms=tuple(args.arms) if args.arms else ("monolithic", "decomposed"),
    )
    for impl, arm in out["arms"].items():
        ratio = arm["trace_overlap_ratio"]
        print(
            f"# {impl}: overlap_ratio="
            f"{ratio if ratio is None else round(ratio, 4)} "
            f"step={arm['step_time_s']}s permutes={arm['permutes']} "
            f"halo_shifts={arm['halo_shifts']} "
            f"lint_errors={len(arm['hlolint_errors'])} "
            f"crosscheck={len(arm['crosscheck'])}",
            file=sys.stderr, flush=True,
        )
    payload = json.dumps(out)
    if args.json_out == "-" or args.json_out is None:
        print(payload, flush=True)
    else:
        with open(args.json_out, "w") as f:
            f.write(payload + "\n")
    rc = 0
    if any(a["hlolint_errors"] for a in out["arms"].values()):
        rc = 1
    if args.require_improvement and not out.get("overlap_improved"):
        rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
