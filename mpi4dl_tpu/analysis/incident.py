"""``python -m mpi4dl_tpu.analyze incident LOGS... [--incident-id ID]
[--json|--md]`` — reconstruct incidents and their postmortems from logs.

The live incident engine (:mod:`mpi4dl_tpu.telemetry.incident`) serves
open/recent incidents on ``/incidentz``; this is the offline half for
when the fleet is gone and only the telemetry directory survives.
``incident.open/update/close`` lifecycle events rebuild the incident
records (:func:`reconstruct_incidents`), and the SAME pure builders the
live manager uses (:func:`build_postmortem` → timeline, first cause,
blast radius, linked flight dumps) recompute the postmortem over the
same files — so the offline timeline matches the live ``/incidentz``
one event for event, which the tier-1 drill asserts.

Pure JSON end to end: no jax, no devices — dispatches before any
backend setup (tests/test_artifact_dispatch.py pins this) and runs on
logs copied off a dead machine. ``--md`` renders the human postmortem
the way an on-call hand-off would want it: summary table, named first
cause, blast radius, linked dumps, and the causally ordered timeline
(cross-pid span anchoring means two processes' wall clocks can be
skewed and the order still holds).
"""

from __future__ import annotations

import json
import sys

from mpi4dl_tpu.telemetry.incident import (
    build_postmortem,
    collect_events,
    reconstruct_incidents,
)


def _fmt_ts(ts) -> str:
    return f"{ts:.6f}" if isinstance(ts, (int, float)) else "-"


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


def _timeline_detail(e: dict) -> str:
    """One compact human line per timeline entry."""
    a = e.get("attrs", {})
    name = e["name"]
    if name == "alert.transition":
        out = f"{a.get('alert')} {a.get('from')}→{a.get('to')}"
        if a.get("replica"):
            out += f" replica={a['replica']}"
        return out
    if name == "chaos.injected":
        return f"{a.get('op')} pid={a.get('pid')}"
    if name == "elastic.restart":
        return " ".join(
            str(a[k]) for k in ("replica", "reason") if a.get(k)
        ) or "restart"
    if name == "flight.dump":
        out = f"reason={a.get('reason')} events={a.get('events')}"
        if a.get("incident"):
            out += f" incident={a['incident']}"
        return out
    if name == "tail.sample":
        return f"trace={a.get('trace_id')} e2e={_fmt_s(a.get('e2e_s'))}"
    if name == "canary.failure":
        return str(a.get("check") or a.get("reason") or "")
    if name == "oom.report":
        return str(a.get("program") or "")
    if name == "journal.replay":
        return str(a.get("outcome") or "")
    if e.get("kind") == "span":
        return (
            f"trace={e.get('trace_id')} phases={len(e.get('phases', ()))} "
            f"dur={_fmt_s(e.get('duration_s'))}"
        )
    return json.dumps(a, sort_keys=True)[:120]


def _render_blast(blast: dict) -> "list[str]":
    burned = blast.get("slo_budget_burned")
    return [
        f"- affected traces: {blast.get('n_traces')}"
        + (f" (e.g. {blast['trace_ids'][0]})" if blast.get("trace_ids")
           else ""),
        f"- tenants: {', '.join(blast.get('tenants') or ()) or '-'}",
        f"- requeues in window: {blast.get('requeues')}",
        f"- sheds in window: {blast.get('sheds')}",
        "- SLO budget burned: "
        + (", ".join(f"{k or 'fleet'}={v:.6f}" for k, v in burned.items())
           if burned else "-"),
    ]


def render_markdown(pm: dict) -> str:
    """The human postmortem for one incident, from its machine-readable
    artifact — the hand-off document, generated not written."""
    inc = pm["incident"]
    cause = pm.get("first_cause")
    lines = [
        f"# Incident {inc['id']} — {inc['state']}",
        "",
        "| field | value |",
        "|---|---|",
        f"| opened | {_fmt_ts(inc.get('opened_ts'))} |",
        f"| closed | {_fmt_ts(inc.get('closed_ts'))} |",
        f"| opened by | `{inc.get('opened_by')}` |",
        f"| members | {', '.join('`%s`' % m for m in sorted(inc.get('members') or ()))} |",
        f"| MTTA | {_fmt_s(inc.get('mtta_s'))} |",
        f"| MTTR | {_fmt_s(inc.get('mttr_s'))} |",
        f"| lookback | {_fmt_s(inc.get('lookback_s'))} |",
        "",
        "## First cause",
        "",
    ]
    if cause:
        lines.append(
            f"**{cause['label']}** — `{cause['event']}` at "
            f"{_fmt_ts(cause['ts'])} (rule: `{cause['rule']}`)"
        )
    else:
        lines.append("No candidate in the window (rule table exhausted).")
    lines += ["", "## Blast radius", ""]
    lines += _render_blast(pm.get("blast_radius", {}))
    dumps = pm.get("dumps") or []
    if dumps:
        lines += ["", "## Flight dumps in window", ""]
        for d in dumps:
            lines.append(
                f"- {_fmt_ts(d.get('ts'))} reason={d.get('reason')} "
                f"trigger={d.get('trigger')} events={d.get('events')}"
            )
    lines += [
        "",
        "## Timeline",
        "",
        "| t−open | event | detail |",
        "|---|---|---|",
    ]
    t0 = inc.get("opened_ts") or 0.0
    for e in pm.get("timeline", ()):
        lines.append(
            f"| {e['ts'] - t0:+.3f}s | `{e['name']}` | "
            f"{_timeline_detail(e)} |"
        )
    return "\n".join(lines) + "\n"


def _render_text(pm: dict) -> None:
    inc = pm["incident"]
    cause = pm.get("first_cause")
    members = ", ".join(sorted(inc.get("members") or ()))
    print(
        f"incident {inc['id']} [{inc['state']}] opened_by={inc['opened_by']}"
        f" members=[{members}] mtta={_fmt_s(inc.get('mtta_s'))}"
        f" mttr={_fmt_s(inc.get('mttr_s'))}"
    )
    print(
        "  first cause: "
        + (f"{cause['label']} ({cause['event']} @ {_fmt_ts(cause['ts'])})"
           if cause else "none")
    )
    blast = pm.get("blast_radius", {})
    print(
        f"  blast: traces={blast.get('n_traces')} "
        f"tenants={len(blast.get('tenants') or ())} "
        f"requeues={blast.get('requeues')} sheds={blast.get('sheds')}"
    )
    t0 = inc.get("opened_ts") or 0.0
    for e in pm.get("timeline", ()):
        print(
            f"  {e['ts'] - t0:+9.3f}s  {e['name']:<18} "
            f"{_timeline_detail(e)}"
        )


def main(argv=None) -> int:
    """See the module doc."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze incident",
        description="Reconstruct incident timelines + postmortems from "
                    "JSONL telemetry logs (the offline twin of "
                    "/incidentz)",
    )
    p.add_argument("logs", nargs="+",
                   help="JSONL telemetry logs / flight dumps, or "
                        "directories of them (the fleet telemetry dir)")
    p.add_argument("--incident-id", default=None,
                   help="render only this incident")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable postmortems as JSON")
    p.add_argument("--md", action="store_true", dest="as_md",
                   help="render markdown postmortems (the hand-off doc)")
    args = p.parse_args(argv)

    events = collect_events(args.logs)
    records = reconstruct_incidents(events)
    if args.incident_id is not None:
        records = [r for r in records if r["id"] == args.incident_id]
        if not records:
            print(
                f"incident: no incident {args.incident_id!r} in the "
                "given logs",
                file=sys.stderr,
            )
            return 1
    if not records:
        print(
            "incident: no incident.open events in the given logs",
            file=sys.stderr,
        )
        return 1

    postmortems = [build_postmortem(r, events) for r in records]
    if args.as_json:
        print(json.dumps(postmortems))
        return 0
    if args.as_md:
        print("\n".join(render_markdown(pm) for pm in postmortems))
        return 0
    for pm in postmortems:
        _render_text(pm)
    print(f"# {len(postmortems)} incident(s)", file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
