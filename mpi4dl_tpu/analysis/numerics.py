"""``python -m mpi4dl_tpu.analyze numerics`` — cross-predictor canary audit.

The numerics sentinel (telemetry/canary.py) verifies each live engine
against its OWN warm-up reference; this subcommand answers the question
the sentinel cannot: do the repo's three serving forwards — single-chip,
spatially sharded, halo-tiled — still agree with EACH OTHER on the same
canary input under the same weights, at the documented f32 boundaries?

Live mode builds one calibrated spatial ResNet (one set of weights),
derives the SAME deterministic canary batch the engines probe with
(:func:`mpi4dl_tpu.telemetry.canary_example`), runs it through a
:class:`SingleChipPredictor`, a :class:`ShardedPredictor` on a CPU tile
mesh, and a :class:`TiledPredictor`, and gates every pair on max-abs
divergence vs the documented tolerance (max-ulp recorded alongside as
the scale-free view). Per-pair bounds COMPOSE from each predictor's
documented distance to the plain forward — the same numbers the tier-1
equivalence suites pin (tests/test_serve_sharded.py 1e-5,
tests/test_serve_tiled.py 5e-6):

=====================  ==========================================
pair                   atol
=====================  ==========================================
single_chip | sharded  1e-5   (f32 reduction-order boundary)
single_chip | tiled    5e-6   (stitched cross-shape boundary)
sharded | tiled        1.5e-5 (triangle bound: 1e-5 + 5e-6)
=====================  ==========================================

``--artifact REPORT.json`` re-gates committed audit reports (and
summarizes ``canary.failure`` events out of JSONL telemetry logs) with
no jax at all — pure JSON, dispatched in ``analysis/cli.py`` before any
backend setup (pinned by tests/test_artifact_dispatch.py). Exit 1 iff
any pair breaches its bound, either mode.
"""

from __future__ import annotations

import argparse
import json
import sys

# Documented distance-to-plain-forward bound per predictor kind; a
# pair's gate is the triangle bound (sum). single_chip IS the plain
# forward on the serving path, so it contributes zero.
PREDICTOR_ATOL = {
    "single_chip": 0.0,
    "sharded": 1e-5,   # tests/test_serve_sharded.py reduction-order bound
    "tiled": 5e-6,     # tests/test_serve_tiled.py stitched-shape bound
}


def pair_atol(a: str, b: str) -> float:
    """Composed max-abs bound for one predictor pair (triangle over the
    documented per-predictor distances to the plain forward)."""
    try:
        return PREDICTOR_ATOL[a] + PREDICTOR_ATOL[b]
    except KeyError as e:
        raise ValueError(f"unknown predictor kind {e.args[0]!r}; expected "
                         f"one of {sorted(PREDICTOR_ATOL)}") from None


def audit_pairs(outputs: dict) -> "list[dict]":
    """All-pairs divergence table over ``{name: np.ndarray}`` canary
    outputs: max-abs (the gate) + max-ulp (the scale-free view) per
    pair, each against its composed bound. Live-mode only (numpy)."""
    import numpy as np

    from mpi4dl_tpu.telemetry.canary import ulp_diff

    names = sorted(outputs)
    pairs = []
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            atol = pair_atol(a, b)
            xa = np.asarray(outputs[a], np.float32)
            xb = np.asarray(outputs[b], np.float32)
            max_abs = float(np.max(np.abs(xa - xb))) if xa.size else 0.0
            pairs.append({
                "a": a,
                "b": b,
                "max_abs": max_abs,
                "max_ulp": int(np.max(ulp_diff(xa, xb))) if xa.size else 0,
                "atol": atol,
                "ok": bool(max_abs <= atol),
            })
    return pairs


def regate_pairs(pairs) -> "list[dict]":
    """Artifact-mode gate: re-apply each recorded pair's bound to its
    recorded max_abs — the committed report cannot vouch for itself.
    A pair with no usable numbers fails loudly instead of passing."""
    out = []
    for p in pairs or ():
        if not isinstance(p, dict):
            continue
        rec = dict(p)
        max_abs = rec.get("max_abs")
        atol = rec.get("atol")
        if not isinstance(atol, (int, float)):
            a, b = rec.get("a"), rec.get("b")
            try:
                atol = pair_atol(str(a), str(b))
            except ValueError:
                atol = None
            rec["atol"] = atol
        rec["ok"] = bool(
            isinstance(max_abs, (int, float))
            and isinstance(atol, (int, float))
            and max_abs <= atol
        )
        out.append(rec)
    return out


def load_artifacts(paths) -> dict:
    """Classify committed inputs: audit reports (``{"pairs": [...]}``)
    vs JSONL telemetry logs (collect their ``canary.failure`` events)."""
    pairs: "list[dict]" = []
    failures: "list[dict]" = []
    counts = {"reports": 0, "logs": 0}
    for path in paths:
        with open(path) as f:
            text = f.read()
        try:
            doc = json.loads(text)
        except ValueError:
            doc = None
        if isinstance(doc, dict) and isinstance(doc.get("pairs"), list):
            counts["reports"] += 1
            pairs.extend(p for p in doc["pairs"] if isinstance(p, dict))
            continue
        counts["logs"] += 1
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict) and ev.get("name") == "canary.failure":
                failures.append(ev)
    return {"inputs": counts, "pairs": pairs, "failures": failures}


def run_live_audit(size, depth, spatial_cells, mesh, tile, seed) -> dict:
    """Build one calibrated spatial ResNet and push the deterministic
    canary batch through all three predictor kinds on this process's
    CPU mesh. Caller owns backend setup (set_cpu_devices before jax)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import SingleChipPredictor
    from mpi4dl_tpu.serve.sharded import ShardedPredictor, serving_mesh_config
    from mpi4dl_tpu.serve.tiled import TiledPredictor
    from mpi4dl_tpu.telemetry.canary import (
        canary_example,
        exact_digest,
        params_checksum,
        quantized_digest,
    )
    from mpi4dl_tpu.train import Trainer

    plain = get_resnet_v1(
        depth=depth, num_classes=10, pool_kernel=size // 4
    )
    n_sp = min(spatial_cells, len(plain) - 1)
    cells = get_resnet_v1(
        depth=depth, num_classes=10, pool_kernel=size // 4,
        spatial_cells=n_sp,
    )
    rng = np.random.default_rng(seed)
    params = init_cells(
        plain, jax.random.PRNGKey(seed), jnp.zeros((1, size, size, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)]
    stats = collect_batch_stats(plain, params, cal)

    shape = (size, size, 3)
    x = canary_example(shape, np.float32, seed=seed)

    cfg = serving_mesh_config(mesh, size)
    trainer = Trainer(
        cells, num_spatial_cells=n_sp, config=cfg, plain_cells=plain
    )
    predictors = {
        "single_chip": SingleChipPredictor(
            plain, params, stats, shape, jnp.float32
        ),
        "sharded": ShardedPredictor(trainer, params, stats, shape),
        "tiled": TiledPredictor(plain, params, stats, shape, tile or size),
    }

    outputs, per = {}, {}
    for name, pred in predictors.items():
        handle = pred.compile_bucket(1)
        row = np.asarray(pred.run(handle, x[None]))[0]
        outputs[name] = row
        per[name] = {
            "digest": exact_digest(row),
            "qdigest": quantized_digest(row),
            "device": str(pred.limit_device()),
            "program": pred.program,
            "params_checksum": params_checksum(pred.param_tree()),
        }

    pairs = audit_pairs(outputs)
    # One shared weight set is the audit's premise: every predictor's
    # live param-tree checksum must agree before divergence means
    # anything (tiled re-splits the tree; the checksum walks it in the
    # rejoined cell order, so agreement is required, not incidental).
    checksums = {per[n]["params_checksum"] for n in per}
    return {
        "canary": {
            "seed": seed,
            "shape": list(shape),
            "dtype": "float32",
            "digest": exact_digest(x),
        },
        "config": {
            "depth": depth, "spatial_cells": n_sp,
            "mesh": list(mesh), "tile": tile or size,
        },
        "predictors": per,
        "checksums_agree": len(checksums) == 1,
        "pairs": pairs,
        "ok": len(checksums) == 1 and all(p["ok"] for p in pairs),
    }


def _render(pairs, failures=None) -> "list[str]":
    lines = []
    for p in pairs:
        verdict = "ok" if p.get("ok") else "BREACH"
        atol = p.get("atol")
        lines.append(
            f"  {p.get('a')} | {p.get('b')}: max_abs "
            f"{p.get('max_abs'):.3g} vs atol "
            f"{format(atol, 'g') if atol is not None else '?'}"
            f" (max_ulp {p.get('max_ulp', '?')}) {verdict}"
        )
    by_check: "dict[str, int]" = {}
    for ev in failures or ():
        check = str((ev.get("attrs") or {}).get("check", "unknown"))
        by_check[check] = by_check.get(check, 0) + 1
    if by_check:
        kinds = ", ".join(f"{k}={v}" for k, v in sorted(by_check.items()))
        lines.append(f"# canary.failure events: "
                     f"{sum(by_check.values())} ({kinds})")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze numerics",
        description=(
            "Cross-predictor canary equivalence audit: single-chip vs "
            "sharded vs tiled on one weight set, gated at the "
            "documented f32 tolerances."
        ),
    )
    ap.add_argument(
        "--artifact", action="append", default=None, metavar="PATH",
        help="pure-JSON mode: re-gate committed audit report(s) and "
             "summarize canary.failure events from JSONL logs "
             "(repeatable; no jax, no devices)",
    )
    ap.add_argument("--size", type=int, default=16, help="square image px")
    ap.add_argument("--depth", type=int, default=8, help="ResNet-v1 depth")
    ap.add_argument("--spatial-cells", type=int, default=2,
                    help="leading cells sharded spatially")
    ap.add_argument("--mesh", default="2x2",
                    help="sharded tile mesh HxW (CPU-simulated)")
    ap.add_argument("--tile", type=int, default=0,
                    help="tiled-predictor core tile px (0 = image size: "
                         "the degenerate single-window grid)")
    ap.add_argument("--seed", type=int, default=0,
                    help="canary derivation seed (matches the engines')")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full audit report JSON here")
    args = ap.parse_args(argv)

    if args.artifact:
        joined = load_artifacts(args.artifact)
        pairs = regate_pairs(joined["pairs"])
        ok = bool(pairs) and all(p["ok"] for p in pairs)
        n_bad = sum(1 for p in pairs if not p["ok"])
        print(
            f"# numerics[artifact]: {len(pairs)} pair(s) from "
            f"{joined['inputs']['reports']} report(s), {n_bad} breach(es), "
            f"{len(joined['failures'])} canary.failure event(s)"
        )
        for line in _render(pairs, joined["failures"]):
            print(line)
        if args.json_out:
            doc = dict(joined, pairs=pairs, ok=ok)
            with open(args.json_out, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
        if not pairs:
            print("# no audit pairs found in the artifacts",
                  file=sys.stderr)
            return 1
        return 0 if ok else 1

    from mpi4dl_tpu.serve.sharded import parse_mesh
    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    mesh = parse_mesh(args.mesh)
    apply_platform_env()
    import os

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(max(8, mesh[0] * mesh[1]))
    enable_compilation_cache()

    report = run_live_audit(
        args.size, args.depth, args.spatial_cells, mesh,
        args.tile, args.seed,
    )
    print(
        f"# numerics: canary {report['canary']['digest']} through "
        f"{len(report['predictors'])} predictors, "
        f"{'agree' if report['ok'] else 'DIVERGED'}"
    )
    for line in _render(report["pairs"]):
        print(line)
    if not report["checksums_agree"]:
        print("# param checksums disagree across predictors",
              file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":  # pragma: no cover — exercised via analyze
    sys.exit(main())
