"""Static analysis over compiled train-step HLO (``hlolint``).

The multi-chip *performance* of this port is unmeasurable on a one-chip
runtime, but the communication/overlap *structure* of the compiled program
is statically checkable — and the overlap literature (T3, arXiv:2401.16677;
FLUX, arXiv:2406.06858) argues the decisive property (is compute scheduled
between a collective's ``-start`` and ``-done``?) is visible right in the
scheduled HLO. This package turns ``trainer._jit_step.lower(...).compile()``
artifacts into:

- a typed op inventory with shapes and bytes-moved per collective
  (:mod:`mpi4dl_tpu.analysis.inventory`),
- start→done scheduling distances for async collectives (same module),
- a rule engine with severities and JSON reports
  (:mod:`mpi4dl_tpu.analysis.rules`, :mod:`mpi4dl_tpu.analysis.report`),
- peak-memory extraction + committed-baseline regression checks
  (:mod:`mpi4dl_tpu.analysis.memory`),
- a CLI (``python -m mpi4dl_tpu.analyze`` →
  :mod:`mpi4dl_tpu.analysis.cli`),
- the runtime half (:mod:`mpi4dl_tpu.analysis.trace`): XProf Chrome-trace
  parsing into per-step compute/collective/transfer/host-gap device-time
  attribution plus a measured-overlap report that cross-checks the static
  start→done rule against what the runtime actually did
  (:func:`crosscheck_overlap`).

Tier-1 tests lint the real compiled CPU-mesh programs with these rules, so
a stray resharding ``all-to-all``, lost overlap, or a peak-HBM regression
fails in CI before ever paying a TPU run. See ``docs/ANALYSIS.md``.
"""

from mpi4dl_tpu.analysis.costmodel import (  # noqa: F401
    INTERCONNECTS,
    Interconnect,
    collective_seconds,
    crosscheck_cost_model,
    predict_from_report,
    predict_program,
    publish_prediction,
)
from mpi4dl_tpu.analysis.expectations import (  # noqa: F401
    CollectiveDelta,
    compose,
    data_parallel_delta,
    pipeline_delta,
    single_chip_delta,
    spatial_delta,
    spatial_join_delta,
    tiled_delta,
)
from mpi4dl_tpu.analysis.hlo import (  # noqa: F401
    HloComputation,
    HloInstruction,
    HloModule,
    parse_hlo_text,
)
from mpi4dl_tpu.analysis.inventory import (  # noqa: F401
    COLLECTIVE_OPS,
    CollectiveRecord,
    collective_inventory,
    collective_records,
    overlap_summary,
)
from mpi4dl_tpu.analysis.memory import memory_summary  # noqa: F401
from mpi4dl_tpu.analysis.metrics import publish_report  # noqa: F401
from mpi4dl_tpu.analysis.report import (  # noqa: F401
    Report,
    analyze_compiled,
    analyze_hlo_text,
)
from mpi4dl_tpu.analysis.rules import (  # noqa: F401
    DEFAULT_RULES,
    Expectations,
    Finding,
    LintContext,
    max_severity,
    run_rules,
)
from mpi4dl_tpu.analysis.trace import (  # noqa: F401
    TraceError,
    analyze_trace_dir,
    crosscheck_overlap,
    publish_attribution,
    static_overlap_verdict,
)
