"""``python -m mpi4dl_tpu.analyze`` — compile a train step, lint its HLO.

Builds the same Trainer the bench/tests use, compiles
``trainer._jit_step.lower(...).compile()`` (no step is ever executed — on a
CPU mesh this lints the full distributed program without touching a TPU),
derives partition-math expectations (tile grid + counted halo shifts), runs
the rule engine, and writes one JSON report. Exit status is the lint gate:
nonzero iff findings at/above ``--fail-on`` severity exist.

Examples::

    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.analyze --model resnet \
        --size 512 --json /tmp/r.json
    python -m mpi4dl_tpu.analyze --model amoebanet --size 64 --dp 2
    python -m mpi4dl_tpu.analyze --model resnet --size 512 --write-baseline

Subcommands: ``python -m mpi4dl_tpu.analyze bench-history
BENCH_r*.json`` compares the committed bench rounds and fails on a
throughput regression (:mod:`mpi4dl_tpu.analysis.bench_history`);
``python -m mpi4dl_tpu.analyze trace-export LOG... [--trace-id ID]``
joins span segments from N processes' JSONL telemetry logs by trace id
and writes one Chrome trace — a request's full client → queue → batch →
device lifetime across process boundaries
(:func:`mpi4dl_tpu.telemetry.federation.trace_export_main`);
``python -m mpi4dl_tpu.analyze tail LOGS... [--trace-id ID] [--top N]``
joins histogram exemplars, span segments, and ``tail.sample`` events to
answer "why was this request slow" per trace id — phase breakdown vs the
window p50, dominant phase named, worst-requests table
(:mod:`mpi4dl_tpu.analysis.tail`);
``python -m mpi4dl_tpu.analyze incident LOGS... [--incident-id ID]
[--json|--md]`` reconstructs incident timelines and postmortems —
lifecycle, causally ordered evidence, named first cause, blast radius —
from JSONL logs alone, matching the live ``/incidentz`` event for event
(:mod:`mpi4dl_tpu.analysis.incident`);
``python -m mpi4dl_tpu.analyze memory-plan`` predicts peak HBM vs the
device limit for a requested config — compile-only, nothing executes —
and bisects the max feasible px/bucket
(:mod:`mpi4dl_tpu.analysis.memory_plan`);
``python -m mpi4dl_tpu.analyze sp-overlap`` measures the SP 2×2 train
step's halo/compute overlap A/B — monolithic vs decomposed spatial conv
— with live trace attribution, partition-math lint, and the
``trace-overlap-crosscheck`` on each arm
(:mod:`mpi4dl_tpu.analysis.overlap_bench`);
``python -m mpi4dl_tpu.analyze serving-sharded`` runs the same A/B on the
SERVING hot path — a spatially-sharded ServingEngine under closed-loop
load per arm, with per-request latency, the mesh-derived lint gate, and
the bit-identity crosscheck between arms
(:mod:`mpi4dl_tpu.analysis.serving_overlap`);
``python -m mpi4dl_tpu.analyze pipeline`` measures the LP pipeline's
schedule A/B — gpipe vs interleaved 1f1b — with live per-stage trace
attribution, the measured bubble fraction cross-checked against the
schedule model, and the exact stage-permute lint budget
(:mod:`mpi4dl_tpu.analysis.pipeline_bench`);
``python -m mpi4dl_tpu.analyze costmodel`` prices a compiled program's
collectives under a parameterized interconnect table — predicted comms
seconds, achievable overlap ceiling, schedule-model bubble — publishes
the ``hlolint_predicted_*`` gauges, and crosschecks against a live trace
capture (``cost-model-crosscheck``); its ``--artifact`` mode prices
committed lint-report JSONs with no jax at all
(:mod:`mpi4dl_tpu.analysis.costmodel`);
``python -m mpi4dl_tpu.analyze coldstart LEDGER.json LOGS.jsonl ...``
ranks executables by compile seconds across footprint-ledger dumps
(grouped by content fingerprint), joins ``elastic.restart`` events and
the fleet recovery phase decomposition, and gates on ``--budget-s`` —
pure JSON, its ``--artifact`` mode needs no jax at all
(:mod:`mpi4dl_tpu.analysis.coldstart`);
``python -m mpi4dl_tpu.analyze numerics`` audits the three serving
forwards — single-chip, spatially sharded, halo-tiled — against each
other on the SAME deterministic canary batch and one weight set, gated
per pair at the documented f32 tolerances; its ``--artifact`` mode
re-gates committed audit reports and summarizes ``canary.failure``
events with no jax at all (:mod:`mpi4dl_tpu.analysis.numerics`).
"""

from __future__ import annotations

import argparse
import sys

from mpi4dl_tpu.analysis.rules import SEVERITY_ORDER


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze",
        description="Static HLO lint over a compiled mpi4dl_tpu train step",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--model", choices=("resnet", "amoebanet"), default="resnet")
    p.add_argument("--size", type=int, default=512, help="square image size")
    p.add_argument("--batch", type=int, default=4, help="global batch size")
    p.add_argument("--depth", type=int, default=8, help="ResNet depth (v1)")
    p.add_argument(
        "--layers", type=int, default=6, help="AmoebaNet-D layer count"
    )
    p.add_argument(
        "--filters", type=int, default=64, help="AmoebaNet-D filter count"
    )
    p.add_argument(
        "--spatial-parts", type=int, default=4,
        help="spatial tiles for the resnet SP front (0 = pure DP)",
    )
    p.add_argument(
        "--spatial-cells", type=int, default=3,
        help="leading cells that run spatially partitioned (resnet)",
    )
    p.add_argument("--slice", default="square", dest="slice_method",
                   choices=("square", "vertical", "horizontal"))
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel replicas (0 = 1 for spatial, 2 for DP)")
    p.add_argument(
        "--remat", default="none",
        choices=("none", "cell", "sqrt", "scan", "scan2", "scanlog",
                 "scanq", "scan_save", "cell_save", "group_save"),
    )
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the full report JSON here")
    p.add_argument("--baseline", default=None,
                   help="peak-memory baseline file "
                        "(default docs/artifacts/hlolint_baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="record this run's peak memory as the new baseline")
    p.add_argument("--fail-on", default="error",
                   choices=("error", "warn", "never"),
                   help="minimum finding severity that fails the process")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="relative peak-memory regression tolerance")
    return p


def _build_trainer(args):
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer

    spatial = args.model == "resnet" and args.spatial_parts > 0
    dp = args.dp or (1 if spatial else 2)
    remat = False if args.remat == "none" else args.remat
    if spatial:
        cfg = ParallelConfig(
            batch_size=args.batch, split_size=1, spatial_size=1,
            num_spatial_parts=(args.spatial_parts,),
            slice_method=args.slice_method,
            image_size=args.size, data_parallel=dp,
        )
    else:
        cfg = ParallelConfig(
            batch_size=args.batch, split_size=1, spatial_size=0,
            image_size=args.size, data_parallel=dp,
        )

    if args.model == "resnet":
        from mpi4dl_tpu.models.resnet import get_resnet_v1

        plain = get_resnet_v1(depth=args.depth)
        n_sp = min(args.spatial_cells, len(plain) - 1) if spatial else 0
        cells = (
            get_resnet_v1(depth=args.depth, spatial_cells=n_sp)
            if n_sp else plain
        )
        trainer = Trainer(
            cells, num_spatial_cells=n_sp, config=cfg, remat=remat,
            plain_cells=plain if n_sp else None,
        )
    else:
        from mpi4dl_tpu.models.amoebanet import amoebanetd

        cells = amoebanetd(
            num_classes=10, num_layers=args.layers, num_filters=args.filters
        )
        n_sp = 0
        trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
    return trainer, cfg, n_sp


def _config_key(args, platform: str) -> str:
    shape = (
        f"sp{args.spatial_parts}x{args.spatial_cells}_{args.slice_method}"
        if args.model == "resnet" and args.spatial_parts > 0
        else f"dp{args.dp or 2}"
    )
    arch = (
        f"d{args.depth}" if args.model == "resnet"
        else f"l{args.layers}f{args.filters}"
    )
    return (
        f"{args.model}_{arch}_{args.size}px_bs{args.batch}_{shape}"
        f"_{args.remat}_{platform}"
    )


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "bench-history":
        # Pure-JSON subcommand: no jax, no devices, no compile — safe to
        # dispatch before any backend setup.
        from mpi4dl_tpu.analysis.bench_history import main as bench_history

        return bench_history(argv[1:])
    if argv and argv[0] == "trace-export":
        # Also pure JSON: joins JSONL span logs into a Chrome trace.
        from mpi4dl_tpu.telemetry.federation import trace_export_main

        return trace_export_main(argv[1:])
    if argv and argv[0] == "tail":
        # Tail forensics: join histogram exemplars, cross-process span
        # segments, and tail.sample events to explain slow requests per
        # trace id. Pure JSON — runs on logs from a dead machine.
        from mpi4dl_tpu.analysis.tail import main as tail_main

        return tail_main(argv[1:])
    if argv and argv[0] == "incident":
        # Incident reconstruction: rebuild incident.open/update/close
        # lifecycles, correlated timelines, first causes, and blast
        # radii from JSONL logs — the offline twin of /incidentz. Pure
        # JSON — runs on logs from a dead machine.
        from mpi4dl_tpu.analysis.incident import main as incident_main

        return incident_main(argv[1:])
    if argv and argv[0] == "sp-overlap":
        # SP 2x2 halo/compute overlap A/B (monolithic vs decomposed
        # spatial conv): sets up its own CPU mesh + jax like the lint
        # path, measures a live capture per arm, lints both programs.
        from mpi4dl_tpu.analysis.overlap_bench import main as sp_overlap

        return sp_overlap(argv[1:])
    if argv and argv[0] == "pipeline":
        # Pipeline schedule A/B (gpipe vs interleaved 1f1b): sets up its
        # own CPU mesh like sp-overlap, measures a live capture per arm
        # (measured bubble fraction + img/s), lints both programs at the
        # exact stage-permute budget.
        from mpi4dl_tpu.analysis.pipeline_bench import main as pipeline_ab

        return pipeline_ab(argv[1:])
    if argv and argv[0] == "serving-sharded":
        # Sharded-serving overlap A/B (monolithic vs decomposed conv on
        # the serving hot path): builds its own CPU tile mesh like
        # sp-overlap, measures a load-run capture per arm, lints both
        # programs against the mesh-derived halo window.
        from mpi4dl_tpu.analysis.serving_overlap import main as serving_ab

        return serving_ab(argv[1:])
    if argv and argv[0] == "costmodel":
        # Static communication cost model. Its --artifact mode (price
        # committed lint-report JSONs under an interconnect table) is
        # pure JSON and dispatches before any backend setup, like
        # bench-history; the live mode compiles on its own mesh and
        # crosschecks the predictions against a short trace capture.
        from mpi4dl_tpu.analysis.costmodel import main as costmodel_main

        return costmodel_main(argv[1:])
    if argv and argv[0] == "coldstart":
        # Cold-start manifest: rank executables by compile seconds
        # across footprint-ledger dumps, join elastic.restart events and
        # fleet recovery phase decompositions. Pure JSON — runs on
        # artifacts from a dead machine, dispatches before any backend
        # setup like bench-history.
        from mpi4dl_tpu.analysis.coldstart import main as coldstart_main

        return coldstart_main(argv[1:])
    if argv and argv[0] == "numerics":
        # Cross-predictor canary equivalence audit (single-chip vs
        # sharded vs tiled at the documented f32 tolerances). Its
        # --artifact mode (re-gate committed audit reports, summarize
        # canary.failure JSONL events) is pure JSON and dispatches
        # before any backend setup, like bench-history; the live mode
        # sets up its own CPU mesh like sp-overlap.
        from mpi4dl_tpu.analysis.numerics import main as numerics_main

        return numerics_main(argv[1:])
    if argv and argv[0] == "memory-plan":
        # Feasibility planner. Its artifact mode (committed peaks vs a
        # limit) is pure JSON and must dispatch before any backend
        # setup, like bench-history; its compile mode sets up jax
        # itself only when asked to lower a config.
        from mpi4dl_tpu.analysis.memory_plan import main as memory_plan

        return memory_plan(argv[1:])
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()
    import os

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # The CPU mesh needs virtual devices before backend init (the same
        # 8-device simulation the test suite runs on).
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(8)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.analysis.expectations import compose
    from mpi4dl_tpu.analysis.memory import load_baseline, write_baseline
    from mpi4dl_tpu.analysis.report import analyze_compiled

    platform = jax.devices()[0].platform
    trainer, cfg, n_sp = _build_trainer(args)

    x_shape = (args.batch, args.size, args.size, 3)
    state = trainer.init(jax.random.PRNGKey(0), x_shape)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(x_shape), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(args.batch,)), jnp.int32)
    xs, ys = trainer.shard_batch(x, y)
    compiled = trainer._jit_step.lower(state, xs, ys).compile()

    # Algebra-derived gate: the trainer contributes its layer deltas
    # (spatial halo window or pure-DP) and compose() folds them into the
    # Expectations the rules consume — no hand-built special cases.
    expected = compose(trainer.collective_deltas(state.params, x_shape))

    key = _config_key(args, platform)
    baseline = load_baseline(key, args.baseline)
    report = analyze_compiled(
        compiled,
        expected=expected,
        remat=trainer.remat_report(),
        platform=platform,
        config={
            "key": key,
            "model": args.model,
            "image_size": args.size,
            "batch_size": args.batch,
            "spatial_cells": n_sp,
            "tile_shape": list(cfg.tile_shape),
            "data_parallel": cfg.data_parallel,
            "remat": args.remat,
            "halo_shifts": expected.halo_shifts,
        },
        baseline_bytes=baseline,
        tolerance=args.tolerance,
    )

    if args.write_baseline and report.memory:
        path = write_baseline(key, report.memory["peak_bytes"], args.baseline)
        print(f"# baseline[{key}] <- {report.memory['peak_bytes']} B ({path})")

    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(report.to_json())
            f.write("\n")
    print(report.summary_line())
    for f in report.findings:
        loc = f" [{f['location']}]" if f.get("location") else ""
        print(f"  {f['severity'].upper()} {f['rule']}{loc}: {f['message']}")

    if args.fail_on == "never" or report.max_severity is None:
        return 0
    if SEVERITY_ORDER[report.max_severity] >= SEVERITY_ORDER[args.fail_on]:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via analyze.py
    sys.exit(main())
