"""Static communication cost model: bytes-moved inventory → predicted seconds.

The T3 observation (arXiv:2401.16677) behind hlolint's overlap rule also
prices the window: once the analyzer knows each collective's payload bytes
(:mod:`mpi4dl_tpu.analysis.hlo` shape math) and whether compute is scheduled
inside its start→done window (:mod:`mpi4dl_tpu.analysis.inventory`), a
per-link interconnect table turns the inventory into *predicted comms
seconds* and a *predicted achievable overlap ratio* — a committed number
the ICI measurement campaign can falsify, instead of CPU-measured vibes.
FLUX-style fused boundaries (arXiv:2406.06858) are the modeled best case:
every async window fully hidden, so the achievable ratio is a CEILING, not
an estimate of what the scheduler will actually do.

Three predictions per program, published as cataloged
``hlolint_predicted_*`` gauges and embedded in bench result lines:

- ``comms_s``: Σ per-collective time under ring/neighbor cost formulas
  (permute: ``lat + bytes/bw``; all-gather / reduce-scatter:
  ``(n-1)·lat + (n-1)/n · bytes/bw``; all-reduce doubles both terms —
  reduce-scatter + all-gather phases of a ring).
- ``overlap_ratio``: the achievable ceiling — the fraction of predicted
  collective seconds whose start→done window has compute scheduled inside
  it. Sync collectives (no ``-start``/``-done`` pair — every CPU-mesh
  collective) can hide nothing, so a CPU program predicts 0.0 and the
  model makes NO overlap claim there (mirrors the trace lens's "CPU emits
  sync collectives" no-claim rule).
- ``bubble_fraction``: passthrough of the schedule model
  (``PipelineTrainer.analytic_bubble_fraction``) when the program is a
  pipeline; None otherwise.

``crosscheck_cost_model`` compares the predictions against the LIVE
gauges (``trace_overlap_ratio``, ``pipeline_bubble_fraction``) and emits
``cost-model-crosscheck`` findings on disagreement beyond tolerance —
measured overlap ABOVE the achievable ceiling is an error (the model's
interconnect table or dependency math is wrong); measured below is info
(exposed latency the scheduler left on the table — T3's target case).

Honest calibration caveat (docs/ANALYSIS.md "Reading the cost model"):
the ``cpu`` table prices the 8-virtual-device shared-memory mesh, where
"links" are memcpy through a shared heap — its absolute seconds are only
order-of-magnitude. The ``ici`` table carries the campaign's priors
(per-link bandwidth/latency of a TPU v4-ish torus) and is exactly the
artifact real hardware falsifies (``docs/artifacts/costmodel_ici_r01.json``).
"""

from __future__ import annotations

import dataclasses
import json

from mpi4dl_tpu.analysis.rules import Finding

__all__ = [
    "INTERCONNECTS",
    "Interconnect",
    "collective_seconds",
    "crosscheck_cost_model",
    "predict_from_report",
    "predict_program",
    "publish_prediction",
]

#: |measured - predicted| slack before the crosscheck files a finding.
#: Generous on purpose: the model prices steady-state bandwidth, the
#: 2-step live capture measures warmup-adjacent steps.
DEFAULT_TOLERANCE = 0.15


@dataclasses.dataclass(frozen=True)
class Interconnect:
    """One link class of the parameterized interconnect table."""

    name: str
    # Per-link unidirectional bandwidth, bytes/second.
    bandwidth_bytes_per_s: float
    # Per-hop launch/teardown latency, seconds.
    latency_s: float
    doc: str = ""


INTERCONNECTS: "dict[str, Interconnect]" = {
    # TPU v4-ish ICI prior: ~100 GB/s per link per direction, ~1 us hop
    # latency. Campaign priors, not measurements — the committed
    # prediction artifact exists to be falsified on real hardware.
    "ici": Interconnect("ici", 100e9, 1e-6,
                        "TPU torus inter-chip links (campaign prior)"),
    # The 8-virtual-device CPU mesh: a "link" is a memcpy through the
    # shared heap. ~10 GB/s effective, ~5 us sync overhead per hop.
    # Order-of-magnitude only — see the calibration caveat above.
    "cpu": Interconnect("cpu", 10e9, 5e-6,
                        "shared-memory virtual-device mesh (approximate)"),
}


def collective_seconds(
    opcode: str, bytes_moved: int, ic: Interconnect, n_devices: int
) -> float:
    """Ring/neighbor cost of one collective on ``n_devices`` participants.

    ``bytes_moved`` is the payload the inventory derived from the output
    shape — the data a participant materializes, matching the standard
    ring formulations below.
    """
    n = max(int(n_devices), 2)
    bw, lat = ic.bandwidth_bytes_per_s, ic.latency_s
    if opcode == "collective-permute":
        # One neighbor hop, full payload.
        return lat + bytes_moved / bw
    if opcode in ("all-gather", "reduce-scatter", "all-to-all",
                  "ragged-all-to-all", "collective-broadcast"):
        # Ring: n-1 steps, each moving 1/n of the payload.
        return (n - 1) * lat + ((n - 1) / n) * bytes_moved / bw
    if opcode == "all-reduce":
        # Ring reduce-scatter + all-gather: both terms doubled.
        return 2 * (n - 1) * lat + (2 * (n - 1) / n) * bytes_moved / bw
    # Unknown collective class: price it as one full-payload hop rather
    # than silently dropping it from the total.
    return lat + bytes_moved / bw


def predict_program(
    collectives: "list[dict]",
    interconnect: "str | Interconnect" = "cpu",
    n_devices: int = 8,
    analytic_bubble: "float | None" = None,
) -> dict:
    """Price a program's collective records (``Report.collectives`` /
    ``collective_records`` as dicts: ``opcode``, ``bytes_moved``,
    ``is_async``, ``compute_between``).

    Returns the prediction dict bench lines embed and
    :func:`publish_prediction` publishes. ``overlap_claim`` is False when
    the program has no async collectives — the model then predicts 0.0
    achievable overlap but does NOT claim it (sync collectives say
    nothing about what an async lowering could hide).
    """
    ic = (interconnect if isinstance(interconnect, Interconnect)
          else INTERCONNECTS[interconnect])
    comms_s = 0.0
    hideable_s = 0.0
    n_async = 0
    per_op: "dict[str, dict]" = {}
    for r in collectives:
        op = r["opcode"]
        t = collective_seconds(op, int(r["bytes_moved"]), ic, n_devices)
        comms_s += t
        is_async = bool(r.get("is_async"))
        n_async += is_async
        # Achievable = the window exists (async) AND the schedule already
        # places compute inside it. A FLUX-style fused boundary could
        # hide more; this prices the program as compiled.
        if is_async and (r.get("compute_between") or 0) > 0:
            hideable_s += t
        slot = per_op.setdefault(
            op, {"count": 0, "bytes": 0, "seconds": 0.0}
        )
        slot["count"] += 1
        slot["bytes"] += int(r["bytes_moved"])
        slot["seconds"] += t
    for slot in per_op.values():
        slot["seconds"] = round(slot["seconds"], 9)
    overlap_claim = n_async > 0
    return {
        "interconnect": ic.name,
        "n_devices": int(n_devices),
        "n_collectives": len(collectives),
        "n_async": n_async,
        "comms_s": round(comms_s, 9),
        "hideable_s": round(hideable_s, 9),
        "exposed_s": round(comms_s - hideable_s, 9),
        "overlap_ratio": round(hideable_s / comms_s, 6) if comms_s else 0.0,
        "overlap_claim": overlap_claim,
        "bubble_fraction": (
            None if analytic_bubble is None else float(analytic_bubble)
        ),
        "per_op": per_op,
    }


def predict_from_report(
    report,
    interconnect: "str | Interconnect" = "cpu",
    n_devices: "int | None" = None,
    analytic_bubble: "float | None" = None,
) -> dict:
    """Price a :class:`~mpi4dl_tpu.analysis.report.Report` (or its
    ``as_dict()`` / loaded JSON form). ``n_devices`` defaults to the
    report config's ``n_devices`` when present, else 8 (the CPU mesh)."""
    d = report if isinstance(report, dict) else report.as_dict()
    cfg = d.get("config") or {}
    if n_devices is None:
        n_devices = int(cfg.get("n_devices") or 8)
    pred = predict_program(
        d.get("collectives") or [],
        interconnect=interconnect,
        n_devices=n_devices,
        analytic_bubble=analytic_bubble,
    )
    pred["program"] = str(
        cfg.get("program") or cfg.get("key") or d.get("module_name")
        or "unknown"
    )
    return pred


def publish_prediction(pred: dict, registry, program: "str | None" = None):
    """Publish one prediction as the cataloged ``hlolint_predicted_*``
    gauges, labeled by program and interconnect."""
    from mpi4dl_tpu import telemetry

    prog = str(program or pred.get("program") or "unknown")
    labels = {"program": prog, "interconnect": pred["interconnect"]}
    telemetry.declare(registry, "hlolint_predicted_comms_seconds").set(
        pred["comms_s"], **labels
    )
    telemetry.declare(registry, "hlolint_predicted_overlap_ratio").set(
        pred["overlap_ratio"], **labels
    )
    if pred.get("bubble_fraction") is not None:
        telemetry.declare(
            registry, "hlolint_predicted_bubble_fraction"
        ).set(pred["bubble_fraction"], **labels)
    return registry


def crosscheck_cost_model(
    pred: dict,
    measured_overlap: "float | None" = None,
    measured_bubble: "float | None" = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> "list[Finding]":
    """``cost-model-crosscheck``: predictions vs the live trace gauges.

    - No async collectives → no overlap claim → clean (the CPU-mesh
      no-claim rule, mirroring ``trace-overlap-crosscheck``).
    - measured overlap > achievable ceiling + tolerance → **error**: the
      runtime hid more communication than the dependency model says is
      hideable, so the model (interconnect table or start→done math) is
      wrong — fix the model, it is about to mis-advise the campaign.
    - measured overlap < ceiling - tolerance → info: achievable overlap
      the scheduler left exposed (the T3 target case).
    - |measured bubble - analytic bubble| > tolerance → **error**: the
      schedule model disagrees with the measured fill-drain — stage
      imbalance or a schedule bug, the same signal as
      ``pipeline-bubble-crosscheck`` but against the *predicted* gauge.
    """
    rule = "cost-model-crosscheck"
    out: "list[Finding]" = []
    if measured_overlap is not None and pred.get("overlap_claim"):
        ceiling = float(pred["overlap_ratio"])
        if measured_overlap > ceiling + tolerance:
            out.append(Finding(
                rule, "error",
                f"measured trace_overlap_ratio {measured_overlap:.2f} "
                f"exceeds the model's achievable ceiling {ceiling:.2f} "
                f"(+{tolerance:.2f} tolerance): the cost model's "
                "interconnect table or start->done dependency math is "
                "wrong for this program.",
            ))
        elif measured_overlap < ceiling - tolerance:
            out.append(Finding(
                rule, "info",
                f"measured trace_overlap_ratio {measured_overlap:.2f} is "
                f"below the achievable ceiling {ceiling:.2f}: the compiled "
                "schedule leaves hideable communication exposed "
                "(T3/FLUX opportunity, not a model error).",
            ))
    bubble = pred.get("bubble_fraction")
    if bubble is not None and measured_bubble is not None:
        if abs(measured_bubble - bubble) > tolerance:
            out.append(Finding(
                rule, "error",
                f"measured pipeline_bubble_fraction {measured_bubble:.3f} "
                f"disagrees with the schedule-model prediction "
                f"{bubble:.3f} by more than {tolerance:.2f}: stage "
                "imbalance or a schedule bug (same signal as "
                "pipeline-bubble-crosscheck, against the predicted gauge).",
            ))
    return out


# -- pure-JSON artifact mode (dispatched before any jax import) --------------

def artifact_main(argv: "list[str] | None" = None) -> int:
    """``analyze costmodel --artifact REPORT.json ...`` — price committed
    lint-report JSONs without jax, devices, or compilation (runs on logs
    from a dead machine, like bench-history)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze costmodel --artifact",
        description="Static comms cost predictions from committed lint "
                    "report JSONs (pure JSON - no jax).",
    )
    p.add_argument("reports", nargs="+", help="lint report JSON files")
    p.add_argument("--interconnect", choices=sorted(INTERCONNECTS),
                   default="ici")
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the predictions JSON here")
    args = p.parse_args(argv)

    preds = []
    for path in args.reports:
        with open(path) as f:
            d = json.load(f)
        pred = predict_from_report(
            d, interconnect=args.interconnect, n_devices=args.n_devices
        )
        pred["source"] = path
        preds.append(pred)
        print(
            f"# costmodel[{pred['program']}] {pred['interconnect']}: "
            f"comms {pred['comms_s'] * 1e3:.3f} ms, achievable overlap "
            f"{pred['overlap_ratio']:.2f}"
            + ("" if pred["overlap_claim"] else " (no claim: sync-only)")
        )
    payload = {"interconnect": args.interconnect, "predictions": preds}
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    else:
        print(json.dumps(payload, indent=2, sort_keys=True))
    return 0


# -- live mode (compiles on this machine's mesh, crosschecks the trace) ------

def main(argv: "list[str] | None" = None) -> int:
    """``analyze costmodel`` — compile a program, price its collectives,
    capture a short live trace, and crosscheck predicted vs measured.

    ``--artifact`` routes to :func:`artifact_main` (pure JSON, no jax) —
    the flag is checked BEFORE any backend import so committed reports
    can be priced on a machine without devices.
    """
    argv = list(argv or [])
    if "--artifact" in argv:
        argv.remove("--artifact")
        return artifact_main(argv)

    import argparse

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze costmodel",
        description="Static comms cost model: predicted seconds/overlap/"
                    "bubble for a compiled program, crosschecked against "
                    "a live trace capture.",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--interconnect", choices=sorted(INTERCONNECTS),
                   default="cpu")
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--spatial-parts", type=int, default=4)
    p.add_argument("--spatial-cells", type=int, default=3)
    p.add_argument("--schedule", choices=("none", "gpipe", "1f1b"),
                   default="none",
                   help="none = SP/DP train step; else a pipeline program "
                        "with the analytic bubble prediction")
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--parts", type=int, default=4)
    p.add_argument("--virtual-stages", type=int, default=2)
    p.add_argument("--steps", type=int, default=2,
                   help="live capture steps for the crosscheck (0 = "
                        "predictions only, no trace)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE)
    p.add_argument("--json", dest="json_out", default=None)
    p.add_argument("--fail-on", default="error",
                   choices=("error", "warn", "never"))
    args = p.parse_args(argv)

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()
    import os

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(8)

    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.analysis.expectations import compose
    from mpi4dl_tpu.analysis.report import analyze_compiled
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1

    rng = np.random.default_rng(0)
    x_shape = (args.batch, args.size, args.size, 3)
    x = jnp.asarray(rng.standard_normal(x_shape), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(args.batch,)), jnp.int32)

    analytic_bubble = None
    if args.schedule != "none":
        from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

        cfg = ParallelConfig(
            batch_size=args.batch, parts=args.parts,
            split_size=args.stages, spatial_size=0, image_size=args.size,
        )
        trainer = PipelineTrainer(
            get_resnet_v1(depth=args.depth), cfg, schedule=args.schedule,
            virtual_stages=args.virtual_stages,
        )
        state = trainer.init(jax.random.PRNGKey(0))
        program = f"pipeline_{args.schedule}"
        analytic_bubble = trainer.analytic_bubble_fraction()
    else:
        from mpi4dl_tpu.train import Trainer

        cfg = ParallelConfig(
            batch_size=args.batch, split_size=1, spatial_size=1,
            num_spatial_parts=(args.spatial_parts,),
            slice_method="square", image_size=args.size, data_parallel=1,
        )
        plain = get_resnet_v1(depth=args.depth)
        n_sp = min(args.spatial_cells, len(plain) - 1)
        cells = get_resnet_v1(depth=args.depth, spatial_cells=n_sp)
        trainer = Trainer(
            cells, num_spatial_cells=n_sp, config=cfg, plain_cells=plain
        )
        state = trainer.init(jax.random.PRNGKey(0), x_shape)
        program = "sp2x2_train"
    xs, ys = trainer.shard_batch(x, y)
    compiled = trainer._jit_step.lower(state, xs, ys).compile()
    deltas_args = (
        (state, x_shape) if args.schedule != "none"
        else (state.params, x_shape)
    )
    report = analyze_compiled(
        compiled,
        expected=compose(trainer.collective_deltas(*deltas_args)),
        platform=jax.devices()[0].platform,
        config={"program": program, "n_devices": cfg.num_devices},
    )
    pred = predict_from_report(
        report, interconnect=args.interconnect,
        n_devices=cfg.num_devices, analytic_bubble=analytic_bubble,
    )

    reg = telemetry.default_registry()
    publish_prediction(pred, reg, program=program)

    measured_overlap = measured_bubble = None
    if args.steps > 0:
        logdir = tempfile.mkdtemp(prefix="mpi4dl-costmodel-")
        try:
            state, summary = trainer.capture_trace_attribution(
                state, xs, ys, steps=args.steps, logdir=logdir,
                registry=reg, program=program,
            )
        finally:
            shutil.rmtree(logdir, ignore_errors=True)
        measured_overlap = summary["collective"]["overlap_ratio"]
        measured_bubble = (summary.get("pipeline") or {}).get(
            "bubble_fraction"
        )
    findings = crosscheck_cost_model(
        pred, measured_overlap=measured_overlap,
        measured_bubble=measured_bubble, tolerance=args.tolerance,
    )

    payload = {
        "program": program,
        "prediction": pred,
        "measured": {
            "trace_overlap_ratio": measured_overlap,
            "pipeline_bubble_fraction": measured_bubble,
        },
        "tolerance": args.tolerance,
        "crosscheck": [f.as_dict() for f in findings],
        "lint_errors": [
            f for f in report.findings if f["severity"] == "error"
        ],
    }
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    claim = "" if pred["overlap_claim"] else " (no overlap claim: sync-only)"
    print(
        f"# costmodel[{program}] {pred['interconnect']}: comms "
        f"{pred['comms_s'] * 1e3:.3f} ms, achievable overlap "
        f"{pred['overlap_ratio']:.2f}{claim}"
        + (f", predicted bubble {pred['bubble_fraction']:.3f}"
           if pred["bubble_fraction"] is not None else "")
    )
    if measured_overlap is not None:
        print(f"# measured trace_overlap_ratio {measured_overlap:.2f}")
    if measured_bubble is not None:
        print(f"# measured pipeline_bubble_fraction {measured_bubble:.3f}")
    for f in findings:
        print(f"  {f.severity.upper()} {f.rule}: {f.message}")
    if not findings:
        print("# cost-model-crosscheck clean")

    sev = {"info": 0, "warn": 1, "error": 2}
    worst = max((sev[f.severity] for f in findings), default=-1)
    lint_worst = 2 if payload["lint_errors"] else -1
    worst = max(worst, lint_worst)
    if args.fail_on == "never" or worst < 0:
        return 0
    return 1 if worst >= sev[args.fail_on] else 0
