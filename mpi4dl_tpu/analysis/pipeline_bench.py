"""Pipeline schedule A/B harness: measured bubble + img/s per schedule.

``python -m mpi4dl_tpu.analyze pipeline`` runs the LP pipeline train step
once per schedule arm — ``gpipe`` (fill-drain) and ``1f1b`` (interleaved
virtual stages) — and measures, per arm:

- the **measured** ``pipeline_bubble_fraction`` of a live XProf capture
  (:meth:`PipelineTrainer.capture_trace_attribution`): idle stage-switch
  slots over all slots, joined from the compiled program's branch
  closures to the real trace — the fill/drain fraction the ROADMAP's
  analytic ``(S-1)/(S-1+M)`` predicted but nothing measured;
- per-stage device seconds and the capture's images/sec;
- the **static** hlolint verdict with the permute window pinned at the
  EXACT stage-boundary budget (``Expectations.extra_permutes =
  PipelineTrainer.stage_permute_count()``);
- the ``pipeline-bubble-crosscheck`` joining analytic and measured.

The A/B verdict asserts what the 1F1B schedule exists for: its measured
bubble strictly below the GPipe arm's at equal (stages, micro-batches).
Run from bench.py as a subprocess (the ``pipeline`` extra) so the pipe
mesh exists regardless of the bench headline's backend, and callable
in-process (:func:`run_pipeline_ab`) from tests on the 8-virtual-CPU
mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile


def _build_arm(schedule, size, batch, depth, stages, parts, virtual_stages,
               warmup):
    """One arm's context: the LP PipelineTrainer built (and warmed) under
    ``schedule``, plus the static lint of its compiled step with the
    permute window pinned at the exact stage-boundary budget."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.analysis import analyze_compiled
    from mpi4dl_tpu.analysis.expectations import compose
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    cfg = ParallelConfig(
        batch_size=batch, parts=parts, split_size=stages, spatial_size=0,
        image_size=size,
    )
    cells = get_resnet_v1(depth=depth)
    trainer = PipelineTrainer(
        cells, cfg, schedule=schedule, virtual_stages=virtual_stages
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, size, size, 3)), jnp.float32
    )
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)
    xs, ys = trainer.shard_batch(x, y)
    state = trainer.init(jax.random.PRNGKey(0))

    compiled = trainer._jit_step.lower(state, xs, ys).compile()
    hlo_text = compiled.as_text()
    report = analyze_compiled(
        compiled,
        # Pure-LP program: the trainer's composed deltas carry zero halo
        # shifts, so the permute window collapses to exactly the
        # stage-boundary budget — the compiled inventory must sit AT
        # stage_permute_count() or the lint errors.
        expected=compose(trainer.collective_deltas(
            state, (batch, size, size, 3)
        )),
        platform=jax.devices()[0].platform,
        config={
            "program": f"pipeline_{schedule}", "schedule": schedule,
            "stages": stages, "parts": parts,
            "virtual_stages": trainer.v,
        },
    )
    loss = None
    for _ in range(max(1, warmup)):
        state, metrics = trainer.train_step(state, xs, ys)
        loss = float(metrics["loss"])  # force execution before any capture
    return {
        "schedule": schedule, "trainer": trainer, "state": state,
        "xs": xs, "ys": ys, "report": report, "warm_loss": loss,
        "hlo_text": hlo_text,
    }


def run_pipeline_ab(
    size: int = 32,
    batch: int = 8,
    depth: int = 8,
    stages: int = 2,
    parts: int = 4,
    virtual_stages: int = 2,
    steps: int = 3,
    warmup: int = 1,
    trials: int = 1,
    arms=("gpipe", "1f1b"),
    registry=None,
) -> dict:
    """Both schedule arms + the A/B verdict. ``trials`` captures per arm
    run INTERLEAVED (gpipe, 1f1b, gpipe, ...) so host drift hits both
    arms alike; the arm bubble pools idle/total slots across its captures
    and img/s is the mean of per-capture throughputs. The warm-up loss of
    each arm is recorded — both arms share one init, so the same value on
    both is the cheap in-band echo of the tier-1 loss-equality golden."""
    from mpi4dl_tpu.analysis.trace import crosscheck_bubble

    out = {
        "config": {
            "size": size, "batch": batch, "depth": depth,
            "stages": stages, "parts": parts,
            "virtual_stages": virtual_stages, "steps": steps,
            "trials": trials,
        },
        "arms": {},
    }
    ctxs = {
        arm: _build_arm(
            arm, size, batch, depth, stages, parts, virtual_stages, warmup
        )
        for arm in arms
    }
    pooled = {
        arm: {"idle": 0, "active": 0, "img": [], "stage_s": None,
              "analytic": None, "crosscheck": None}
        for arm in arms
    }
    for _ in range(max(1, int(trials))):
        for arm in arms:
            ctx, acc = ctxs[arm], pooled[arm]
            logdir = tempfile.mkdtemp(prefix=f"mpi4dl-pipeline-{arm}-")
            try:
                ctx["state"], summary = (
                    ctx["trainer"].capture_trace_attribution(
                        ctx["state"], ctx["xs"], ctx["ys"], steps=steps,
                        logdir=logdir, registry=registry,
                        program=f"pipeline_{arm}",
                        hlo_text=ctx["hlo_text"],
                    )
                )
            finally:
                shutil.rmtree(logdir, ignore_errors=True)
            pipe = summary["pipeline"]
            acc["idle"] += pipe["idle_slots"]
            acc["active"] += sum(pipe["active_slots_by_stage"])
            acc["img"].append(pipe["img_per_s"])
            acc["stage_s"] = pipe["stage_device_seconds"]
            acc["analytic"] = pipe["analytic_bubble_fraction"]
            if acc["crosscheck"] is None:
                acc["crosscheck"] = [
                    f.as_dict()
                    for f in crosscheck_bubble(acc["analytic"], pipe)
                ]
    for arm in arms:
        ctx, acc = ctxs[arm], pooled[arm]
        report = ctx["report"]
        total = acc["idle"] + acc["active"]
        out["arms"][arm] = {
            "schedule": arm,
            "bubble_fraction": acc["idle"] / total if total else None,
            "analytic_bubble_fraction": acc["analytic"],
            "img_per_s": (
                round(sum(acc["img"]) / len(acc["img"]), 3)
                if acc["img"] else None
            ),
            "stage_device_seconds": [
                round(s, 4) for s in (acc["stage_s"] or [])
            ],
            "warm_loss": ctx["warm_loss"],
            "permutes": report.inventory.get("collective-permute", 0),
            "permute_budget": ctx["trainer"].stage_permute_count(),
            "hlolint_errors": [
                f for f in report.findings if f["severity"] == "error"
            ],
            "crosscheck": acc["crosscheck"] or [],
        }
    gp = out["arms"].get("gpipe")
    fb = out["arms"].get("1f1b")
    if gp and fb:
        bg, bf = gp["bubble_fraction"], fb["bubble_fraction"]
        out["bubble_improved"] = (
            bg is not None and bf is not None and bf < bg
        )
        out["loss_equal"] = (
            gp["warm_loss"] is not None
            and fb["warm_loss"] is not None
            and abs(gp["warm_loss"] - fb["warm_loss"])
            <= 1e-5 * max(1.0, abs(gp["warm_loss"]))
        )
        ig, if_ = gp["img_per_s"], fb["img_per_s"]
        out["img_per_s_ratio"] = (
            round(if_ / ig, 4) if ig and if_ else None
        )
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze pipeline",
        description="Pipeline schedule A/B: gpipe vs interleaved 1f1b, "
                    "measured bubble fraction + img/s, permute-budget "
                    "linted",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--parts", type=int, default=4)
    p.add_argument("--virtual-stages", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--trials", type=int, default=1,
                   help="captures per arm, interleaved across arms; the "
                        "arm bubble pools idle/total slots over all of "
                        "them")
    p.add_argument("--schedule", action="append", dest="arms", default=None,
                   choices=("gpipe", "1f1b"),
                   help="restrict to one schedule arm (repeatable); "
                        "default both")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the A/B record here ('-' = stdout)")
    p.add_argument("--require-improvement", action="store_true",
                   help="exit 1 unless the 1f1b arm's measured bubble is "
                        "strictly below the gpipe arm's")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # The pipe mesh needs virtual devices before backend init — the
        # same 8-device simulation the test suite runs on.
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(max(8, args.stages))
    enable_compilation_cache()

    out = run_pipeline_ab(
        size=args.size, batch=args.batch, depth=args.depth,
        stages=args.stages, parts=args.parts,
        virtual_stages=args.virtual_stages, steps=args.steps,
        warmup=args.warmup, trials=args.trials,
        arms=tuple(args.arms) if args.arms else ("gpipe", "1f1b"),
    )
    for arm, rec in out["arms"].items():
        bub = rec["bubble_fraction"]
        print(
            f"# {arm}: bubble="
            f"{bub if bub is None else round(bub, 4)} "
            f"analytic={round(rec['analytic_bubble_fraction'], 4)} "
            f"img/s={rec['img_per_s']} permutes={rec['permutes']}"
            f"/{rec['permute_budget']} "
            f"lint_errors={len(rec['hlolint_errors'])} "
            f"crosscheck={len(rec['crosscheck'])}",
            file=sys.stderr, flush=True,
        )
    payload = json.dumps(out)
    if args.json_out == "-" or args.json_out is None:
        print(payload, flush=True)
    else:
        with open(args.json_out, "w") as f:
            f.write(payload + "\n")
    rc = 0
    if any(a["hlolint_errors"] for a in out["arms"].values()):
        rc = 1
    if any(a["crosscheck"] for a in out["arms"].values()):
        rc = 1
    if args.require_improvement and not out.get("bubble_improved"):
        rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover — exercised via analyze.py
    sys.exit(main())
