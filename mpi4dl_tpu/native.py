"""ctypes bindings + on-demand build of the native data runtime
(``mpi4dl_tpu/native_src/dataloader.cpp`` — shipped as package data, so
installed copies keep the fast path).

The shared library is compiled once with the system ``g++`` (no pybind11 in
the image — plain ``extern "C"`` + ctypes), into a ``build/`` dir next to
the source when writable, else ``~/.cache/mpi4dl_tpu`` (installed packages
may live on a read-only filesystem). All entry points degrade gracefully:
if the toolchain or the build is unavailable, callers fall back to numpy
(``available()`` gates the fast path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "native_src", "dataloader.cpp")


def _pick_build_dir() -> str:
    explicit = os.environ.get("MPI4DL_TPU_NATIVE_BUILD")
    if explicit:
        return explicit
    preferred = os.path.join(os.path.dirname(_SRC), "build")
    probe_root = os.path.dirname(preferred)
    if os.access(probe_root, os.W_OK):
        return preferred
    return os.path.join(
        os.path.expanduser("~"), ".cache", "mpi4dl_tpu", "native_build"
    )


_BUILD_DIR = _pick_build_dir()
_LIB = os.path.join(_BUILD_DIR, "libmpi4dl_data.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O3", "-fPIC", "-shared", "-pthread", "-std=c++17",
        _SRC, "-o", _LIB,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MPI4DL_TPU_NO_NATIVE"):
            return None
        have_src = os.path.exists(_SRC)
        stale = not os.path.exists(_LIB) or (
            have_src and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if stale and (not have_src or not _build()):
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.mpi4dl_fill_uniform.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.mpi4dl_fill_labels.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_int,
        ]
        lib.mpi4dl_slice_tile.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.mpi4dl_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _nthreads(num_threads: int | None) -> int:
    if num_threads and num_threads > 0:
        return num_threads
    return max(os.cpu_count() or 1, 1)


def fill_uniform(shape, seed: int, num_threads: int | None = None) -> np.ndarray:
    """Deterministic uniform [0,1) float32 array; thread-count independent."""
    lib = _load()
    out = np.empty(shape, np.float32)
    n = out.size
    if lib is None:
        rng = np.random.default_rng(seed)
        out[...] = rng.random(shape, dtype=np.float32)
        return out
    lib.mpi4dl_fill_uniform(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, ctypes.c_uint64(seed & (2**64 - 1)), _nthreads(num_threads),
    )
    return out


def fill_labels(
    n: int, num_classes: int, seed: int, num_threads: int | None = None
) -> np.ndarray:
    lib = _load()
    out = np.empty((n,), np.int32)
    if lib is None:
        rng = np.random.default_rng(seed + 1)
        out[...] = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
        return out
    lib.mpi4dl_fill_labels(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, ctypes.c_uint64(seed & (2**64 - 1)), num_classes, _nthreads(num_threads),
    )
    return out


def slice_tile(
    batch: np.ndarray, th: int, tw: int, ti: int, tj: int,
    num_threads: int | None = None,
) -> np.ndarray:
    """Host-side ``split_input`` (ref ``train_spatial.py:241-290``): tile
    (ti, tj) of a contiguous NHWC float32 batch."""
    b, h, w, c = batch.shape
    lib = _load()
    if lib is None or batch.dtype != np.float32 or not batch.flags.c_contiguous:
        return np.ascontiguousarray(
            batch[:, ti * (h // th) : (ti + 1) * (h // th),
                  tj * (w // tw) : (tj + 1) * (w // tw), :]
        )
    out = np.empty((b, h // th, w // tw, c), np.float32)
    lib.mpi4dl_slice_tile(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        b, h, w, c, th, tw, ti, tj, _nthreads(num_threads),
    )
    return out
