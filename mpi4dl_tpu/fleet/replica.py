"""Replica plumbing: the RPC client and the subprocess lifecycle handle.

One replica = one :class:`~mpi4dl_tpu.serve.ServingEngine` in its own
process (one chip each — TPU access is exclusive per process, the same
constraint that shaped :mod:`mpi4dl_tpu.elastic`), fronted by the tiny
HTTP predict server in :mod:`mpi4dl_tpu.fleet.worker`. This module is
the ROUTER side of that seam:

- :class:`ReplicaClient` — blocking JSON-over-HTTP ``/predict`` call
  (stdlib ``urllib``; float32 example bytes travel base64-encoded).
  Failures map to TYPED exceptions because the router's requeue logic
  branches on them: :class:`ReplicaUnreachable` (connection refused /
  reset / timeout — the replica may be dead, requeue on a survivor),
  :class:`ReplicaQueueFull` (alive but shedding — back off, requeue),
  :class:`ReplicaDeadline` (the engine itself deadline-failed it —
  terminal, requeueing cannot un-miss a deadline), and
  :class:`ReplicaRemoteError` (the request failed *in* the engine —
  terminal for that attempt, counted against the retry budget).
- :class:`ReplicaProcess` — spawn/ready/alive/kill for one worker
  subprocess: ready handshake via an atomically-replaced JSON file
  (stdout parsing would need a pump thread per respawn), heartbeat
  staleness via the same mtime-change clock :func:`elastic.supervise`
  uses.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np


class ReplicaError(RuntimeError):
    """Base of the typed replica-RPC failures."""

    def __init__(self, msg: str, replica: str = ""):
        super().__init__(msg)
        self.replica = replica


class ReplicaUnreachable(ReplicaError):
    """Connection refused/reset/timed out — the replica may be dead or
    mid-restart. The request's execution state is UNKNOWN; the router
    may requeue (inference is idempotent) but must never complete the
    same future twice."""


class ReplicaQueueFull(ReplicaError):
    """The replica's own admission control bounced the request.

    kind: the structured 429 body's ``error`` field — ``"queue_full"``
        (a physically full queue / burn-rate shed) or
        ``"quota_exceeded"`` (the tenant's token bucket is empty;
        ``retry_after_s`` is then the bucket's refill time and
        ``tenant`` names who to bill). The router-set client surfaces
        quota bounces as typed
        :class:`~mpi4dl_tpu.tenancy.QuotaExceededError` instead of
        failing over — each router refills its own buckets, so retrying
        elsewhere would multiply the tenant's effective quota."""

    def __init__(self, msg: str, replica: str = "",
                 retry_after_s: "float | None" = None,
                 kind: str = "queue_full",
                 tenant: "str | None" = None):
        super().__init__(msg, replica)
        self.retry_after_s = retry_after_s
        self.kind = kind
        self.tenant = tenant


class ReplicaDeadline(ReplicaError):
    """The replica's engine deadline-failed the request (terminal)."""


class ReplicaRemoteError(ReplicaError):
    """The request failed inside the replica's engine."""


class FleetUnreachableError(ReplicaError):
    """EVERY front-door router is currently unreachable (all marked down
    by recent connection-refused/reset). Retriable — the supervisor
    respawns routers — so it carries the same ``retry_after_s`` hint
    shape as :class:`~mpi4dl_tpu.serve.QueueFullError`, and the load
    generator's backoff-retry loop treats it accordingly (counted as
    ``router_failovers``, not queue pressure)."""

    def __init__(self, msg: str, retry_after_s: "float | None" = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ReplicaClient:
    """Blocking HTTP client for one replica's predict/chaos surface."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base_url = base_url.rstrip("/")

    def _post(self, path: str, payload: dict, timeout_s: float) -> dict:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode())

    def predict(
        self,
        x: np.ndarray,
        trace_id: str,
        deadline_s: float,
        timeout_s: float,
        slo_class: "str | None" = None,
        retried: bool = False,
        tiled: bool = False,
        tenant: "str | None" = None,
    ) -> "tuple[np.ndarray, dict]":
        """One blocking predict RPC; returns ``(logits, payload)`` or
        raises one of the typed errors above. ``slo_class`` propagates
        the router-side SLO class into the replica engine's scheduler
        (the worker's engine must declare the same classes).
        ``retried=True`` marks a failover retry whose earlier attempt
        MAY have executed — a front-door router receiving it probes the
        replicas' served-caches before dispatching (duplicate
        suppression across the router failure domain). ``tiled=True``
        targets the worker's gigapixel ``/predict_tiled`` surface
        (serve/tiled.py) instead of ``/predict`` — same RPC shape, same
        structured errors, same idempotency cache."""
        payload = {
            "trace_id": trace_id,
            "deadline_s": float(deadline_s),
            "shape": [int(d) for d in x.shape],
            "dtype": str(x.dtype),
            "x_b64": base64.b64encode(np.ascontiguousarray(x).tobytes())
            .decode(),
        }
        if slo_class is not None:
            payload["slo_class"] = str(slo_class)
        if tenant is not None:
            payload["tenant"] = str(tenant)
        if retried:
            payload["retried"] = True
        try:
            out = self._post(
                "/predict_tiled" if tiled else "/predict",
                payload, timeout_s,
            )
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001 — error bodies are advisory
                err = {}
            kind = err.get("error", f"http {e.code}")
            if e.code == 429:
                raise ReplicaQueueFull(
                    f"{self.name}: {kind}", self.name,
                    retry_after_s=err.get("retry_after_s"),
                    kind=str(kind), tenant=err.get("tenant"),
                ) from None
            if e.code == 504:
                raise ReplicaDeadline(
                    f"{self.name}: {kind}", self.name
                ) from None
            if e.code == 503:
                # Draining / not accepting: alive, but this request must
                # move to a survivor — the unreachable-shaped outcome.
                raise ReplicaUnreachable(
                    f"{self.name}: {kind}", self.name
                ) from None
            raise ReplicaRemoteError(
                f"{self.name}: {kind}", self.name
            ) from None
        except (
            urllib.error.URLError, ConnectionError, socket.timeout,
            http.client.HTTPException, OSError,
        ) as e:
            raise ReplicaUnreachable(
                f"{self.name}: {type(e).__name__}: {e}", self.name
            ) from None
        logits = np.frombuffer(
            base64.b64decode(out["logits_b64"]), dtype=out["dtype"]
        ).reshape(out["shape"])
        return logits, out

    def chaos(self, timeout_s: float = 5.0, **payload) -> dict:
        """Apply a soft fault via the worker's ``/chaos`` endpoint."""
        return self._post("/chaos", payload, timeout_s)

    def served(
        self, trace_ids, timeout_s: float = 2.0
    ) -> "list[str]":
        """Which of ``trace_ids`` this replica has served (idempotency
        cache) or currently has in flight — the dedupe probe a successor
        router runs over journal orphans before re-dispatching them.
        Raises the usual typed errors on transport failure (the caller
        treats an unanswerable replica as 'cannot vouch')."""
        out = self._post(
            "/served", {"trace_ids": [str(t) for t in trace_ids]},
            timeout_s,
        )
        return list(out.get("served", ()))


class ReplicaProcess:
    """One replica worker subprocess: spawn, ready handshake, liveness.

    cmd: full argv EXCEPT the ``--ready-file`` pair, appended here (the
        ready file is per-spawn so a stale file from the previous
        incarnation can never satisfy the handshake).
    env: full environment for the child; ``MPI4DL_TPU_HEARTBEAT`` is
        added when ``heartbeat_path`` is given.
    """

    def __init__(
        self,
        name: str,
        cmd: "list[str]",
        base_dir: str,
        env: "dict | None" = None,
        heartbeat_path: "str | None" = None,
        log_path: "str | None" = None,
    ):
        from mpi4dl_tpu import elastic

        self.name = name
        self.cmd = list(cmd)
        self.base_dir = base_dir
        self.env = dict(env if env is not None else os.environ)
        self.heartbeat_path = heartbeat_path
        if heartbeat_path:
            self.env[elastic.HEARTBEAT_ENV] = heartbeat_path
        self.log_path = log_path
        self._log_fh = None
        self.proc: "subprocess.Popen | None" = None
        self.ports: "dict | None" = None
        self.spawned_at: "float | None" = None
        self._spawn_seq = 0
        self._hb_mtime: "float | None" = None
        self._hb_seen: "float | None" = None

    # -- lifecycle ------------------------------------------------------------

    def spawn(self) -> None:
        os.makedirs(self.base_dir, exist_ok=True)
        self._spawn_seq += 1
        self.ready_file = os.path.join(
            self.base_dir, f"{self.name}.ready.{self._spawn_seq}.json"
        )
        # A fresh handle instance restarts the seq counter, so a STALE
        # handshake file from a previous incarnation could satisfy the
        # ready poll with dead ports — remove it before the child exists.
        try:
            os.unlink(self.ready_file)
        except OSError:
            pass
        if self.heartbeat_path:
            from mpi4dl_tpu import elastic

            elastic.touch(self.heartbeat_path)  # fresh staleness epoch
        self._hb_mtime = None
        self._hb_seen = time.monotonic()
        self.ports = None
        if self._log_fh is not None:
            self._log_fh.close()
        stdio = subprocess.DEVNULL
        if self.log_path:
            self._log_fh = stdio = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            self.cmd + ["--ready-file", self.ready_file],
            env=self.env, stdout=stdio, stderr=stdio,
        )
        self.spawned_at = time.monotonic()

    def poll_ready(self) -> "dict | None":
        """Non-blocking: the worker's ready payload (``pid`` /
        ``predict_port`` / ``metrics_port``) once its handshake file
        lands, else None."""
        if self.ports is not None:
            return self.ports
        try:
            with open(self.ready_file) as f:
                self.ports = json.load(f)
        except (OSError, ValueError):
            return None
        return self.ports

    def wait_ready(self, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ports = self.poll_ready()
            if ports is not None:
                return ports
            if not self.alive():
                raise RuntimeError(
                    f"replica {self.name} died before ready "
                    f"(rc={self.proc.returncode})"
                )
            time.sleep(0.05)
        raise TimeoutError(
            f"replica {self.name} not ready within {timeout_s:.0f}s"
        )

    @property
    def pid(self) -> "int | None":
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self) -> "int | None":
        return self.proc.returncode if self.proc is not None else None

    def spawned_age_s(self) -> float:
        """Seconds since spawn() on THIS process's monotonic clock — the
        spawn-timeout input. Kept here (rather than supervisor-side
        ``clock() - spawned_at`` arithmetic) so an injected supervisor
        test clock can never be subtracted from a real monotonic stamp."""
        if self.spawned_at is None:
            return 0.0
        return time.monotonic() - self.spawned_at

    def heartbeat_stale_s(self) -> "float | None":
        """Seconds since the last observed heartbeat mtime CHANGE (the
        clock-skew-immune staleness measure of ``elastic.supervise``);
        None when no heartbeat is configured."""
        if not self.heartbeat_path:
            return None
        try:
            mtime = os.path.getmtime(self.heartbeat_path)
        except OSError:
            mtime = None
        if mtime != self._hb_mtime:
            self._hb_mtime = mtime
            self._hb_seen = time.monotonic()
        return time.monotonic() - self._hb_seen

    def kill_hard(self) -> None:
        """SIGKILL — the chaos ``kill`` drill and the wedged-replica
        remedy (a wedged collective ignores SIGTERM)."""
        if self.alive():
            try:
                os.kill(self.proc.pid, signal.SIGKILL)
            except OSError:
                pass

    def terminate(self, wait_s: float = 10.0) -> "int | None":
        """Graceful stop: SIGTERM (the worker drains + exits 0),
        escalating to SIGKILL after ``wait_s``."""
        if self.proc is None:
            return None
        if self.alive():
            try:
                self.proc.terminate()
            except OSError:
                pass
            try:
                self.proc.wait(timeout=wait_s)
            except subprocess.TimeoutExpired:
                self.kill_hard()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return self.proc.returncode


def worker_cmd(args: "list[str] | None" = None) -> "list[str]":
    """The replica worker's argv prefix (callers append worker flags;
    :class:`ReplicaProcess` appends ``--ready-file``)."""
    return [sys.executable, "-m", "mpi4dl_tpu.fleet.worker"] + list(
        args or ()
    )
