"""The HA front door: router-as-a-process + the failover client.

PR 8's :class:`~mpi4dl_tpu.fleet.router.Router` made the *replicas*
expendable; this module makes the router itself one. It has two halves:

- :class:`RouterServer` / ``python -m mpi4dl_tpu.fleet.frontdoor`` —
  one Router in its own process, fronted by HTTP. ``POST /submit``
  (aliased as ``/predict``, so :class:`ReplicaClient` speaks to a router
  exactly as it speaks to a replica worker) is the blocking admission
  RPC, reusing the worker's structured error shapes: 429 queue-full with
  ``retry_after_s``, 504 deadline, 503 draining, 500 terminal dispatch
  failure. ``POST /replicas`` is the supervisor's membership feed
  (add/remove/drain — the router learns the replica set the same way a
  respawned incarnation re-learns it). The standard telemetry surface
  (``/metrics``, ``/snapshotz``, ``/healthz``, ``/debugz``) rides a
  :class:`telemetry.MetricsServer` over the router registry, so a
  federation aggregator merges a router like any replica. The process
  imports NO JAX: a router respawn is handshake-bound (sub-second),
  never compile-bound — which is the whole reason router recovery can
  be fast while replica recovery needs the warm pool.
- :class:`RouterSetClient` — the client side of an N-router set.
  Engine-shaped (``submit() -> Future``, ``example_shape``,
  ``stats()``), so the existing load generators drive a router SET
  unchanged. Connection-refused/reset on one router is retried on the
  next with the existing full-jitter backoff and counted as
  ``router_failovers``; a router that bounced us 429 is retried after
  its ``retry_after_s`` hint. Only when EVERY router is marked down does
  ``submit`` raise the typed
  :class:`~mpi4dl_tpu.fleet.replica.FleetUnreachableError` — which the
  load generator treats as retriable (the supervisor is respawning the
  router behind our back).

Exactly-once across a router death is a three-party contract:
the dead router's fsync'd journal (:mod:`.journal`) names what was
stranded; its successor replays it (dedupe-probe first, re-dispatch
after the grace); and the replica-side idempotency cache
(:class:`~mpi4dl_tpu.fleet.worker._ServedCache`) makes any residual
duplicate — client retry racing the replay — a cache read instead of a
second execution. ``docs/FLEET.md`` has the full failure-domain table.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from mpi4dl_tpu import elastic, telemetry
from mpi4dl_tpu.fleet.replica import (
    FleetUnreachableError,
    ReplicaClient,
    ReplicaDeadline,
    ReplicaError,
    ReplicaQueueFull,
    ReplicaUnreachable,
)


def router_cmd(args: "list[str] | None" = None) -> "list[str]":
    """The router process argv prefix (the fleet supervisor appends
    per-slot ``--name``/``--journal-dir``; :class:`ReplicaProcess`
    appends ``--ready-file``)."""
    return [sys.executable, "-m", "mpi4dl_tpu.fleet.frontdoor"] + list(
        args or ()
    )


def _router_health(router):
    """The router PROCESS's health payload. ``healthy`` is
    process-liveness — a router with zero healthy replicas is still a
    healthy ROUTER (it queues, journals, and sheds with typed errors;
    killing and respawning it would not conjure replicas) — while
    ``replicas_healthy`` carries the replica-availability view the
    in-process :meth:`Router.health_snapshot` reports. The supervisor's
    503-streak remedy therefore only fires on a router that stopped
    ANSWERING, which is the failure replacement actually fixes."""

    def payload() -> dict:
        snap = router.health_snapshot()
        return {
            "healthy": True,
            "replicas_healthy": snap["healthy"],
            "reason": snap["reason"],
            "queue_depth": snap["queue_depth"],
            "replicas": snap["replicas"],
            "pid": os.getpid(),
        }

    return payload


class RouterServer:
    """HTTP surface over one in-process Router: admission + membership.

    router: the :class:`~mpi4dl_tpu.fleet.router.Router` to front.
    port: the submit/admin endpoint (0 = ephemeral).
    metrics_port: telemetry endpoint (None disables; 0 = ephemeral).
    """

    def __init__(self, router, port: int = 0,
                 metrics_port: "int | None" = 0):
        self.router = router
        self._httpd = _submit_server(router, port)
        self.port = self._httpd.server_address[1]
        self.metrics_server = None
        if metrics_port is not None:
            self.metrics_server = telemetry.MetricsServer(
                router.registry, port=metrics_port,
                health=_router_health(router),
                debug=router.stats,
            )

    @property
    def metrics_port(self) -> "int | None":
        return (
            self.metrics_server.port
            if self.metrics_server is not None else None
        )

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self.metrics_server is not None:
            self.metrics_server.close()


def _submit_server(router, port: int) -> ThreadingHTTPServer:
    from mpi4dl_tpu.fleet.router import FleetRequestError
    from mpi4dl_tpu.serve.engine import (
        DeadlineExceededError,
        DrainedError,
        QueueFullError,
    )
    from mpi4dl_tpu.tenancy.model import QuotaExceededError

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 — http.server API
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length).decode())
                if self.path in ("/submit", "/predict"):
                    self._submit(req)
                elif self.path == "/predict_tiled":
                    # Gigapixel passthrough: same RPC/error shapes, the
                    # router dispatches to the replicas' tiled surface.
                    self._submit(req, tiled=True)
                elif self.path == "/replicas":
                    self._replicas(req)
                else:
                    self._reply(404, {"ok": False, "error": "not found"})
            except BrokenPipeError:
                pass  # client gone mid-reply: nothing to answer
            except Exception as e:  # noqa: BLE001 — one bad request must
                # not take a handler thread down
                try:
                    self._reply(500, {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    })
                except Exception:  # noqa: BLE001
                    pass

        def _submit(self, req: dict, tiled: bool = False) -> None:
            x = np.frombuffer(
                base64.b64decode(req["x_b64"]),
                dtype=req.get("dtype", "float32"),
            ).reshape(req["shape"])
            if req.get("retried") and req.get("trace_id"):
                # A failover retry: the client cannot know whether its
                # first attempt executed before the router died. Probe
                # the replicas' served-caches FIRST — a vouching replica
                # answers from its cache (or in-flight future) and the
                # request is never executed a second time on a second
                # replica.
                hit = router.fetch_served(
                    req["trace_id"], x,
                    deadline_s=min(req.get("deadline_s") or 5.0, 5.0),
                    tiled=tiled,
                )
                if hit is not None:
                    logits, payload = hit
                    self._reply(200, dict(
                        payload, router=router.name, cached=True,
                    ))
                    return
            try:
                fut = router.submit(
                    x,
                    deadline_s=req.get("deadline_s"),
                    trace_id=req.get("trace_id"),
                    slo_class=req.get("slo_class"),
                    tiled=tiled,
                    tenant=req.get("tenant"),
                    retried=bool(req.get("retried")),
                )
            except QuotaExceededError as e:
                # Front-door quota shed: same 429 status as queue-full,
                # distinguishable by error kind, retry_after_s = the
                # token bucket's refill time.
                self._reply(429, {
                    "ok": False, "error": "quota_exceeded",
                    "retry_after_s": e.retry_after_s,
                    "tenant": e.tenant,
                    "slo_class": e.slo_class,
                    "shed": True,
                })
                return
            except ValueError as e:
                # Unknown tenant / class outside the tenant's allowlist
                # / bad shape: the caller's bug, not fleet pressure.
                self._reply(400, {
                    "ok": False, "error": f"{type(e).__name__}: {e}",
                })
                return
            except QueueFullError as e:
                self._reply(429, {
                    "ok": False, "error": "queue_full",
                    "retry_after_s": e.retry_after_s,
                    "slo_class": e.slo_class,
                    "shed": e.shed,
                })
                return
            except RuntimeError as e:
                # "router is stopped": alive socket, draining router —
                # the 503 shape the client treats as go-elsewhere.
                self._reply(503, {"ok": False, "error": f"draining: {e}"})
                return
            try:
                logits = fut.result(
                    timeout=(req.get("deadline_s") or 30.0) + 5.0
                )
            except DeadlineExceededError as e:
                self._reply(504, {"ok": False, "error": f"deadline: {e}"})
                return
            except DrainedError as e:
                self._reply(503, {"ok": False, "error": f"drained: {e}"})
                return
            except FleetRequestError as e:
                self._reply(500, {
                    "ok": False,
                    "error": f"fleet: {e}",
                    "attempts": e.attempts,
                    "replicas": list(e.replicas),
                })
                return
            except Exception as e:  # noqa: BLE001 — router-side failure
                self._reply(500, {
                    "ok": False, "error": f"{type(e).__name__}: {e}",
                })
                return
            logits = np.asarray(logits)
            self._reply(200, {
                "ok": True,
                "logits_b64": base64.b64encode(logits.tobytes()).decode(),
                "dtype": str(logits.dtype),
                "shape": list(logits.shape),
                "trace_id": getattr(fut, "trace_id", req.get("trace_id")),
                "engine_e2e_s": getattr(fut, "e2e_latency_s", None),
                "router": router.name,
                "pid": os.getpid(),
            })

        def _replicas(self, req: dict) -> None:
            """Membership feed: the supervisor's add/remove/drain calls
            (and a respawned router's full re-registration)."""
            op = req.get("op")
            if op == "add":
                router.add_replica(
                    req["name"], req["predict_url"],
                    health_url=req.get("health_url"),
                )
                out: dict = {"ok": True}
            elif op == "remove":
                out = {
                    "ok": True,
                    "requeued": router.remove_replica(
                        req["name"], requeue=bool(req.get("requeue", True)),
                    ),
                }
            elif op == "drain":
                out = {
                    "ok": True,
                    "drained": router.drain_replica(
                        req["name"],
                        timeout_s=float(req.get("timeout_s", 10.0)),
                    ),
                }
            else:
                self._reply(400, {"ok": False,
                                  "error": f"unknown op {op!r}"})
                return
            out["replicas"] = router.replicas()
            self._reply(200, out)

        def log_message(self, *a):  # RPC traffic must not spam stderr
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    httpd.daemon_threads = True
    threading.Thread(
        target=httpd.serve_forever, name="mpi4dl-router-submit",
        daemon=True,
    ).start()
    return httpd


class RouterAdminClient:
    """The supervisor's handle on one router process's membership feed."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self._client = ReplicaClient(name, base_url)

    def replica_op(self, op: str, timeout_s: float = 5.0,
                   **payload) -> dict:
        return self._client._post(
            "/replicas", {"op": op, **payload}, timeout_s
        )


class RouterSetClient:
    """Failover client over N router ``/submit`` endpoints.

    Engine-shaped — ``submit(x, deadline_s, trace_id, slo_class)``
    returns a ``Future``; ``example_shape`` / ``stats()`` / ``registry``
    match what the load generators expect — so loadgen drives a router
    SET the way it drives one engine. Per request, a worker thread walks
    the router list round-robin:

    - success / 504 / terminal 500 resolve the future (result or the
      typed error);
    - 429 sleeps the router's ``retry_after_s`` hint (bounded by the
      deadline) and moves to the next router;
    - connection refused/reset/timeout marks THAT router down for
      ``down_s`` and fails over to the next with full-jitter backoff —
      counted per request (``future.failovers``) and in aggregate
      (``stats()["router_failovers"]``).

    ``submit`` itself only raises when admission fails before any RPC:
    client-side pending bound (``QueueFullError``) or every router
    currently marked down (:class:`FleetUnreachableError` — retriable,
    the supervisor respawns routers).
    """

    def __init__(
        self,
        routers,
        example_shape,
        dtype: str = "float32",
        registry=None,
        default_deadline_s: float = 30.0,
        max_pending: int = 512,
        down_s: float = 0.5,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 0.5,
        events=None,
        telemetry_dir: "str | None" = None,
    ):
        if isinstance(routers, dict):
            items = list(routers.items())
        else:
            items = [(f"rt{i}", url) for i, url in enumerate(routers)]
        if not items:
            raise ValueError("RouterSetClient needs at least one router")
        self._routers = [
            (name, ReplicaClient(name, url)) for name, url in items
        ]
        self.example_shape = tuple(int(d) for d in example_shape)
        self._np_dtype = np.dtype(dtype)
        self.registry = (
            registry if registry is not None else telemetry.MetricsRegistry()
        )
        self._default_deadline_s = float(default_deadline_s)
        self._max_pending = int(max_pending)
        self._down_s = float(down_s)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._events = (
            events if events is not None
            else telemetry.JsonlWriter(telemetry_dir)
        )
        self._owns_events = events is None
        self._lock = threading.Lock()
        self._down_until = {name: 0.0 for name, _ in self._routers}
        self._counts = {
            "submitted": 0, "pending": 0, "router_failovers": 0,
            "queue_full_retries": 0,
        }
        self._per_router = {
            name: {"dispatches": 0, "failovers": 0}
            for name, _ in self._routers
        }
        self._rr = 0
        self._stopping = False

    @property
    def events(self):
        return self._events

    def submit(
        self,
        x,
        deadline_s: "float | None" = None,
        trace_id: "str | None" = None,
        slo_class: "str | None" = None,
        tenant: "str | None" = None,
    ):
        from concurrent.futures import Future

        from mpi4dl_tpu.serve.engine import QueueFullError

        if self._stopping:
            raise RuntimeError("router-set client is stopped")
        x = np.asarray(x, self._np_dtype)
        if x.shape != self.example_shape:
            raise ValueError(
                f"example shape {x.shape} != configured {self.example_shape}"
            )
        now = time.monotonic()
        with self._lock:
            if self._counts["pending"] >= self._max_pending:
                raise QueueFullError(
                    f"client pending bound ({self._max_pending}) reached",
                    retry_after_s=0.05,
                )
            down = [t for t in self._down_until.values() if t > now]
            if len(down) == len(self._routers):
                raise FleetUnreachableError(
                    f"all {len(self._routers)} routers marked down",
                    retry_after_s=max(0.05, min(down) - now),
                )
            self._counts["submitted"] += 1
            self._counts["pending"] += 1
            start_at = self._rr
            self._rr += 1
        fut = Future()
        tid = str(trace_id) if trace_id else telemetry.new_trace_id("fleet")
        ddl = (
            deadline_s if deadline_s is not None else self._default_deadline_s
        )
        t = threading.Thread(
            target=self._run_one,
            args=(fut, x, tid, float(ddl), slo_class, tenant, start_at),
            name="mpi4dl-routerset-req", daemon=True,
        )
        t.start()
        return fut

    def _run_one(self, fut, x, tid, deadline_s, slo_class, tenant,
                 start_at) -> None:
        from mpi4dl_tpu.fleet.router import FleetRequestError
        from mpi4dl_tpu.serve.engine import DeadlineExceededError
        from mpi4dl_tpu.tenancy.model import QuotaExceededError

        deadline = time.monotonic() + deadline_s
        n = len(self._routers)
        i = start_at
        failovers = 0
        last_error: "Exception | None" = None
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    fut.failovers = failovers
                    fut.trace_id = tid
                    fut.set_exception(DeadlineExceededError(
                        f"deadline expired across router attempts "
                        f"(last: {last_error})"
                    ))
                    return
                name, client = self._routers[i % n]
                now = time.monotonic()
                with self._lock:
                    skip = (
                        self._down_until[name] > now
                        and any(
                            t <= now for t in self._down_until.values()
                        )
                    )
                if skip:
                    i += 1
                    continue
                try:
                    with self._lock:
                        self._per_router[name]["dispatches"] += 1
                    logits, payload = client.predict(
                        x, tid, deadline_s=remaining,
                        timeout_s=remaining + 1.0, slo_class=slo_class,
                        tenant=tenant,
                        # After any unreachable bounce the first attempt
                        # MAY have executed — the router must probe the
                        # served-caches before dispatching again.
                        retried=failovers > 0,
                    )
                except ReplicaQueueFull as e:
                    if e.kind == "quota_exceeded":
                        # Typed quota shed, surfaced to the caller with
                        # the bucket's refill hint. No failover: every
                        # router refills its own buckets, so shopping
                        # the request around would multiply the
                        # tenant's effective quota.
                        fut.failovers = failovers
                        fut.trace_id = tid
                        fut.set_exception(QuotaExceededError(
                            str(e), tenant=e.tenant or tenant or "default",
                            retry_after_s=e.retry_after_s,
                            slo_class=slo_class,
                        ))
                        return
                    last_error = e
                    with self._lock:
                        self._counts["queue_full_retries"] += 1
                    time.sleep(min(
                        max(0.0, remaining),
                        e.retry_after_s or self._backoff_base_s,
                    ))
                    i += 1  # spread the retry over the set
                    continue
                except ReplicaDeadline as e:
                    fut.failovers = failovers
                    fut.trace_id = tid
                    fut.set_exception(DeadlineExceededError(str(e)))
                    return
                except ReplicaUnreachable as e:
                    # The failover path: THIS router is down (killed,
                    # draining, mid-respawn) — mark it, back off with
                    # full jitter, try the next one. The same trace id
                    # travels, so the replica-side cache dedupes any
                    # half-finished work the dead router left behind.
                    last_error = e
                    failovers += 1
                    with self._lock:
                        self._counts["router_failovers"] += 1
                        self._per_router[name]["failovers"] += 1
                        self._down_until[name] = (
                            time.monotonic() + self._down_s
                        )
                    i += 1
                    time.sleep(min(
                        max(0.0, remaining),
                        elastic.full_jitter_backoff(
                            failovers, base_s=self._backoff_base_s,
                            max_s=self._backoff_max_s,
                        ),
                    ))
                    continue
                except ReplicaError as e:
                    # Terminal 500: the router already spent ITS retry
                    # budget across replicas — don't multiply budgets.
                    fut.failovers = failovers
                    fut.trace_id = tid
                    fut.set_exception(FleetRequestError(
                        str(e), last_error=e
                    ))
                    return
                fut.failovers = failovers
                fut.trace_id = payload.get("trace_id", tid)
                if payload.get("engine_e2e_s") is not None:
                    fut.e2e_latency_s = payload["engine_e2e_s"]
                fut.set_result(logits)
                return
        except Exception as e:  # noqa: BLE001 — a dying worker thread
            # must never strand its caller's future
            fut.failovers = failovers
            fut.trace_id = tid
            if not fut.done():
                fut.set_exception(e)
        finally:
            with self._lock:
                self._counts["pending"] -= 1

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["per_router"] = {
                k: dict(v) for k, v in self._per_router.items()
            }
        return out

    def close(self) -> None:
        self._stopping = True
        if self._owns_events:
            self._events.close()


# -- the router process --------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.fleet.frontdoor",
        description="mpi4dl_tpu HA front door: one fleet router as a "
                    "supervised process (no JAX — respawn is "
                    "handshake-bound)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--ready-file", required=True,
                   help="JSON handshake file written (atomically) once "
                        "the journal is replayed and the ports are bound")
    p.add_argument("--name", default="rt0",
                   help="stable router identity (journal file name; a "
                        "respawned incarnation recovers its "
                        "predecessor's journal by it)")
    p.add_argument("--port", type=int, default=0,
                   help="submit/admin endpoint port (0 = ephemeral)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="telemetry endpoint port (0 = ephemeral); "
                        "federation merges it like any replica")
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--inflight-per-replica", type=int, default=4)
    p.add_argument("--default-deadline-s", type=float, default=30.0)
    p.add_argument("--health-interval", type=float, default=0.25)
    p.add_argument("--load-slack", type=int, default=4,
                   help="load-aware pull slack: a replica this many "
                        "queued requests above the least-loaded one "
                        "stops pulling (negative disables)")
    p.add_argument("--journal-dir", default=None,
                   help="recovery journals land here as "
                        "<name>.journal.jsonl; unset disables "
                        "journaling (and with it router-death replay)")
    p.add_argument("--replay-grace", type=float, default=1.5,
                   help="seconds replay parks orphans polling the "
                        "replicas' served-caches before re-dispatching")
    p.add_argument("--slo-classes", default=None, metavar="SPEC")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="tenant quota/weight specs "
                        "(NAME=RPS:BURST[:WEIGHT][@CLASSES], comma-"
                        "separated; NAME=none = unlimited). Each router "
                        "refills its own buckets: with R routers a "
                        "tenant's effective front-door rate is R x its "
                        "spec")
    p.add_argument("--telemetry-dir", default=None)
    p.add_argument("--replica", action="append", default=[],
                   metavar="NAME=PREDICT_URL[,HEALTH_URL]",
                   help="static replica registration (standalone use; "
                        "under a FleetSupervisor membership arrives via "
                        "POST /replicas instead)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.fleet.router import Router

    size = args.image_size
    journal_path = None
    if args.journal_dir:
        journal_path = os.path.join(
            args.journal_dir, f"{args.name}.journal.jsonl"
        )
    router = Router(
        example_shape=(size, size, 3),
        dtype=args.dtype,
        name=args.name,
        max_queue=args.max_queue,
        default_deadline_s=args.default_deadline_s,
        max_attempts=args.max_attempts,
        inflight_per_replica=args.inflight_per_replica,
        health_interval_s=args.health_interval,
        telemetry_dir=args.telemetry_dir,
        slo_classes=args.slo_classes,
        tenants=args.tenants,
        journal_path=journal_path,
        replay_grace_s=args.replay_grace,
        load_slack=args.load_slack if args.load_slack >= 0 else None,
    )
    replayed = router.replay_journal()
    if replayed:
        print(
            f"# {args.name}: replaying {replayed} journal orphan(s) from "
            "a dead predecessor", file=sys.stderr, flush=True,
        )
    for spec in args.replica:
        name, _, urls = spec.partition("=")
        predict_url, _, health_url = urls.partition(",")
        router.add_replica(name, predict_url,
                           health_url=health_url or None)

    server = RouterServer(router, port=args.port,
                          metrics_port=args.metrics_port)

    heartbeat = None
    hb_path = elastic.heartbeat_path_from_env()
    if hb_path:
        # Plain (ungated) beats: a router with zero healthy replicas is
        # still doing its job (journaling + queueing); only process
        # death should stale its heartbeat.
        heartbeat = elastic.HeartbeatReporter(hb_path, interval_s=0.2)
        heartbeat.start()

    stop_evt = threading.Event()

    def _sigterm(signum, frame):  # noqa: ARG001 — signal API
        stop_evt.set()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)

    ready = {
        "pid": os.getpid(),
        "predict_port": server.port,
        "metrics_port": server.metrics_port,
        "role": "router",
    }
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
    os.replace(tmp, args.ready_file)
    print(f"# router ready: {json.dumps(ready)}", file=sys.stderr,
          flush=True)

    stop_evt.wait()
    router.stop(drain=True, timeout_s=10.0)
    server.close()
    if heartbeat is not None:
        heartbeat.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
