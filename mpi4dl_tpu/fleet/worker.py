"""Replica worker: one ServingEngine + predict HTTP server, per process.

``python -m mpi4dl_tpu.fleet.worker --ready-file /run/r0.ready.json``
builds a synthetic calibrated model (the same zero-artifact path as
``python -m mpi4dl_tpu.serve``), AOT-warms the engine, then serves:

- ``POST /predict`` — blocking predict RPC (base64 float bytes in/out;
  the router's :class:`~mpi4dl_tpu.fleet.replica.ReplicaClient` is the
  other side). Engine admission failures map to structured HTTP errors:
  429 queue-full (with the engine's ``retry_after_s`` cadence hint),
  504 deadline, 503 draining. Idempotent by trace id
  (:class:`_ServedCache`): a duplicate arrival — a client's failover
  retry through a second router, or a successor router replaying a dead
  router's journal — answers from the cached result (``"cached": true``)
  or joins the in-flight future instead of executing twice.
- ``POST /served`` — the dedupe probe: which of the posted trace ids
  this replica served or has in flight (journal replay asks before
  re-dispatching an orphan).
- ``POST /chaos`` — the fault-injection surface
  (:mod:`mpi4dl_tpu.fleet.chaos`): ``wedge`` blocks the batcher's
  dispatch mid-loop (submit path and HTTP threads stay alive — the
  wedged-but-alive shape only the watchdog-gated heartbeat exposes),
  ``blackhole_healthz`` makes ``/healthz`` hang, ``delay_scrape`` adds
  latency to ``/snapshotz``, ``delay_predict`` adds latency to every
  dispatched batch (the straggler shape: healthy but slow — only
  ``fleet_replica_skew`` names it), ``unwedge`` recovers.
- the standard telemetry surface (``/metrics``, ``/snapshotz``,
  ``/healthz``, ``/debugz``) — built HERE rather than via
  ``metrics_port=`` so the chaos hooks can wrap the health callable and
  registry, and so ``/healthz`` carries the live ``queue_depth`` +
  ``draining`` fields the router's one-endpoint scrape reads.

Ready handshake: once everything is up, the ports land atomically in
``--ready-file`` (``os.replace`` — a partially-written handshake can
never be read). Supervision: the spawning fleet supervisor sets
``MPI4DL_TPU_HEARTBEAT``; the health-gated
:class:`~mpi4dl_tpu.elastic.HeartbeatReporter` goes silent when the
watchdog trips, which is how a wedged batcher gets this process killed
and replaced. SIGTERM drains: stop admissions (503), flush in-flight,
exit 0.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.fleet.worker",
        description="mpi4dl_tpu fleet replica worker (one engine, one chip)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--ready-file", required=True,
                   help="JSON handshake file written (atomically) once "
                        "the engine is warm and the ports are bound")
    p.add_argument("--port", type=int, default=0,
                   help="predict endpoint port (0 = ephemeral)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="telemetry endpoint port (0 = ephemeral)")
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--depth", type=int, default=None,
                   help="synthetic ResNet-v2 depth (9n+2); default tiny")
    p.add_argument("--mesh", default=None, metavar="HxW",
                   help="claim a tile_h x tile_w device subset and run "
                        "the engine's forward spatially sharded over it "
                        "(serve/sharded.py; the synthetic model becomes "
                        "a spatial ResNet-v1 front, --depth then 6n+2). "
                        "The mesh shape rides the /healthz payload, so "
                        "shard-for-model-size and replicate-for-traffic "
                        "are visible as two orthogonal fleet axes")
    p.add_argument("--spatial-cells", type=int, default=2,
                   help="leading spatial cells of the sharded synthetic "
                        "model (--mesh only)")
    p.add_argument("--tiled", default=None, metavar="HxW",
                   help="additionally serve POST /predict_tiled: a "
                        "second engine streaming halo-correct overlap-"
                        "read tiles of HxW images through one chip at "
                        "bounded memory (serve/tiled.py), with its own "
                        "'tiled' SLO class — the gigapixel surface the "
                        "router's tiled passthrough targets")
    p.add_argument("--tile", type=int, default=None,
                   help="tiled core extent in px (--tiled only; "
                        "default: a quarter of the image)")
    p.add_argument("--tile-batch", type=int, default=1,
                   help="largest power-of-two TILE bucket of the tiled "
                        "forward (--tiled only; 1 = the exact default)")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--default-deadline-s", type=float, default=30.0)
    p.add_argument("--watchdog-factor", type=float, default=20.0)
    p.add_argument("--watchdog-min-timeout", type=float, default=2.0,
                   help="floor of the stall detector — drills shrink it "
                        "so a wedge is declared fast")
    p.add_argument("--telemetry-dir", default=None)
    p.add_argument("--tail-factor", type=float, default=4.0,
                   help="slow-request trip multiplier over the rolling "
                        "p99 (telemetry/tail.py; drills shrink it so a "
                        "delayed replica's tail.samples capture fast)")
    p.add_argument("--tail-min-interval", type=float, default=1.0,
                   help="rate limit between captured tail.samples, "
                        "seconds")
    p.add_argument("--slo-classes", default=None, metavar="SPEC",
                   help="named SLO classes for the engine scheduler "
                        "(NAME=THRESHOLD[:TARGET_PCT][@DEADLINE], comma-"
                        "separated) — must match the router's classes "
                        "for slo_class propagation")
    p.add_argument("--scheduler", choices=("edf", "fifo"), default="edf",
                   help="engine batch former (edf = continuous "
                        "scheduler; fifo = windowed baseline)")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="tenant quota/weight specs for the engine "
                        "(NAME=RPS:BURST[:WEIGHT][@CLASSES], comma-"
                        "separated; NAME=none = unlimited) — must match "
                        "the router's tenants for tenant propagation")
    p.add_argument("--canary-interval", type=float, default=10.0,
                   help="numerics-sentinel cadence, seconds "
                        "(telemetry/canary.py): golden probe through "
                        "the real dispatch path + params-checksum "
                        "re-audit; a divergence FENCES this replica "
                        "(healthz unhealthy + /predict 503) until the "
                        "supervisor respawns it. 0 disables the daemon "
                        "(references and the load checksum still "
                        "record)")
    return p


class _ChaosState:
    """The worker-side fault switches the /chaos endpoint flips."""

    def __init__(self, engine=None):
        self.wedged = threading.Event()
        self.blackhole_healthz = False
        self.scrape_delay_s = 0.0
        self.predict_delay_s = 0.0
        # The corrupt drill's engine handle (set in main(); None in the
        # soft-action unit tests that never corrupt).
        self.engine = engine

    def apply(self, action: str, seconds: float = 0.0) -> dict:
        if action == "wedge":
            self.wedged.set()
        elif action == "unwedge":
            self.wedged.clear()
        elif action == "blackhole_healthz":
            self.blackhole_healthz = True
        elif action == "delay_scrape":
            self.scrape_delay_s = float(seconds)
        elif action == "delay_predict":
            self.predict_delay_s = float(seconds)
        elif action == "corrupt_params":
            # The corrupt drill: flip bits in the LIVE param buffer
            # (telemetry/canary.py) — the spec's BITS rides the generic
            # seconds field. Deliberately leaves checksums/references
            # untouched: the sentinel must discover the damage.
            if self.engine is None:
                raise ValueError("no engine bound for corrupt_params")
            forensics = self.engine.corrupt_params(
                bits=int(seconds) if seconds else 3
            )
            return {"ok": True, "applied": action, "forensics": forensics}
        else:
            raise ValueError(f"unknown chaos action {action!r}")
        return {"ok": True, "applied": action}

    def gate_dispatch(self) -> None:
        """Called inside the batcher's dispatch: while wedged, block —
        the loop thread hangs exactly like a stuck device call, while
        every other thread in the process stays alive. The straggler
        drill's delay sleeps here too: every batch pays it, so the
        replica's OWN latency histogram inflates (which is exactly what
        federation-side skew scoring reads) while health stays green."""
        while self.wedged.is_set():
            time.sleep(0.05)
        if self.predict_delay_s > 0:
            time.sleep(self.predict_delay_s)


class _DelayedRegistry:
    """Registry proxy whose snapshot() honors the delay-scrape drill —
    slow telemetry must slow the FEDERATION view (scrape timeouts,
    stale merges), never the serving path, which keeps writing to the
    real registry underneath."""

    def __init__(self, registry, chaos: _ChaosState):
        self._registry = registry
        self._chaos = chaos

    def snapshot(self):
        if self._chaos.scrape_delay_s > 0:
            time.sleep(self._chaos.scrape_delay_s)
        return self._registry.snapshot()

    def __getattr__(self, name):
        return getattr(self._registry, name)


class _ServedCache:
    """Replica-side idempotency registry, keyed by trace id.

    The exactly-once guarantee across a ROUTER death needs the replica's
    help: the same trace id can legitimately arrive twice — the client's
    failover retry through a surviving router, and the dead router's
    successor re-dispatching its journal orphans. This cache makes the
    second arrival a read, not a second execution: completed requests
    answer from the cached payload, concurrent duplicates join the
    in-flight engine future. Bounded FIFO eviction; the window only has
    to outlive the replay grace + client retry horizon, not history."""

    def __init__(self, capacity: int = 4096):
        import collections

        self._done: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._inflight: "dict[str, object]" = {}
        self._capacity = int(capacity)
        self._lock = threading.Lock()

    def lookup(self, trace_id: str):
        """(cached_payload, inflight_future) — at most one is non-None."""
        with self._lock:
            payload = self._done.get(trace_id)
            if payload is not None:
                return payload, None
            return None, self._inflight.get(trace_id)

    def begin(self, trace_id: str, future) -> None:
        with self._lock:
            self._inflight[trace_id] = future

    def finish(self, trace_id: str, payload: "dict | None") -> None:
        """Complete an in-flight entry; only SUCCESS payloads are cached
        (queue-full/deadline outcomes stay retriable by design)."""
        with self._lock:
            self._inflight.pop(trace_id, None)
            if payload is not None:
                self._done[trace_id] = payload
                while len(self._done) > self._capacity:
                    self._done.popitem(last=False)

    def served(self, trace_ids) -> "list[str]":
        with self._lock:
            return [
                t for t in trace_ids
                if t in self._done or t in self._inflight
            ]


class _NumericsFence:
    """The worker's quarantine latch: set by the canary's on_failure
    callback the moment the sentinel proves corruption. Once set, this
    replica refuses /predict (503 ``numerics_fenced``) — checked at
    admission AND again when a result comes back, so an answer computed
    before detection but delivered after it is withheld too. The router
    treats the 503 like any unreachable replica (mark unhealthy +
    requeue elsewhere); the supervisor sees healthz go red and respawns.
    One-way by design: only a process replacement (fresh params, fresh
    references) clears a numerics fence."""

    def __init__(self):
        self.fenced = threading.Event()
        self.evidence: "dict | None" = None
        self._lock = threading.Lock()

    def trip(self, attrs: dict) -> None:
        with self._lock:
            if self.evidence is None:
                self.evidence = {"ts": time.time(), **attrs}
        self.fenced.set()

    def view(self) -> "dict | None":
        with self._lock:
            return dict(self.evidence) if self.evidence else None


def _predict_server(engine, chaos: _ChaosState, draining: threading.Event,
                    port: int, tiled_engine=None,
                    fence: "_NumericsFence | None" = None,
                    ) -> ThreadingHTTPServer:
    from mpi4dl_tpu.serve.engine import (
        DeadlineExceededError,
        DrainedError,
        QueueFullError,
    )
    from mpi4dl_tpu.tenancy.model import QuotaExceededError

    cache = _ServedCache()

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 — http.server API
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length).decode())
                if self.path == "/predict":
                    self._predict(req)
                elif self.path == "/predict_tiled":
                    # The gigapixel surface: same RPC shape + idempotency
                    # cache, answered by the tile-streaming engine.
                    if tiled_engine is None:
                        self._reply(404, {
                            "ok": False,
                            "error": "no tiled engine (spawn with --tiled)",
                        })
                    else:
                        self._predict(req, engine=tiled_engine)
                elif self.path == "/served":
                    self._reply(200, {
                        "ok": True,
                        "served": cache.served(req.get("trace_ids", ())),
                    })
                elif self.path == "/chaos":
                    self._reply(200, chaos.apply(
                        req["action"], req.get("seconds", 0.0)
                    ))
                else:
                    self._reply(404, {"ok": False, "error": "not found"})
            except BrokenPipeError:
                pass  # client gone (a killed router): nothing to answer
            except Exception as e:  # noqa: BLE001 — one bad request must
                # not kill the handler thread pool
                try:
                    self._reply(500, {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    })
                except Exception:  # noqa: BLE001
                    pass

        def _predict(self, req: dict, engine=engine) -> None:
            if draining.is_set():
                self._reply(503, {"ok": False, "error": "draining"})
                return
            if fence is not None and fence.fenced.is_set():
                # Admission-side of the numerics fence: covers fresh
                # submits AND the idempotency-cache/join fast paths — a
                # corrupted replica must not answer even from cache.
                self._reply(503, {"ok": False, "error": "numerics_fenced"})
                return
            # Idempotency by trace id: a duplicate of a COMPLETED request
            # (client failover retry or a successor router's journal
            # replay) answers from the cache; a duplicate of an IN-FLIGHT
            # one joins the live engine future — this engine executes a
            # given trace id at most once.
            tid = req.get("trace_id")
            joined = None
            if tid:
                payload, joined = cache.lookup(tid)
                if payload is not None:
                    self._reply(200, dict(payload, cached=True))
                    return
            if joined is not None:
                fut = joined
            else:
                x = np.frombuffer(
                    base64.b64decode(req["x_b64"]), dtype=req.get(
                        "dtype", "float32"
                    )
                ).reshape(req["shape"])
                try:
                    fut = engine.submit(
                        x,
                        deadline_s=req.get("deadline_s"),
                        trace_id=tid,
                        slo_class=req.get("slo_class"),
                        # Only tenanted traffic forwards the kwarg, so
                        # plain engines (and test stubs) keep working.
                        **(
                            {"tenant": req["tenant"]}
                            if req.get("tenant") is not None else {}
                        ),
                    )
                except QuotaExceededError as e:
                    # Engine-edge quota shed: typed 429 carrying the
                    # token bucket's refill time, distinguishable from
                    # a physically-full queue by error kind.
                    self._reply(429, {
                        "ok": False, "error": "quota_exceeded",
                        "retry_after_s": e.retry_after_s,
                        "tenant": e.tenant,
                        "slo_class": e.slo_class,
                        "shed": True,
                    })
                    return
                except QueueFullError as e:
                    self._reply(429, {
                        "ok": False, "error": "queue_full",
                        "retry_after_s": e.retry_after_s,
                        "slo_class": e.slo_class,
                        "shed": e.shed,
                    })
                    return
                if tid:
                    cache.begin(tid, fut)
            try:
                # The engine enforces the deadline; +5s grace means a
                # late result still surfaces as the engine's own typed
                # outcome rather than a worker-side timeout guess.
                logits = fut.result(
                    timeout=(req.get("deadline_s") or 30.0) + 5.0
                )
            except DeadlineExceededError as e:
                if tid:
                    cache.finish(tid, None)  # terminal but NOT cacheable
                self._reply(504, {"ok": False, "error": f"deadline: {e}"})
                return
            except DrainedError as e:
                if tid:
                    cache.finish(tid, None)
                self._reply(503, {"ok": False, "error": f"drained: {e}"})
                return
            except Exception as e:  # noqa: BLE001 — engine-side failure
                if tid:
                    cache.finish(tid, None)
                self._reply(500, {
                    "ok": False, "error": f"{type(e).__name__}: {e}",
                })
                return
            if fence is not None and fence.fenced.is_set():
                # Response-side re-check: the answer resolved, but the
                # sentinel proved corruption while it was in flight —
                # the computation is suspect, so it is withheld. The
                # router requeues on a healthy replica; exactly-once
                # holds because nothing was delivered.
                if tid:
                    cache.finish(tid, None)
                self._reply(503, {"ok": False, "error": "numerics_fenced"})
                return
            logits = np.asarray(logits)
            payload = {
                "ok": True,
                "logits_b64": base64.b64encode(logits.tobytes()).decode(),
                "dtype": str(logits.dtype),
                "shape": list(logits.shape),
                "trace_id": getattr(fut, "trace_id", tid),
                "engine_e2e_s": getattr(fut, "e2e_latency_s", None),
                "pid": os.getpid(),
            }
            if tid:
                cache.finish(tid, payload)
            self._reply(
                200, dict(payload, cached=True) if joined is not None
                else payload
            )

        def log_message(self, *a):  # RPC traffic must not spam stderr
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    httpd.daemon_threads = True
    threading.Thread(
        target=httpd.serve_forever, name="mpi4dl-replica-predict",
        daemon=True,
    ).start()
    return httpd


def main(argv=None) -> int:
    # Cold-start phase stamps (monotonic; only DURATIONS leave the
    # process — clock-skew-safe for the supervisor's recovery math).
    t_start = time.monotonic()
    args = build_parser().parse_args(argv)

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    mesh_shape = None
    if args.mesh:
        from mpi4dl_tpu.serve.sharded import parse_mesh

        mesh_shape = parse_mesh(args.mesh)
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # The tile mesh needs virtual devices before backend init.
            from mpi4dl_tpu.compat import set_cpu_devices

            set_cpu_devices(max(8, mesh_shape[0] * mesh_shape[1]))

    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu import elastic, telemetry
    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.utils import get_depth

    t_imports = time.monotonic()
    size = args.image_size
    engine_kw = dict(
        max_batch=args.max_batch, max_wait_s=args.max_wait_ms / 1e3,
        max_queue=args.max_queue,
        default_deadline_s=args.default_deadline_s,
        telemetry_dir=args.telemetry_dir,
        watchdog_factor=args.watchdog_factor or None,
        watchdog_min_timeout_s=args.watchdog_min_timeout,
        tail_factor=args.tail_factor,
        tail_min_interval_s=args.tail_min_interval,
        slo_classes=args.slo_classes,
        scheduler=args.scheduler,
        tenants=args.tenants,
        canary_interval_s=args.canary_interval or None,
    )
    if mesh_shape is not None:
        # Sharded replica: this process claims a device SUBSET shaped
        # tile_h x tile_w and serves the spatially-partitioned forward
        # on it — the fleet's replicate-for-traffic axis stays above.
        from mpi4dl_tpu.serve.sharded import synthetic_sharded_engine

        engine = synthetic_sharded_engine(
            mesh_shape, image_size=size,
            depth=args.depth if args.depth is not None else 8,
            num_classes=args.classes, spatial_cells=args.spatial_cells,
            **engine_kw,
        )
    else:
        depth = args.depth if args.depth is not None else get_depth(2, 1)
        cells = get_resnet_v2(
            depth=depth, num_classes=args.classes, pool_kernel=size // 4
        )
        rng = np.random.default_rng(0)
        params = init_cells(
            cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
        )
        stats = collect_batch_stats(
            cells, params,
            [jnp.asarray(
                rng.standard_normal((4, size, size, 3)), jnp.float32
            )],
        )
        engine = ServingEngine(
            cells, params, stats, example_shape=(size, size, 3),
            **engine_kw,
        )

    tiled_engine = None
    if args.tiled:
        # The gigapixel surface rides a SECOND engine (its own scheduler
        # classes, buckets, and registry — counters of 60-second tiled
        # requests must not fold into the interactive engine's series;
        # its geometry/latency facts surface on /healthz).
        from mpi4dl_tpu.serve.__main__ import _parse_tiled_size
        from mpi4dl_tpu.serve.tiled import synthetic_tiled_engine

        tiled_engine = synthetic_tiled_engine(
            _parse_tiled_size(args.tiled), tile=args.tile,
            depth=8, num_classes=args.classes,
            tile_batch=args.tile_batch,
            max_queue=args.max_queue,
            default_deadline_s=max(args.default_deadline_s, 120.0),
            watchdog_factor=args.watchdog_factor or None,
            watchdog_min_timeout_s=args.watchdog_min_timeout,
        )
        tiled_engine.start()

    t_engine = time.monotonic()
    # Worker-side recovery phase decomposition (telemetry.coldstart
    # vocabulary, spawn = the supervisor-side residual): the AOT phase
    # sums come from the engines' own warm-up ledgers, construct is the
    # remaining engine-build wall (params init, BN calibration,
    # device_put), ready is filled in at the handshake write below.
    warmups = [engine.warmup_stats()]
    if tiled_engine is not None:
        warmups.append(tiled_engine.warmup_stats())
    compile_s = sum(
        w["totals"]["trace_s"] + w["totals"]["compile_s"] for w in warmups
    )
    warm_s = sum(w["totals"]["warm_s"] for w in warmups)
    phases = {
        "import": round(t_imports - t_start, 6),
        "construct": round(
            max(0.0, (t_engine - t_imports) - compile_s - warm_s), 6
        ),
        "compile": round(compile_s, 6),
        "warm": round(warm_s, 6),
    }

    chaos = _ChaosState(engine=engine)
    # Chaos seam: the wedge gate runs INSIDE the batcher thread's
    # dispatch, upstream of the real one — a wedged batcher with live
    # submit/HTTP/heartbeat threads, which is the failure shape the
    # health-gated heartbeat exists to expose.
    orig_dispatch = engine._dispatch

    def gated_dispatch(reqs):
        chaos.gate_dispatch()
        return orig_dispatch(reqs)

    engine._dispatch = gated_dispatch

    draining = threading.Event()
    fence = _NumericsFence()

    def _on_canary_failure(attrs: dict) -> None:
        # The sentinel proved corruption: latch the fence (503s every
        # /predict from here on) and flip the engine's own health flag
        # so /healthz, the serve_healthy gauge, and the heartbeat all
        # tell the same story the supervisor acts on.
        fence.trip(attrs)
        engine.health.set_unhealthy(
            f"numerics divergence: {attrs.get('check')}"
        )

    engine.canary.on_failure(_on_canary_failure)

    def health_payload() -> dict:
        if chaos.blackhole_healthz:
            time.sleep(3600)  # the probe black-hole drill
        snap = dict(engine.health.snapshot())
        snap["queue_depth"] = engine.queue_depth()
        snap["draining"] = draining.is_set()
        snap["pid"] = os.getpid()
        # Numerics-sentinel surface: the params checksum + canary
        # verdicts (federation compares these across replicas), and the
        # fence latch. A fenced replica is unhealthy REGARDLESS of the
        # underlying HealthState — the watchdog may flip that back to
        # healthy when residual batches complete, but a numerics fence
        # only clears by process replacement.
        snap["numerics"] = engine.canary.view()
        snap["fenced"] = fence.fenced.is_set()
        if fence.fenced.is_set():
            snap["healthy"] = False
            snap["fence_evidence"] = fence.view()
        # The device subset this replica claims: (1,1) = one chip,
        # tile_h x tile_w = a sharded forward. Routers/operators read
        # shard-for-model-size here, orthogonal to replica count.
        snap["mesh"] = list(engine.mesh_shape)
        # Cold-start attribution: the same phase durations the ready
        # handshake carried, plus the live warm-up decomposition — the
        # supervisor (or an operator) reads where THIS incarnation's
        # spawn time went off the one-endpoint scrape.
        snap["phases"] = dict(phases)
        snap["warmup"] = engine.warmup_stats()
        if tiled_engine is not None:
            # The gigapixel surface this replica additionally serves:
            # routers and operators read the geometry (and live request/
            # tile totals) off the same one-endpoint scrape.
            snap["tiled"] = tiled_engine.stats().get("tiled")
        return snap

    def numerics_payload() -> dict:
        snap = dict(engine.canary.view())
        snap["fenced"] = fence.fenced.is_set()
        return snap

    # Engine-side incident engine: rides the engine's OWN SLO evaluator
    # (when configured) exactly like the federation manager rides the
    # aggregator — a single-replica deployment still gets incidents,
    # and this replica's flight dumps file under the open incident.
    incidents = None
    if engine.slo is not None:
        incidents = telemetry.IncidentManager(
            engine.slo.state,
            registry=engine.registry,
            events=engine.events,
            flight=engine.flight,
            source="engine",
        )
        engine.flight.incident = incidents.open_incident_id
        incidents.start(interval_s=0.5)

    metrics_server = telemetry.MetricsServer(
        _DelayedRegistry(engine.registry, chaos),
        port=args.metrics_port,
        health=health_payload,
        debug=engine._debugz,
        alerts=engine.slo.state if engine.slo is not None else None,
        numerics=numerics_payload,
        incidents=incidents.state if incidents is not None else None,
    )
    predict_httpd = _predict_server(
        engine, chaos, draining, args.port, tiled_engine=tiled_engine,
        fence=fence,
    )

    heartbeat = None
    hb_path = elastic.heartbeat_path_from_env()
    if hb_path:
        heartbeat = elastic.HeartbeatReporter(
            hb_path, health=engine.health, watchdog=engine.watchdog,
            interval_s=0.2,
        )
        heartbeat.start()

    engine.start()

    stop_evt = threading.Event()

    def _sigterm(signum, frame):  # noqa: ARG001 — signal API
        draining.set()
        stop_evt.set()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)

    phases["ready"] = round(time.monotonic() - t_engine, 6)
    # The footprint ledger (per-executable peaks + fingerprints +
    # trace/compile/warm seconds) lands next to the ready file so a
    # fleet-wide `analyze coldstart` has its inputs even after this
    # process dies; the path rides the handshake.
    ledger_path = args.ready_file + ".ledger.json"
    try:
        entries = engine.memory_ledger.entries()
        if tiled_engine is not None:
            entries += tiled_engine.memory_ledger.entries()
        with open(ledger_path + ".tmp", "w") as f:
            json.dump({"entries": entries}, f, indent=2)
        os.replace(ledger_path + ".tmp", ledger_path)
    except OSError:
        ledger_path = None

    ready = {
        "pid": os.getpid(),
        "predict_port": predict_httpd.server_address[1],
        "metrics_port": metrics_server.port,
        "phases": phases,
        "ledger": ledger_path,
        # The load-time parameter-integrity baseline: a supervisor (or
        # operator) can compare this across a fleet's handshakes before
        # any traffic flows — same checkpoint ⇒ same checksum.
        "params_checksum": engine.canary.load_checksum,
    }
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
    os.replace(tmp, args.ready_file)
    print(f"# replica ready: {json.dumps(ready)}", file=sys.stderr,
          flush=True)

    stop_evt.wait()
    # Graceful drain: admissions already answer 503; serve what's
    # queued, then tear down.
    engine.stop(drain=True)
    if tiled_engine is not None:
        tiled_engine.stop(drain=True)
    predict_httpd.shutdown()
    metrics_server.close()
    if incidents is not None:
        incidents.close()
    if heartbeat is not None:
        heartbeat.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
