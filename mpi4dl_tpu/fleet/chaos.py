"""Fault-injection harness: the drills the fleet must survive, on tap.

A fault-tolerance layer that has never seen a fault is a hypothesis, not
a feature. This module makes the failure modes the router + supervisor
claim to handle injectable on demand — the SAME drills the tier-1 tests
run (``tests/test_fleet.py``), exposed as ``--chaos`` CLI flags so an
operator can rehearse them against a live fleet:

- ``kill`` — ``SIGKILL`` the replica process (no cleanup, no flush: the
  hard-down case; in-flight RPCs die with the sockets and the router
  requeues them on survivors);
- ``wedge`` — block the replica's batcher mid-loop while its submit
  path, HTTP threads, and heartbeat *machinery* stay alive (the
  wedged-but-alive shape, SURVEY §5.3 — only the watchdog-gated
  heartbeat going silent exposes it);
- ``blackhole`` — make the replica's ``/healthz`` hang instead of
  answering (probe black-hole: the router's scrape must time out and
  count it down, not wait forever);
- ``delay-scrape`` — add seconds of latency to ``/snapshotz`` (slow
  telemetry must degrade the *federation view*, never the serving path);
- ``delay`` — add seconds of latency to every batch the replica's
  engine dispatches (the STRAGGLER shape: the replica stays healthy and
  keeps serving, just slowly — only the federation-side
  ``fleet_replica_skew`` scoring names it; docs/OBSERVABILITY.md "Tail
  forensics");
- ``flood`` — offer a burst of EXTRA traffic under a named tenant (the
  NOISY-NEIGHBOR shape: the front door's token-bucket quota must shed
  the flood with ``retry_after_s`` before it occupies queue slots, and
  the deficit-weighted fill must hold the victim tenants' p99 —
  docs/SERVING.md "Multi-tenancy");
- ``corrupt`` — flip bits in the replica's LIVE parameter buffer
  (the SILENT-CORRUPTION shape: full availability, wrong answers —
  only the numerics sentinel's canary/checksum audit names it; the
  drill proves detect → ``numerics_divergence`` page → quarantine —
  docs/OBSERVABILITY.md "Numerics").

Spec grammar (``--chaos``, repeatable)::

    ACTION[:TARGET][@AT[s]]

    kill:1          SIGKILL replica index 1 (at the default +1.0s)
    kill:router     SIGKILL router process 0 (the ROUTER failure domain:
                    the successor replays the journal — docs/FLEET.md)
    kill:router:1   SIGKILL router process 1
    wedge:0@2.5     wedge replica 0's batcher 2.5s into the load run
    delay-scrape:1=3@2   delay r1's /snapshotz by 3s from t=+2s
    delay:1=0.3@2   slow r1's serving path by 0.3s/batch from t=+2s
    flood:bulk=500@2     offer 500 rps AS TENANT 'bulk' from t=+2s
                         (a fixed 2s burst through the front door)
    corrupt:1@2     flip 3 bits in r1's largest param leaf at t=+2s
    corrupt:1=8@2   ... 8 bits

``TARGET`` is the replica *slot index* (default 0) — or
``router[:INDEX]`` to target a front-door router process instead
(``kill`` only: routers have no in-process ``/chaos`` surface; their
failure mode IS hard death) — or, for ``flood``, the tenant NAME to
flood as. ``AT`` is seconds after the load run starts; ``=SECONDS``
(delay / delay-scrape) is the added latency, ``=RPS`` (flood) is the
burst's offered rate, and ``=BITS`` (corrupt) is how many bits to
flip. Parsing is pure stdlib — ``--plan`` dispatch and the CLI smoke
never touch a backend.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
import time

ACTIONS = (
    "kill", "wedge", "blackhole", "delay-scrape", "delay", "flood",
    "corrupt",
)

_SPEC_RE = re.compile(
    r"^(?P<action>[a-z-]+)"
    r"(?::(?P<target>router(?::\d+)?|\d+|[a-z][a-z0-9_]*))?"
    r"(?:=(?P<seconds>\d+(?:\.\d+)?))?"
    r"(?:@(?P<at>\d+(?:\.\d+)?)s?)?$"
)

FLOOD_DURATION_S = 2.0  # every flood burst is a fixed-length window


@dataclasses.dataclass
class ChaosOp:
    """One scheduled fault injection."""

    action: str
    target: int = 0        # slot index within the target domain
    at_s: float = 1.0      # seconds after the load run starts
    seconds: float = 3.0   # delay/delay-scrape: added latency;
    #                        corrupt: BITS to flip (same =N spec field)
    domain: str = "replica"  # "replica" | "router" | "tenant"
    tenant: str = ""       # flood only: the tenant to flood as
    rps: float = 0.0       # flood only: the burst's offered rate

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r}; expected one of "
                f"{ACTIONS}"
            )
        if self.domain not in ("replica", "router", "tenant"):
            raise ValueError(f"unknown chaos domain {self.domain!r}")
        if self.domain == "router" and self.action != "kill":
            raise ValueError(
                f"router chaos supports only 'kill' (got "
                f"{self.action!r}): routers have no /chaos surface — "
                "their failure mode is hard death"
            )
        if (self.action == "flood") != (self.domain == "tenant"):
            raise ValueError(
                "flood is the only tenant-domain chaos action; spell it "
                "flood:TENANT=RPS[@AT] (e.g. flood:bulk=500@2)"
            )
        if self.action == "flood" and (not self.tenant or self.rps <= 0):
            raise ValueError(
                f"flood needs a tenant name and a positive rate: "
                f"flood:TENANT=RPS[@AT], got tenant={self.tenant!r} "
                f"rps={self.rps!r}"
            )
        if self.target < 0 or self.at_s < 0 or self.seconds <= 0:
            raise ValueError(f"invalid chaos op: {self}")
        if self.action == "corrupt" and self.seconds < 1:
            raise ValueError(
                f"corrupt needs at least 1 bit to flip "
                f"(corrupt:REPLICA[=BITS]), got {self.seconds!r}"
            )

    def describe(self) -> str:
        if self.action == "flood":
            return f"flood:{self.tenant}={self.rps:g}rps@+{self.at_s:g}s"
        if self.action == "corrupt":
            extra = f"={int(self.seconds)}b"
            return f"corrupt:r{self.target}{extra}@+{self.at_s:g}s"
        extra = (
            f"={self.seconds:g}s"
            if self.action in ("delay-scrape", "delay") else ""
        )
        prefix = "router" if self.domain == "router" else "r"
        return f"{self.action}:{prefix}{self.target}{extra}@+{self.at_s:g}s"


def parse_chaos_spec(spec: str) -> ChaosOp:
    """``ACTION[:TARGET][=SECONDS][@AT]`` → :class:`ChaosOp` (``TARGET``
    may be ``router[:N]`` for the router failure domain); raises
    ``ValueError`` naming the problem (argparse turns it into a usage
    error)."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad chaos spec {spec!r}; expected ACTION[:TARGET][=SECONDS]"
            f"[@AT], e.g. kill:1, kill:router, or wedge:0@2.5 "
            f"(actions: {ACTIONS})"
        )
    kw = {"action": m.group("action")}
    target = m.group("target")
    if kw["action"] == "flood":
        # flood:TENANT=RPS — TARGET is a tenant name, =SECONDS is rps.
        kw["domain"] = "tenant"
        kw["tenant"] = target or ""
        if m.group("seconds") is not None:
            kw["rps"] = float(m.group("seconds"))
        if m.group("at") is not None:
            kw["at_s"] = float(m.group("at"))
        return ChaosOp(**kw)
    if target is not None:
        if target.startswith("router"):
            kw["domain"] = "router"
            _, _, idx = target.partition(":")
            kw["target"] = int(idx) if idx else 0
        else:
            try:
                kw["target"] = int(target)
            except ValueError:
                raise ValueError(
                    f"chaos target {target!r} must be a replica index or "
                    f"router[:N] for action {kw['action']!r} (tenant-name "
                    "targets belong to flood:TENANT=RPS)"
                ) from None
    if m.group("at") is not None:
        kw["at_s"] = float(m.group("at"))
    if m.group("seconds") is not None:
        kw["seconds"] = float(m.group("seconds"))
    return ChaosOp(**kw)


def parse_chaos_specs(specs) -> "list[ChaosOp]":
    return [parse_chaos_spec(s) for s in specs or ()]


def _note_injected(op: ChaosOp, record: dict, supervisor, pid=None) -> None:
    """Every injected fault self-labels on the fleet event log + flight
    ring as a schema-valid ``chaos.injected`` event — the incident
    engine's first-cause table (and any post-hoc debugger) blames the
    drill from the log alone, no out-of-band knowledge. ``pid`` is the
    victim process where the fault landed (the injector for flood).
    Best-effort: telemetry must never fail an injection."""
    ev = {
        "ts": float(record.get("ts") or time.time()),
        "kind": "event",
        "name": "chaos.injected",
        "attrs": {
            "op": record.get("op") or op.describe(),
            "action": op.action,
            "domain": op.domain,
            "target": (
                record.get("replica") or record.get("router")
                or record.get("tenant") or f"r{op.target}"
            ),
            "at_s": op.at_s,
            "pid": pid,
        },
    }
    events = getattr(supervisor, "_events", None)
    if events is not None and getattr(events, "enabled", False):
        try:
            events.write(ev)
        except Exception:  # noqa: BLE001
            pass
    flight = getattr(supervisor, "_flight", None)
    if flight is not None:
        try:
            flight.record(ev)
        except Exception:  # noqa: BLE001
            pass


def inject(op: ChaosOp, supervisor, flood=None) -> dict:
    """Apply one op against a live fleet NOW. ``kill`` goes straight to
    the OS (the point is that the victim gets no say); the soft faults
    go through the victim's own ``/chaos`` endpoint. ``domain="router"``
    targets a front-door router slot instead of a replica; ``flood``
    calls the caller-supplied ``flood(op)`` injector (the fleet CLI
    wires a front-door open-loop burst) and embeds what it returns.
    Returns a record of what was done (the CLI report embeds it); the
    same facts land on the fleet event log + flight ring as a
    ``chaos.injected`` event."""
    if op.action == "flood":
        if flood is None:
            raise ValueError(
                "flood chaos needs a traffic injector (the fleet CLI "
                "wires one); none was provided"
            )
        record = {"op": op.describe(), "tenant": op.tenant,
                  "rps": op.rps, "ts": time.time()}
        _note_injected(op, record, supervisor, pid=os.getpid())
        record.update(flood(op) or {})
        return record
    if op.domain == "router":
        slot = supervisor.router_slot_by_index(op.target)
        if slot is None:
            raise ValueError(
                f"chaos target router index {op.target} has no live router"
            )
        record = {"op": op.describe(), "router": slot.name,
                  "pid": slot.pid, "ts": time.time()}
        _note_injected(op, record, supervisor, pid=slot.pid)
        slot.kill_hard()
        return record
    slot = supervisor.slot_by_index(op.target)
    if slot is None:
        raise ValueError(
            f"chaos target index {op.target} has no live replica"
        )
    record = {"op": op.describe(), "replica": slot.name, "ts": time.time()}
    # The self-label is written BEFORE the fault lands: the cause must
    # sit at-or-before its first symptom on the incident timeline.
    _note_injected(op, record, supervisor, pid=slot.pid)
    if op.action == "kill":
        record["pid"] = slot.pid
        slot.kill_hard()
        return record
    actions = {
        "wedge": {"action": "wedge"},
        "blackhole": {"action": "blackhole_healthz"},
        "delay-scrape": {"action": "delay_scrape", "seconds": op.seconds},
        "delay": {"action": "delay_predict", "seconds": op.seconds},
        # corrupt: BITS rides the generic seconds field; the worker's
        # chaos endpoint flips that many bits in the live param buffer.
        "corrupt": {"action": "corrupt_params", "seconds": op.seconds},
    }
    record.update(slot.client.chaos(**actions[op.action]))
    return record


class ChaosMonkey:
    """Schedules :class:`ChaosOp` injections relative to a start mark.

    Built for drills, so it is deliberately boring: a daemon thread,
    ops sorted by ``at_s``, each applied once; failures are recorded
    (a drill against an already-dead replica must not kill the drill
    runner). ``log`` holds what actually happened."""

    def __init__(self, ops, supervisor, flood=None):
        self.ops = sorted(ops, key=lambda o: o.at_s)
        self.supervisor = supervisor
        self.flood = flood  # flood-op injector: op -> record dict
        self.log: "list[dict]" = []
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> None:
        if not self.ops or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="mpi4dl-chaos", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        t0 = time.monotonic()
        for op in self.ops:
            delay = op.at_s - (time.monotonic() - t0)
            if delay > 0 and self._stop_evt.wait(delay):
                return
            try:
                self.log.append(inject(op, self.supervisor,
                                       flood=self.flood))
            except Exception as e:  # noqa: BLE001 — a failed injection
                # is drill data, not a drill crash
                self.log.append({
                    "op": op.describe(),
                    "error": f"{type(e).__name__}: {e}",
                    "ts": time.time(),
                })

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
