"""The fleet front end: admission, health-aware dispatch, requeue-on-death.

One :class:`~mpi4dl_tpu.serve.ServingEngine` is a component; the fleet is
the product (ROADMAP). The router owns the client-facing surface of N
replica processes:

- **Admission.** ``submit()`` mirrors the engine's contract — bounded
  queue, :class:`~mpi4dl_tpu.serve.QueueFullError` with a
  ``retry_after_s`` hint, per-request deadline, a ``Future`` per
  request — so the existing load generators (and any engine client)
  drive a fleet unchanged.
- **Dispatch.** Each replica gets ``inflight_per_replica`` dispatcher
  threads pulling from the shared queue; a replica only pulls while its
  scraped ``/healthz`` says healthy, it isn't draining/backing off, and
  it has a free in-flight slot — so load balances toward the replicas
  that are actually absorbing work (busy or unhealthy replicas simply
  stop pulling), and queue depth scraped off ``/healthz`` can gate a
  replica whose engine queue is already deep (``replica_depth_limit``).
- **In-flight ledger + requeue.** Every dispatched request sits in its
  replica's ledger until the RPC resolves. A dead replica (connection
  refused/reset, RPC timeout, or :meth:`remove_replica` from the
  supervisor on confirmed death) gets its ledger REQUEUED onto
  survivors. Completion is exactly-once by construction: a per-request
  state machine (``pending → inflight → done``) guarded by a lock, with
  a dispatch **epoch** that makes stale requeues/completions no-ops —
  a future is never double-completed, and a request already re-dispatched
  to a survivor cannot be requeued again by the dead replica's
  late-failing RPC thread.
- **Tracing.** The router mints each request's trace id (callers may
  pass their own) and emits ``router.request`` / per-attempt
  ``router.dispatch`` span segments into its JSONL log, so ``python -m
  mpi4dl_tpu.analyze trace-export`` renders a requeued request's full
  client → router → dead-replica → survivor lifetime even though the
  dead replica never flushed its own spans.

Failure semantics: every accepted request's future resolves — with
logits, or with a TYPED error (:class:`DeadlineExceededError`,
:class:`FleetRequestError` after ``max_attempts`` dispatch errors,
:class:`~mpi4dl_tpu.serve.DrainedError` on router stop). Queue-full
bounces at a replica do not count against the attempt budget (the
replica is alive — the deadline bounds the retry loop); dispatch errors
do.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.fleet.replica import (
    ReplicaClient,
    ReplicaDeadline,
    ReplicaError,
    ReplicaQueueFull,
    ReplicaUnreachable,
)
from mpi4dl_tpu.profiling import percentiles
from mpi4dl_tpu.tenancy.dedupe import pin_order
from mpi4dl_tpu.tenancy.model import (
    QuotaExceededError,
    TenantAdmission,
    normalize_tenants,
)


class FleetRequestError(RuntimeError):
    """Terminal dispatch failure: the retry budget is spent and no
    replica could serve the request. Carries the attempt history so the
    caller sees which replicas were tried and why the last one failed."""

    def __init__(self, msg: str, attempts: int = 0, replicas=(),
                 last_error: "Exception | None" = None):
        super().__init__(msg)
        self.attempts = attempts
        self.replicas = tuple(replicas)
        self.last_error = last_error


#: fleet_* metric names the router publishes (the supervisor adds its
#: own set; both go through telemetry.declare, so the catalog is the
#: single source of truth).
ROUTER_METRICS = (
    "fleet_requests_total",
    "fleet_requeues_total",
    "fleet_dispatches_total",
    "fleet_inflight",
    "fleet_replicas",
    "fleet_request_latency_seconds",
    "fleet_router_journal_replays_total",
)


class _Record:
    """One accepted request's lifecycle. The lock guards the state
    machine; ``epoch`` increments per dispatch so stale requeues and
    completions (from a replica declared dead while its RPC was still
    in flight) are detectable no-ops."""

    __slots__ = (
        "x", "submit_t", "deadline", "future", "trace_id", "slo_class",
        "lock", "state", "epoch", "attempts", "history",
        "first_dispatch_t", "last_error", "replayed", "tiled",
        "rpc_slo_class", "tenant", "retried", "probed",
    )

    def __init__(self, x, submit_t, deadline, future, trace_id,
                 slo_class=None, tiled=False, rpc_slo_class=None,
                 tenant=None, retried=False):
        self.x = x
        self.submit_t = submit_t
        self.deadline = deadline
        self.future = future
        self.trace_id = trace_id
        self.slo_class = slo_class
        self.lock = threading.Lock()
        self.state = "pending"
        self.epoch = 0
        self.attempts = 0
        self.history: "list[str]" = []
        self.first_dispatch_t: "float | None" = None
        self.last_error: "Exception | None" = None
        self.replayed = False
        self.tiled = bool(tiled)
        # Tenancy + exactly-once context: the admitted tenant rides the
        # replica RPC and every span; `retried` marks a request some
        # EARLIER attempt may already have executed (client failover
        # retry, or journal replay) — dispatch must probe the fleet's
        # served-caches first and then pin to the rendezvous replica
        # (tenancy/dedupe.py). `probed` makes the fan-out probe
        # once-per-record.
        self.tenant = tenant
        self.retried = bool(retried)
        self.probed = False
        # What rides the replica RPC: for plain requests the router's
        # resolved class (worker engines declare the same classes); for
        # tiled requests only an EXPLICIT caller class — the tiled
        # engine has its own class set (default "tiled"), which it
        # resolves itself when none is sent.
        self.rpc_slo_class = (
            rpc_slo_class if tiled else (rpc_slo_class or slo_class)
        )


class _Replica:
    """Router-side view of one replica: client, scraped health, ledger."""

    def __init__(self, name: str, predict_url: str, health_url: str):
        self.name = name
        self.client = ReplicaClient(name, predict_url)
        self.health_url = health_url.rstrip("/") + "/healthz"
        self.healthy = True          # optimistic until the first scrape
        self.fenced = False          # numerics fence self-report (scraped)
        self.queue_depth: "float | None" = None
        self.scrape_failures = 0
        self.backoff_until = 0.0
        self.draining = False
        self.removed = False
        self.inflight: "dict[str, _Record]" = {}
        self.threads: "list[threading.Thread]" = []

    def accepting(self, now: float, depth_limit: "int | None") -> bool:
        if self.removed or self.draining or not self.healthy:
            return False
        if now < self.backoff_until:
            return False
        if (
            depth_limit is not None
            and self.queue_depth is not None
            and self.queue_depth >= depth_limit
        ):
            return False
        return True

    def state(self) -> dict:
        return {
            "name": self.name,
            "healthy": self.healthy,
            "fenced": self.fenced,
            "draining": self.draining,
            "removed": self.removed,
            "queue_depth": self.queue_depth,
            "inflight": len(self.inflight),
            "scrape_failures": self.scrape_failures,
        }


class Router:
    """Front-end admission + dispatch over N replica predict endpoints.

    example_shape / dtype: the per-request input contract (mirrors
        :class:`ServingEngine`, so load generators work unchanged).
    registry: shared :class:`telemetry.MetricsRegistry`; the router
        declares and publishes the ``fleet_*`` router metrics on it.
    max_queue: admission bound on requests waiting for a dispatcher.
    max_attempts: dispatch ERRORS allowed per request before its future
        fails with :class:`FleetRequestError` (queue-full bounces are
        not errors and don't count — the deadline bounds those).
    inflight_per_replica: dispatcher threads (= max concurrent RPCs)
        per replica.
    replica_depth_limit: optional scraped-queue-depth gate — a replica
        whose engine queue is at/over this stops pulling until it
        drains below.
    health_interval_s / scrape_timeout_s: the ``/healthz`` scrape loop.
        The worker enriches its health payload with ``queue_depth``, so
        one cheap endpoint feeds both signals.
    dispatch_timeout_s: per-RPC cap; None = the request's remaining
        deadline (+1s grace for the response to travel).
    events / telemetry_dir: span-segment sink (``events`` wins; a
        shared :class:`telemetry.JsonlWriter` lets the in-process load
        generator's client segments land in the same file).
    name: this router's stable identity (journal file + span attrs);
        an N-router front door gives each instance its own name so a
        respawned incarnation finds its predecessor's journal.
    journal_path: append-only recovery journal (:mod:`.journal`). When
        set, every accepted request and terminal delivery is journaled
        (accept/done fsync'd), and :meth:`replay_journal` lets a
        successor re-dispatch what a dead predecessor stranded. None
        (default) keeps the in-memory-only PR-8 behavior.
    replay_grace_s: how long a replay parks orphans while polling the
        replicas' served-cache before re-dispatching — the window in
        which a client's own failover retry normally completes the
        request on a surviving router, making the orphan a dedupe
        no-op instead of a second execution.
    load_slack: load-aware pull. A replica whose scraped ``queue_depth``
        exceeds the least-loaded accepting replica's by more than this
        stops pulling until it drains back — with N shared-nothing
        routers over one replica set, this is what keeps two routers
        from piling onto the same replica (each router reads the same
        enriched ``/healthz`` depth). None disables.
    slo_classes: named SLO classes (spec string / SLOClass sequence /
        None — :mod:`mpi4dl_tpu.serve.scheduler`). ``submit(slo_class=)``
        validates against them and the class rides every replica RPC, so
        the replica engine's EDF scheduler sees the caller's class. The
        router also applies the SAME burn-rate shedding policy
        (:class:`~mpi4dl_tpu.serve.ClassFeedback`) at its own admission
        edge: when the router's registry carries per-class
        ``slo_burn_rate`` gauges (a federated aggregator evaluating
        fleet-wide SLOs publishes them) and the pending queue is past
        ``shed_queue_ratio``, admissions for deprioritized classes are
        rejected before they cross a process boundary.
    shed_queue_ratio: router-queue occupancy at which class-aware
        shedding engages.
    """

    def __init__(
        self,
        example_shape,
        dtype: str = "float32",
        registry=None,
        max_queue: int = 256,
        default_deadline_s: float = 30.0,
        max_attempts: int = 3,
        inflight_per_replica: int = 8,
        replica_depth_limit: "int | None" = None,
        health_interval_s: float = 0.25,
        scrape_timeout_s: float = 1.0,
        dispatch_timeout_s: "float | None" = None,
        events=None,
        telemetry_dir: "str | None" = None,
        slo_classes=None,
        shed_queue_ratio: float = 0.5,
        tenants=None,
        name: str = "router",
        journal_path: "str | None" = None,
        journal_fsync: bool = True,
        replay_grace_s: float = 1.5,
        load_slack: "int | None" = 4,
    ):
        from mpi4dl_tpu.serve.scheduler import (
            ClassFeedback,
            normalize_classes,
        )
        self.name = str(name)
        self.example_shape = tuple(int(d) for d in example_shape)
        self._np_dtype = np.dtype(dtype)
        self.registry = (
            registry if registry is not None else telemetry.MetricsRegistry()
        )
        self._events = (
            events if events is not None
            else telemetry.JsonlWriter(telemetry_dir)
        )
        self._owns_events = events is None
        self._max_queue = int(max_queue)
        self._default_deadline_s = float(default_deadline_s)
        self._max_attempts = int(max_attempts)
        self._inflight_per_replica = int(inflight_per_replica)
        self._depth_limit = replica_depth_limit
        self._health_interval_s = float(health_interval_s)
        self._scrape_timeout_s = float(scrape_timeout_s)
        self._dispatch_timeout_s = dispatch_timeout_s
        # SLO classes + the engine-identical shedding policy. Feedback
        # needs >1 class with at least one objective AND burn gauges in
        # THIS registry (a federated evaluator publishes them); absent
        # either, states() answers "normal" for everyone and the router
        # sheds nothing — evidence-only, like the engine scheduler.
        self._classes = normalize_classes(slo_classes)
        self._class_names = {c.name for c in self._classes}
        self._default_class = (
            self._classes[-1] if "default" not in self._class_names
            else next(c for c in self._classes if c.name == "default")
        )
        self._shed_queue_ratio = float(shed_queue_ratio)
        self._feedback = (
            ClassFeedback(self.registry, self._classes)
            if len(self._classes) > 1
            and any(c.latency_threshold_s for c in self._classes)
            else None
        )
        self._m_shed = (
            telemetry.declare(self.registry, "serve_class_shed_total")
            if self._feedback is not None else None
        )
        # Front-door quota admission (tenancy subsystem): each router
        # refills its OWN token buckets at the configured per-tenant
        # rate — with R routers a tenant's effective front-door rate is
        # R x its spec (documented in docs/SERVING.md); the engine-edge
        # buckets are the authoritative per-replica bound. None = off.
        self._tenants = normalize_tenants(tenants)
        self._admission = (
            TenantAdmission(self._tenants, registry=self.registry)
            if self._tenants is not None
            else None
        )

        self._m_requests = telemetry.declare(
            self.registry, "fleet_requests_total"
        )
        self._m_requeues = telemetry.declare(
            self.registry, "fleet_requeues_total"
        )
        self._m_dispatches = telemetry.declare(
            self.registry, "fleet_dispatches_total"
        )
        self._m_inflight = telemetry.declare(self.registry, "fleet_inflight")
        self._m_latency = telemetry.declare(
            self.registry, "fleet_request_latency_seconds"
        )
        self._m_replicas = telemetry.declare(self.registry, "fleet_replicas")
        self._m_replicas.set(0, state="configured")
        self._m_replicas.set(0, state="healthy")
        self._m_replays = telemetry.declare(
            self.registry, "fleet_router_journal_replays_total"
        )

        self._replay_grace_s = float(replay_grace_s)
        self._load_slack = None if load_slack is None else int(load_slack)
        self._journal = None
        if journal_path:
            from mpi4dl_tpu.fleet.journal import RouterJournal

            self._journal = RouterJournal(journal_path, fsync=journal_fsync)
        self._replay_thread: "threading.Thread | None" = None

        self._cond = threading.Condition()
        self._pending: "collections.deque[_Record]" = collections.deque()
        self._replicas: "dict[str, _Replica]" = {}
        self._lock = threading.Lock()  # replica map + counters
        self._counts = {
            "submitted": 0, "served": 0, "failed": 0,
            "rejected_queue_full": 0, "rejected_deadline": 0,
            "rejected_quota": 0,
            "drained": 0, "requeued": 0, "shed": 0, "replayed": 0,
        }
        self._latencies: "list[float]" = []
        self._stopping = False
        self._scrape_stop = threading.Event()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="mpi4dl-router-health",
            daemon=True,
        )
        self._scrape_thread.start()

    # -- replica membership ---------------------------------------------------

    def add_replica(
        self, name: str, predict_url: str, health_url: "str | None" = None
    ) -> None:
        """Register a replica (the supervisor calls this once the worker's
        ready handshake lands). Re-adding an existing name replaces the
        entry — the respawned incarnation of a slot."""
        rep = _Replica(name, predict_url, health_url or predict_url)
        with self._lock:
            old = self._replicas.get(name)
            self._replicas[name] = rep
            self._m_replicas.set(len(self._replicas), state="configured")
        if old is not None:
            old.removed = True
        for _ in range(self._inflight_per_replica):
            t = threading.Thread(
                target=self._dispatch_loop, args=(rep,),
                name=f"mpi4dl-router-{name}", daemon=True,
            )
            rep.threads.append(t)
            t.start()
        with self._cond:
            self._cond.notify_all()

    def remove_replica(self, name: str, requeue: bool = True) -> int:
        """Drop a replica from dispatch. ``requeue=True`` is the
        DEAD-replica path (supervisor-confirmed): every request in its
        in-flight ledger goes back on the queue for survivors. Only call
        with ``requeue=True`` once the process is actually gone —
        requeueing work a live replica is still executing is how
        double-execution happens. Returns the number requeued."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            self._m_replicas.set(len(self._replicas), state="configured")
        if rep is None:
            return 0
        rep.removed = True
        with self._cond:
            self._cond.notify_all()
        n = 0
        if requeue:
            for rec in list(rep.inflight.values()):
                with rec.lock:
                    epoch = rec.epoch
                if self._requeue(
                    rec, epoch, reason="replica_removed",
                    count_attempt=False,
                ):
                    n += 1
        rep.inflight.clear()
        self._m_inflight.set(0, replica=name)
        return n

    def drain_replica(self, name: str, timeout_s: float = 10.0) -> bool:
        """Scale-down drain: stop routing new work to the replica, then
        wait for its in-flight ledger to flush. Returns True when the
        ledger emptied (the caller may now terminate the process)."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            return True
        rep.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not rep.inflight:
                return True
            time.sleep(0.02)
        return not rep.inflight

    def replicas(self) -> "list[dict]":
        with self._lock:
            return [r.state() for r in self._replicas.values()]

    # -- client surface (engine-shaped: loadgen drives it unchanged) ----------

    @property
    def events(self):
        return self._events

    def submit(
        self,
        x,
        deadline_s: "float | None" = None,
        trace_id: "str | None" = None,
        slo_class: "str | None" = None,
        tiled: bool = False,
        tenant: "str | None" = None,
        retried: bool = False,
    ):
        """Admit one request; returns a ``Future``. Mirrors
        :meth:`ServingEngine.submit` (queue-full/deadline semantics,
        trace-id propagation, ``slo_class``) so engine clients need no
        changes. The class is validated against the router's configured
        classes and rides every replica RPC; under queue pressure the
        burn-rate feedback sheds deprioritized classes HERE, before
        a doomed request crosses to a replica. ``tiled=True`` routes to
        the replicas' gigapixel ``/predict_tiled`` surface — the image
        is shape-checked by the replica's tiled engine (its large
        example shape is a worker-spawn fact the router does not
        duplicate), everything else (ledger, requeue-on-death, journal
        replay, idempotency) is identical."""
        from concurrent.futures import Future

        from mpi4dl_tpu.serve.engine import QueueFullError

        x = np.asarray(x, self._np_dtype)
        if not tiled and x.shape != self.example_shape:
            raise ValueError(
                f"example shape {x.shape} != configured {self.example_shape}"
            )
        if slo_class is None:
            cls = self._default_class
        elif str(slo_class) in self._class_names:
            cls = next(
                c for c in self._classes if c.name == str(slo_class)
            )
        else:
            raise ValueError(
                f"unknown SLO class {slo_class!r} (configured: "
                f"{sorted(self._class_names)})"
            )
        if self._stopping:
            raise RuntimeError("router is stopped")
        # Front-door quota: over-quota floods shed with the bucket's
        # refill time as the retry hint BEFORE occupying a router queue
        # slot (QuotaExceededError, typed; never forwarded to a
        # replica). With tenancy off the name is carried to spans/RPCs
        # unvalidated.
        if self._admission is not None:
            try:
                ten = self._admission.admit(tenant, slo_class=cls.name)
            except QuotaExceededError:
                with self._lock:
                    self._counts["rejected_quota"] += 1
                self._m_requests.inc(outcome="rejected_quota")
                raise
            tenant_name = ten.name
        else:
            tenant_name = tenant or "default"
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = (
                cls.deadline_s if cls.deadline_s is not None
                else self._default_deadline_s
            )
        ddl = now + deadline_s
        rec = _Record(
            x=x, submit_t=now, deadline=ddl, future=Future(),
            trace_id=(
                str(trace_id) if trace_id else telemetry.new_trace_id("fleet")
            ),
            slo_class=cls.name, tiled=tiled,
            rpc_slo_class=(
                str(slo_class) if slo_class is not None else None
            ),
            tenant=tenant_name, retried=retried,
        )
        with self._cond:
            depth = len(self._pending)
            if (
                self._feedback is not None
                and depth >= self._shed_queue_ratio * self._max_queue
                and self._feedback.states().get(cls.name) == "deprioritized"
            ):
                # The engine scheduler's shed policy, applied one hop
                # earlier: this class is burning budget slowest while
                # another class burns hot, and the router queue is
                # under pressure — reject instead of forwarding.
                with self._lock:
                    self._counts["rejected_queue_full"] += 1
                    self._counts["shed"] += 1
                self._m_requests.inc(outcome="rejected_queue_full")
                self._m_shed.inc(slo_class=cls.name)
                raise QueueFullError(
                    f"router shed class {cls.name!r} by burn-rate "
                    f"feedback ({depth}/{self._max_queue} waiting)",
                    retry_after_s=0.05, slo_class=cls.name, shed=True,
                )
            if depth >= self._max_queue:
                with self._lock:
                    self._counts["rejected_queue_full"] += 1
                self._m_requests.inc(outcome="rejected_queue_full")
                raise QueueFullError(
                    f"router queue full ({self._max_queue} waiting)",
                    retry_after_s=0.05, slo_class=cls.name,
                )
            self._pending.append(rec)
            self._cond.notify()
        if self._journal is not None:
            # Durable accept (fsync'd) OUTSIDE the queue lock: a router
            # killed after this line replays the request; killed before
            # it, the client's own failover retry covers the request and
            # the replica-side idempotency cache dedupes the overlap.
            self._journal.accept(
                rec.trace_id, x, deadline_s, slo_class=cls.name,
                tiled=tiled, tenant=tenant_name,
            )
        with self._lock:
            self._counts["submitted"] += 1
        return rec.future

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            lat = list(self._latencies)
        out["latency_s"] = percentiles(lat)
        out["queue_depth"] = len(self._pending)
        out["replicas"] = self.replicas()
        if self._admission is not None:
            out["tenancy"] = self._admission.state()
        return out

    def health_snapshot(self) -> dict:
        reps = self.replicas()
        up = [r for r in reps if r["healthy"] and not r["removed"]]
        healthy = bool(up)
        return {
            "healthy": healthy,
            "reason": (
                "ok" if healthy else "no healthy replica accepting work"
            ),
            "queue_depth": len(self._pending),
            "replicas": reps,
        }

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop dispatching. ``drain=True`` waits (bounded) for queued +
        in-flight work to finish first; whatever remains is failed with
        :class:`DrainedError` (outcome ``drained`` — a lifecycle event,
        not an availability failure)."""
        if drain:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    busy = any(
                        r.inflight for r in self._replicas.values()
                    )
                if not self._pending and not busy:
                    break
                time.sleep(0.02)
        self._stopping = True
        self._scrape_stop.set()
        with self._cond:
            self._cond.notify_all()
        self._scrape_thread.join(timeout=5)
        if self._replay_thread is not None:
            self._replay_thread.join(timeout=5)
        from mpi4dl_tpu.serve.engine import DrainedError

        while True:
            with self._cond:
                if not self._pending:
                    break
                rec = self._pending.popleft()
            with rec.lock:
                if rec.state == "done":
                    continue
                rec.state = "done"
            self._journal_done(rec, "drained")
            with self._lock:
                self._counts["drained"] += 1
            self._m_requests.inc(outcome="drained")
            rec.future.set_exception(DrainedError(
                "router stopped before this request was dispatched"
            ))
        if self._journal is not None:
            self._journal.close()
        if self._owns_events:
            self._events.close()

    def fetch_served(self, trace_id: str, x,
                     deadline_s: float = 5.0,
                     tiled: bool = False) -> "tuple | None":
        """Duplicate-suppression probe for a RETRIED request (a client
        failing over after a router death cannot know whether its first
        attempt executed): ask each replica's served-cache whether it
        vouches for ``trace_id``; if one does, fetch the CACHED result
        from that same replica (its ``/predict`` answers from the cache
        or joins the in-flight future — it never re-executes). Returns
        ``(logits, payload)`` or None (no replica can vouch — the caller
        submits normally and the request executes for the first time on
        THIS side of the failover)."""
        with self._lock:
            reps = [r for r in self._replicas.values() if not r.removed]
        for rep in reps:
            try:
                if trace_id not in rep.client.served([trace_id]):
                    continue
                out = rep.client.predict(
                    x, trace_id, deadline_s=deadline_s,
                    timeout_s=deadline_s + 1.0, tiled=tiled,
                )
            except Exception:  # noqa: BLE001 — a replica that cannot
                continue  # vouch (or died holding the cache) proves
                # nothing; the normal submit path takes over
            self._m_requests.inc(outcome="served_cached")
            return out
        return None

    # -- journal replay (router-death recovery) -------------------------------

    def replay_journal(self) -> int:
        """Process what a dead predecessor's journal stranded. Orphans
        (accepted, never completed) are PARKED first: for up to
        ``replay_grace_s`` the replay thread polls every registered
        replica's served-cache — an orphan a replica already served (or
        has in flight: the client's failover retry on a surviving
        router) is completed in the journal as a dedupe no-op, never
        re-executed. What remains after the grace is re-dispatched with
        a fresh request epoch through the normal dispatch machinery
        (the replica-side idempotency cache still backstops any residual
        overlap). Returns the orphan count parked; every processed
        orphan lands in ``fleet_router_journal_replays_total{outcome=
        deduped|redispatched|expired}``."""
        if self._journal is None:
            return 0
        recovered = self._journal.recovered
        for _ in range(recovered.expired):
            self._m_replays.inc(outcome="expired")
        if not recovered.orphans:
            return 0
        self._replay_thread = threading.Thread(
            target=self._replay_loop, args=(list(recovered.orphans),),
            name=f"mpi4dl-router-replay-{self.name}", daemon=True,
        )
        self._replay_thread.start()
        return len(recovered.orphans)

    def _replay_loop(self, parked) -> None:
        # A fresh successor has an empty replica map until the supervisor
        # re-registers the fleet; the dedupe grace only means something
        # once there is someone to ask, so wait (bounded) for the first
        # registration before starting the clock.
        wait_deadline = time.monotonic() + max(10.0, self._replay_grace_s)
        while not self._stopping and time.monotonic() < wait_deadline:
            with self._lock:
                if self._replicas:
                    break
            time.sleep(0.05)
        grace_deadline = time.monotonic() + self._replay_grace_s
        while parked and not self._stopping:
            tids = [o.trace_id for o in parked]
            found: "set[str]" = set()
            with self._lock:
                reps = [r for r in self._replicas.values() if not r.removed]
            for rep in reps:
                try:
                    found.update(rep.client.served(tids))
                except Exception:  # noqa: BLE001 — an unreachable replica
                    pass  # just can't vouch; the grace window bounds us
            still = []
            for o in parked:
                if o.trace_id in found:
                    self._journal.done(o.trace_id, "served")
                    self._m_replays.inc(outcome="deduped")
                    with self._lock:
                        self._counts["replayed"] += 1
                else:
                    still.append(o)
            parked = still
            if time.monotonic() >= grace_deadline:
                break
            time.sleep(min(0.2, max(0.0,
                                    grace_deadline - time.monotonic())))
        for o in parked:
            if self._stopping:
                return
            self._redispatch_orphan(o)

    def _redispatch_orphan(self, orphan) -> None:
        from concurrent.futures import Future

        remaining = orphan.remaining_s()
        if remaining <= 0:
            self._journal.done(orphan.trace_id, "rejected_deadline")
            self._m_replays.inc(outcome="expired")
            return
        cls_name = (
            orphan.slo_class
            if orphan.slo_class in self._class_names
            else self._default_class.name
        )
        now = time.monotonic()
        rec = _Record(
            x=np.asarray(orphan.x, self._np_dtype), submit_t=now,
            deadline=now + remaining, future=Future(),
            trace_id=orphan.trace_id, slo_class=cls_name,
            tiled=getattr(orphan, "tiled", False),
            tenant=getattr(orphan, "tenant", None),
            # A replayed orphan is by definition a request an earlier
            # incarnation may have executed: the dispatch path must
            # probe + pin it like any client-marked retry.
            retried=True,
        )
        rec.replayed = True
        # Re-accept under THIS incarnation's epoch so a second router
        # death replays it again (the scan dedupes by trace id).
        self._journal.accept(
            rec.trace_id, rec.x, remaining, slo_class=cls_name,
            tiled=rec.tiled, tenant=rec.tenant,
        )
        self._m_replays.inc(outcome="redispatched")
        with self._lock:
            self._counts["replayed"] += 1
        with self._cond:
            # Front of the queue: an orphan is the oldest work there is.
            self._pending.appendleft(rec)
            self._cond.notify()

    # -- dispatch -------------------------------------------------------------

    def _rep_overloaded(self, rep: _Replica) -> bool:
        """Load-aware pull: True while this replica's scraped queue depth
        exceeds the least-loaded accepting replica's by more than
        ``load_slack`` — it stops pulling and the work flows to the
        lighter replicas instead. This is the cross-router coordination
        point: N shared-nothing routers all read the same enriched
        ``/healthz`` depth, so they all back off the same pile-up."""
        if self._load_slack is None:
            return False
        d = rep.queue_depth
        if d is None or d <= self._load_slack:
            return False
        with self._lock:
            others = [
                r.queue_depth for r in self._replicas.values()
                if r is not rep and not r.removed and not r.draining
                and r.healthy and r.queue_depth is not None
            ]
        return bool(others) and d > min(others) + self._load_slack

    def _dispatch_loop(self, rep: _Replica) -> None:
        while True:
            rec = None
            with self._cond:
                while True:
                    if self._stopping or rep.removed:
                        return
                    if (
                        self._pending
                        and rep.accepting(time.monotonic(), self._depth_limit)
                        and not self._rep_overloaded(rep)
                    ):
                        rec = self._pending.popleft()
                        if (
                            rec.attempts
                            and rec.history
                            and rec.history[-1] == rep.name
                            and len(self._replicas) > 1
                        ):
                            # Re-dispatch dedupe: a request that just
                            # FAILED here goes to a different replica
                            # while one exists; only a one-replica
                            # fleet retries in place.
                            self._pending.appendleft(rec)
                            self._cond.wait(0.02)
                            continue
                        pin = self._pin_for(rec)
                        if pin is not None and pin != rep.name:
                            # Exactly-once pin: every router dispatches
                            # a RETRIED trace id to the same rendezvous
                            # replica, whose served-cache/in-flight join
                            # makes racing copies execute at most once
                            # (tenancy/dedupe.py). This dispatcher is
                            # not the pin — push back and let the pin
                            # replica's dispatcher pull it.
                            self._pending.appendleft(rec)
                            self._cond.wait(0.02)
                            continue
                        break
                    # Timed wait: health/backoff state changes outside
                    # the condition (scrape loop) must be re-checked.
                    self._cond.wait(0.05)
            try:
                self._dispatch_one(rep, rec)
            except Exception as e:  # noqa: BLE001 — a dispatcher dying
                # would strand its record; fail it loudly instead.
                self._fail(rec, rec.epoch, e)

    def _pin_for(self, rec: _Record) -> "str | None":
        """The rendezvous replica a RETRIED record must dispatch to —
        computed over the currently-ACCEPTING membership so a dead pin
        falls through to the same successor on every router. None for
        normal records (no constraint) or when no replica accepts."""
        if not rec.retried:
            return None
        now = time.monotonic()
        with self._lock:
            names = [
                r.name for r in self._replicas.values()
                if r.accepting(now, self._depth_limit)
            ]
        order = pin_order(rec.trace_id, names)
        return order[0] if order else None

    def _probe_served(self, rec: _Record) -> bool:
        """The fan-out `/served` probe a RETRIED record takes before ANY
        dispatch: a voucher anywhere means an earlier attempt already
        executed — complete from that replica's idempotency cache
        instead of dispatching. Returns True when the record was
        completed here (caller must not dispatch)."""
        with self._lock:
            reps = [r for r in self._replicas.values() if not r.removed]
        for rep in reps:
            try:
                if rec.trace_id not in rep.client.served([rec.trace_id]):
                    continue
                remaining = max(0.5, rec.deadline - time.monotonic())
                logits, payload = rep.client.predict(
                    rec.x, rec.trace_id, deadline_s=remaining,
                    timeout_s=remaining + 1.0, tiled=rec.tiled,
                )
            except Exception:  # noqa: BLE001 — a replica that cannot
                continue  # vouch (or died holding the cache) proves
                # nothing; the pinned dispatch path takes over
            with rec.lock:
                if rec.state == "done":
                    return True
                rec.state = "done"
            self._journal_done(rec, "served")
            end = time.monotonic()
            with self._lock:
                self._counts["served"] += 1
                self._latencies.append(end - rec.submit_t)
            self._m_requests.inc(outcome="served_cached")
            if rec.replayed:
                # The replay path's dedupe promise, kept by the probe:
                # the orphan never re-executed.
                self._m_replays.inc(outcome="deduped")
            self._m_latency.observe(end - rec.submit_t,
                                    exemplar=rec.trace_id)
            rec.future.trace_id = rec.trace_id
            if payload and payload.get("engine_e2e_s") is not None:
                rec.future.e2e_latency_s = payload["engine_e2e_s"]
            self._emit_request_span(rec, end, "served_cached")
            rec.future.set_result(logits)
            return True
        return False

    def _dispatch_one(self, rep: _Replica, rec: _Record) -> None:
        if rec.retried and not rec.probed:
            rec.probed = True
            if self._probe_served(rec):
                return
        now = time.monotonic()
        with rec.lock:
            if rec.state == "done":
                return
            if now > rec.deadline:
                rec.state = "done"
                terminal_deadline = True
            else:
                terminal_deadline = False
                rec.state = "inflight"
                rec.epoch += 1
                epoch = rec.epoch
                rec.history.append(rep.name)
                if rec.first_dispatch_t is None:
                    rec.first_dispatch_t = now
        if terminal_deadline:
            self._deliver_deadline(rec, "expired while queued at the router")
            return
        if self._journal is not None:
            self._journal.dispatch(rec.trace_id, rep.name, epoch)
        rep.inflight[rec.trace_id] = rec
        self._m_inflight.set(len(rep.inflight), replica=rep.name)
        remaining = rec.deadline - now
        timeout = remaining + 1.0  # grace: let the engine's own
        # deadline machinery answer 504 before the socket gives up
        if self._dispatch_timeout_s is not None:
            timeout = min(timeout, self._dispatch_timeout_s)
        t0 = now
        outcome, payload, logits, error = "ok", None, None, None
        try:
            logits, payload = rep.client.predict(
                rec.x, rec.trace_id, deadline_s=remaining, timeout_s=timeout,
                slo_class=rec.rpc_slo_class, tiled=rec.tiled,
                tenant=rec.tenant, retried=rec.retried,
            )
        except ReplicaQueueFull as e:
            outcome, error = "queue_full", e
            rep.backoff_until = time.monotonic() + (e.retry_after_s or 0.02)
        except ReplicaDeadline as e:
            outcome, error = "deadline", e
        except ReplicaUnreachable as e:
            # Connection refused/reset/timed out: the strongest death
            # signal there is. Mark the replica down IMMEDIATELY (before
            # the requeue) so survivors' dispatchers — not this
            # replica's — pick the request up; the scrape loop restores
            # `healthy` the moment a probe answers again.
            outcome, error = "error", e
            rep.scrape_failures += 1
            rep.healthy = False
        except ReplicaError as e:
            outcome, error = "error", e
            rep.scrape_failures += 1
            if rep.scrape_failures >= 2:
                # Two straight failures: stop pulling until a scrape
                # says otherwise (the scrape loop resets on success).
                rep.healthy = False
        rep.inflight.pop(rec.trace_id, None)
        self._m_inflight.set(len(rep.inflight), replica=rep.name)
        self._m_dispatches.inc(replica=rep.name, outcome=outcome)
        self._emit_dispatch_span(rec, rep, t0, time.monotonic(), outcome)
        if outcome == "ok":
            self._complete(rec, epoch, logits, payload)
        elif outcome == "deadline":
            with rec.lock:
                stale = rec.state != "inflight" or rec.epoch != epoch
                if not stale:
                    rec.state = "done"
            if not stale:
                self._deliver_deadline(rec, str(error))
        elif outcome == "queue_full":
            self._requeue(
                rec, epoch, reason="replica_queue_full", count_attempt=False,
            )
        else:
            self._requeue(
                rec, epoch, reason="dispatch_error", count_attempt=True,
                error=error,
            )

    def _requeue(
        self, rec: _Record, epoch: int, reason: str,
        count_attempt: bool, error=None,
    ) -> bool:
        """Move an in-flight record back to pending — exactly once per
        dispatch epoch. A record already completed, already requeued, or
        already re-dispatched to a survivor (epoch moved on) is left
        alone. Returns True when the record actually went back on the
        queue."""
        terminal = None
        with rec.lock:
            if rec.state != "inflight" or rec.epoch != epoch:
                return False
            if error is not None:
                rec.last_error = error
            if count_attempt:
                rec.attempts += 1
            now = time.monotonic()
            if now > rec.deadline:
                rec.state = "done"
                terminal = "deadline"
            elif count_attempt and rec.attempts >= self._max_attempts:
                rec.state = "done"
                terminal = "failed"
            else:
                rec.state = "pending"
        if terminal == "deadline":
            self._deliver_deadline(
                rec, "deadline expired across dispatch attempts"
            )
            return False
        if terminal == "failed":
            self._deliver_failed(rec)
            return False
        with self._lock:
            self._counts["requeued"] += 1
        self._m_requeues.inc(reason=reason)
        with self._cond:
            # Front of the queue: a requeued request is the oldest work
            # in the system; FIFO fairness says it goes next.
            self._pending.appendleft(rec)
            self._cond.notify()
        return True

    # -- terminal deliveries (each guarded: state=="done" exactly once) -------

    def _journal_done(self, rec: _Record, outcome: str) -> None:
        if self._journal is not None:
            self._journal.done(rec.trace_id, outcome)

    def _complete(self, rec: _Record, epoch: int, logits, payload) -> None:
        with rec.lock:
            if rec.state != "inflight" or rec.epoch != epoch:
                return  # a stale win: someone else owns this record now
            rec.state = "done"
        self._journal_done(rec, "served")
        end = time.monotonic()
        with self._lock:
            self._counts["served"] += 1
            self._latencies.append(end - rec.submit_t)
        self._m_requests.inc(outcome="served")
        # Fleet-level e2e (requeues and retries included) with the trace
        # id as the bucket exemplar: a scrape of the fleet p99 bucket
        # names a real request (`analyze tail --trace-id` takes it from
        # there).
        self._m_latency.observe(end - rec.submit_t, exemplar=rec.trace_id)
        # The engine's own e2e rides the future (loadgen computes its
        # observed-minus-engine overhead from it — now the router+RPC
        # hop cost instead of the in-process future overhead).
        rec.future.trace_id = rec.trace_id
        if payload and payload.get("engine_e2e_s") is not None:
            rec.future.e2e_latency_s = payload["engine_e2e_s"]
        self._emit_request_span(rec, end, "served")
        rec.future.set_result(logits)

    def _deliver_deadline(self, rec: _Record, why: str) -> None:
        from mpi4dl_tpu.serve.engine import DeadlineExceededError

        self._journal_done(rec, "rejected_deadline")
        with self._lock:
            self._counts["rejected_deadline"] += 1
        self._m_requests.inc(outcome="rejected_deadline")
        self._emit_request_span(rec, time.monotonic(), "rejected_deadline")
        rec.future.set_exception(DeadlineExceededError(why))

    def _deliver_failed(self, rec: _Record) -> None:
        self._journal_done(rec, "failed")
        with self._lock:
            self._counts["failed"] += 1
        self._m_requests.inc(outcome="failed")
        self._emit_request_span(rec, time.monotonic(), "failed")
        rec.future.set_exception(FleetRequestError(
            f"request failed after {rec.attempts} dispatch attempt(s) "
            f"across replicas {rec.history} (last: {rec.last_error})",
            attempts=rec.attempts, replicas=rec.history,
            last_error=rec.last_error,
        ))

    def _fail(self, rec: _Record, epoch: int, error: Exception) -> None:
        with rec.lock:
            if rec.state == "done":
                return
            rec.state = "done"
            rec.last_error = error
        self._deliver_failed(rec)

    # -- span segments --------------------------------------------------------

    def _emit_dispatch_span(
        self, rec: _Record, rep: _Replica, t0: float, t1: float, outcome: str
    ) -> None:
        """One RPC attempt as a span segment — the hop that makes a
        requeued request's DEAD-replica attempt visible in trace-export
        (the dead engine never got to flush its own spans)."""
        if not self._events.enabled:
            return
        self._events.write(telemetry.span_event(
            "router.dispatch", rec.trace_id,
            telemetry.spans_from_marks(
                [("sent", t0), (f"rpc_{rep.name}", max(t1, t0))]
            ),
            attrs={
                "pid": os.getpid(), "role": "router",
                "replica": rep.name, "attempt": len(rec.history),
                "outcome": outcome,
            },
        ))

    def _emit_request_span(self, rec: _Record, end: float, outcome: str):
        if not self._events.enabled:
            return
        route_t = rec.first_dispatch_t
        marks = [("submit", rec.submit_t)]
        if route_t is not None and route_t <= end:
            marks.append(("route_queue", route_t))
        marks.append(("dispatch", max(end, rec.submit_t)))
        self._events.write(telemetry.span_event(
            "router.request", rec.trace_id,
            telemetry.spans_from_marks(marks),
            attrs={
                "pid": os.getpid(), "role": "router", "outcome": outcome,
                "attempts": len(rec.history), "replicas": rec.history,
                "e2e_latency_s": end - rec.submit_t,
                "slo_class": rec.slo_class,
                "tenant": rec.tenant or "default",
                "router": self.name,
                "replayed": rec.replayed,
                "retried": rec.retried,
            },
        ))

    # -- health scraping ------------------------------------------------------

    def _scrape_loop(self) -> None:
        while not self._scrape_stop.wait(self._health_interval_s):
            self._scrape_once()

    def _scrape_once(self) -> None:
        healthy = 0
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            payload, reachable = None, False
            try:
                with urllib.request.urlopen(
                    rep.health_url, timeout=self._scrape_timeout_s
                ) as resp:
                    payload = json.loads(resp.read().decode())
                reachable = True
            except urllib.error.HTTPError as e:
                # 503 is a VALID answer: reachable but unhealthy.
                reachable = True
                try:
                    payload = json.loads(e.read().decode())
                except Exception:  # noqa: BLE001 — body is advisory
                    payload = {"healthy": False}
            except Exception:  # noqa: BLE001 — down/black-holed probe
                rep.scrape_failures += 1
                if rep.scrape_failures >= 2:
                    rep.healthy = False
            if reachable:
                rep.scrape_failures = 0
                rep.healthy = bool(payload.get("healthy"))
                # A numerics-fenced replica self-reports healthy=False
                # (so the generic path already stops pulling); keep the
                # distinct flag so admin views name WHY it was benched.
                rep.fenced = bool(payload.get("fenced"))
                if payload.get("queue_depth") is not None:
                    rep.queue_depth = float(payload["queue_depth"])
            if rep.healthy and not rep.removed:
                healthy += 1
        self._m_replicas.set(healthy, state="healthy")
        with self._cond:
            self._cond.notify_all()
