"""Router recovery journal: the front door's crash-consistent ledger.

The router's in-flight ledger (PR 8) survives any *replica* death, but it
lives in the router process's memory — a router death loses every
pending/in-flight entry, and the requests stranded there are exactly the
ones whose clients are blocked waiting. This module makes the ledger's
state transitions durable:

- **accept** — a request passed admission. The entry carries the full
  request payload (base64 input bytes, deadline, SLO class) because the
  successor must be able to *re-dispatch* it, not merely know it existed.
  The deadline is stored as a WALL-clock absolute (monotonic clocks are
  per-process and meaningless to a successor); a bounded wall-clock skew
  therefore shifts replayed deadlines, never the router's own live
  deadline math, which stays monotonic.
- **dispatch** — a pending→inflight transition (replica + request epoch).
  Forensic: replay does not branch on it — an accept without a done is
  orphaned whether it was queued or mid-RPC when the router died.
- **done** — a terminal delivery (served/failed/rejected/drained).

``accept`` and ``done`` are fsync'd by default: they are the entries
correctness rides on (an un-synced accept would silently drop a request
from replay; an un-synced done would re-dispatch a completed one — the
replica-side idempotency cache then has to catch it). ``dispatch``
entries only flush.

**Epoch fencing.** Every incarnation of a router (same name, same
journal file) appends an ``epoch`` marker at open; its entries carry
that ``router_epoch``. :func:`scan` folds the whole multi-incarnation
history per trace id: a ``done`` in ANY epoch completes the id, so a
stale accept from an older epoch for a request a newer incarnation
already finished is a no-op — the cross-restart twin of the per-request
dispatch epoch that already fences stale replica RPCs.

Torn tails are expected (a SIGKILL mid-write): the scanner skips any
line that does not parse, and the appender always starts a fresh line.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import threading
import time

import numpy as np


@dataclasses.dataclass
class JournalOrphan:
    """One accepted-but-never-completed request recovered from a journal."""

    trace_id: str
    x: np.ndarray
    deadline_wall: float
    slo_class: "str | None"
    router_epoch: int
    tiled: bool = False  # re-dispatch to /predict_tiled, not /predict
    tenant: "str | None" = None  # re-dispatch under the same tenant

    def remaining_s(self, now: "float | None" = None) -> float:
        return self.deadline_wall - (time.time() if now is None else now)


@dataclasses.dataclass
class JournalScan:
    """What a journal file says happened before this incarnation."""

    orphans: "list[JournalOrphan]"
    completed: int = 0        # trace ids with a done entry
    expired: int = 0          # orphans whose deadline already passed
    skipped_lines: int = 0    # torn/unparseable lines tolerated
    last_epoch: int = 0       # highest epoch marker seen


def scan(path: str, now: "float | None" = None) -> JournalScan:
    """Fold a journal file into orphans + completion counts. Safe on a
    missing file (empty scan), a torn final line (skipped), and
    multi-incarnation histories (accept re-journaled by a replaying
    successor dedupes by trace id; done in any epoch completes)."""
    now = time.time() if now is None else now
    accepts: "dict[str, dict]" = {}
    done: "set[str]" = set()
    skipped = 0
    last_epoch = 0
    try:
        fh = open(path, "rb")
    except OSError:
        return JournalScan(orphans=[])
    with fh:
        for raw in fh:
            try:
                ev = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                skipped += 1
                continue
            kind = ev.get("kind")
            if kind == "epoch":
                last_epoch = max(last_epoch, int(ev.get("router_epoch", 0)))
            elif kind == "accept":
                accepts[ev["trace_id"]] = ev
            elif kind == "done":
                done.add(ev["trace_id"])
    orphans: "list[JournalOrphan]" = []
    completed = 0
    expired = 0
    for tid, ev in accepts.items():
        if tid in done:
            completed += 1
            continue
        if float(ev["deadline_wall"]) <= now:
            expired += 1
            continue
        try:
            x = np.frombuffer(
                base64.b64decode(ev["x_b64"]), dtype=ev["dtype"]
            ).reshape(ev["shape"])
        except (KeyError, ValueError):
            skipped += 1  # a corrupt payload cannot be re-dispatched
            continue
        orphans.append(JournalOrphan(
            trace_id=tid, x=x,
            deadline_wall=float(ev["deadline_wall"]),
            slo_class=ev.get("slo_class"),
            router_epoch=int(ev.get("router_epoch", 0)),
            tiled=bool(ev.get("tiled", False)),
            tenant=ev.get("tenant"),
        ))
    return JournalScan(
        orphans=orphans, completed=completed, expired=expired,
        skipped_lines=skipped, last_epoch=last_epoch,
    )


class RouterJournal:
    """Append-only recovery journal for one router name.

    Opening scans whatever a predecessor left (``.recovered``), then
    appends a fresh epoch marker — entries written by this incarnation
    carry ``router_epoch = predecessor's + 1``. All writes are
    line-atomic appends under a lock; ``fsync=True`` (default) syncs the
    correctness-bearing kinds (accept/done) to disk before returning.
    """

    SYNCED_KINDS = ("accept", "done", "epoch")

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self._fsync = bool(fsync)
        self._lock = threading.Lock()
        self.recovered = scan(path)
        self.router_epoch = self.recovered.last_epoch + 1
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "ab")
        self._append({"kind": "epoch", "router_epoch": self.router_epoch,
                      "ts": time.time()})

    # -- transitions ----------------------------------------------------------

    def accept(
        self,
        trace_id: str,
        x: np.ndarray,
        deadline_remaining_s: float,
        slo_class: "str | None" = None,
        tiled: bool = False,
        tenant: "str | None" = None,
    ) -> None:
        self._append({
            "kind": "accept",
            "trace_id": str(trace_id),
            "x_b64": base64.b64encode(
                np.ascontiguousarray(x).tobytes()
            ).decode(),
            "dtype": str(x.dtype),
            "shape": [int(d) for d in x.shape],
            "deadline_wall": time.time() + float(deadline_remaining_s),
            "slo_class": slo_class,
            "tiled": bool(tiled),
            "tenant": tenant,
            "router_epoch": self.router_epoch,
        })

    def dispatch(self, trace_id: str, replica: str, epoch: int) -> None:
        self._append({
            "kind": "dispatch", "trace_id": str(trace_id),
            "replica": str(replica), "epoch": int(epoch),
            "router_epoch": self.router_epoch,
        })

    def done(self, trace_id: str, outcome: str) -> None:
        self._append({
            "kind": "done", "trace_id": str(trace_id),
            "outcome": str(outcome), "router_epoch": self.router_epoch,
        })

    # -- plumbing -------------------------------------------------------------

    def _append(self, ev: dict) -> None:
        line = (json.dumps(ev) + "\n").encode()
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line)
            self._fh.flush()
            if self._fsync and ev["kind"] in self.SYNCED_KINDS:
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
