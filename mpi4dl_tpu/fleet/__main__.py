"""``python -m mpi4dl_tpu.fleet`` — spawn a replica fleet, load it, break it.

Builds a router + N supervised replica workers (synthetic calibrated
ResNet each — no artifacts needed), runs the requested load model
THROUGH the router, optionally injects chaos mid-run (``--chaos
kill:1``: the drills of :mod:`mpi4dl_tpu.fleet.chaos`), waits for the
supervisor to restore the fleet, and prints ONE JSON report line to
stdout (bench.py's keep-the-last-line protocol) with the loadgen
numbers, requeue counts, restart log, and recovery latency.

``--plan`` is the pure-dispatch mode: parse everything, print the fleet
plan as JSON, exit — no processes, no compiles, no devices (the CLI
smoke-test surface).

Examples::

    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --requests 128 --concurrency 16
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --chaos kill:1@1.5 --requests 256 --json /tmp/drill.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.fleet",
        description="mpi4dl_tpu replica fleet: router + supervised "
                    "replicas + chaos drills",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2,
                   help="initial replica count (the autoscale floor)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscale ceiling (default: --replicas)")
    p.add_argument("--chaos", action="append", default=[],
                   metavar="SPEC",
                   help="fault injection, repeatable: "
                        "ACTION[:TARGET][=SECONDS][@AT] with actions "
                        "kill, wedge, blackhole, delay-scrape, delay — "
                        "e.g. kill:1@1.5 (SIGKILL replica 1, 1.5s into "
                        "load) or delay:1=0.3 (straggler: slow replica "
                        "1's serving path by 0.3s per batch)")
    p.add_argument("--plan", action="store_true",
                   help="print the fleet plan as JSON and exit without "
                        "spawning anything (pure dispatch)")
    # worker / model
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--depth", type=int, default=None,
                   help="synthetic ResNet-v2 depth (9n+2); default tiny")
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--replica-max-queue", type=int, default=64)
    p.add_argument("--worker-watchdog-min-timeout", type=float, default=1.0,
                   help="replica stall-detector floor; drills keep it "
                        "small so a wedge is declared fast")
    # router
    p.add_argument("--max-queue", type=int, default=256,
                   help="router admission bound")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="dispatch errors per request before its future "
                        "fails (typed)")
    p.add_argument("--inflight-per-replica", type=int, default=4)
    # supervision
    p.add_argument("--heartbeat-timeout", type=float, default=5.0)
    p.add_argument("--breaker-max-restarts", type=int, default=3)
    p.add_argument("--breaker-window", type=float, default=60.0)
    p.add_argument("--no-federation", action="store_true",
                   help="static desired-replica count instead of the "
                        "federated autoscale gauge")
    p.add_argument("--recovery-timeout", type=float, default=180.0,
                   help="post-load wait for the supervisor to restore "
                        "the fleet to the desired count")
    # load
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--requests", type=int, default=128,
                   help="closed loop: total requests")
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--rate", type=float, default=100.0,
                   help="open loop: offered requests/sec")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--deadline-ms", type=float, default=30000.0)
    p.add_argument("--slo-classes", default=None, metavar="SPEC",
                   help="named SLO classes (NAME=THRESHOLD[:TARGET_PCT]"
                        "[@DEADLINE], comma-separated) configured on the "
                        "router AND every worker engine, so slo_class "
                        "propagates client -> router -> replica scheduler")
    p.add_argument("--class-mix", default=None, metavar="MIX",
                   help="loadgen class mix NAME:WEIGHT[:DEADLINE], "
                        "comma-separated; report carries by_class")
    p.add_argument("--queue-full-retries", type=int, default=0)
    # observability
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve the router registry (fleet_* + federated "
                        "view) on this port (0 = ephemeral)")
    p.add_argument("--telemetry-dir", default=None,
                   help="JSONL span logs (router + every replica) land "
                        "here; default: a temp dir, echoed on stderr — "
                        "feed it to `analyze trace-export`")
    p.add_argument("--spawn-timeout", type=float, default=600.0)
    p.add_argument("--json", dest="json_out", default=None)
    return p


def plan(args) -> dict:
    """The pure-dispatch fleet plan (validated chaos specs included) —
    what `--plan` prints and the CLI smoke asserts on."""
    from mpi4dl_tpu.fleet.chaos import parse_chaos_specs
    from mpi4dl_tpu.fleet.replica import worker_cmd

    ops = parse_chaos_specs(args.chaos)
    for op in ops:
        if op.target >= args.replicas:
            raise ValueError(
                f"chaos target r{op.target} outside --replicas "
                f"{args.replicas}"
            )
    return {
        "replicas": args.replicas,
        "max_replicas": args.max_replicas or args.replicas,
        "mode": args.mode,
        "chaos": [op.describe() for op in ops],
        "worker_cmd": worker_cmd(_worker_args(args)),
        "federation": not args.no_federation,
    }


def _worker_args(args) -> "list[str]":
    out = [
        "--image-size", str(args.image_size),
        "--max-batch", str(args.max_batch),
        "--max-queue", str(args.replica_max_queue),
        "--watchdog-min-timeout", str(args.worker_watchdog_min_timeout),
    ]
    if args.depth is not None:
        out += ["--depth", str(args.depth)]
    if args.telemetry_dir:
        out += ["--telemetry-dir", args.telemetry_dir]
    if args.slo_classes:
        out += ["--slo-classes", args.slo_classes]
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        the_plan = plan(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.plan:
        print(json.dumps(the_plan))
        return 0

    import tempfile

    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.fleet.chaos import ChaosMonkey, parse_chaos_specs
    from mpi4dl_tpu.fleet.router import Router
    from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
    from mpi4dl_tpu.serve.loadgen import run_closed_loop, run_open_loop
    from mpi4dl_tpu.telemetry.autoscale import AutoscaleConfig

    if not args.telemetry_dir:
        args.telemetry_dir = tempfile.mkdtemp(prefix="mpi4dl-fleet-tele-")
        print(f"# telemetry: {args.telemetry_dir}", file=sys.stderr,
              flush=True)

    size = args.image_size
    router = Router(
        example_shape=(size, size, 3),
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_ms / 1e3,
        max_attempts=args.max_attempts,
        inflight_per_replica=args.inflight_per_replica,
        telemetry_dir=args.telemetry_dir,
        slo_classes=args.slo_classes,
    )
    federation = None
    if not args.no_federation:
        federation = telemetry.SLOConfig(
            availability=0.999, interval_s=1.0,
            autoscale=AutoscaleConfig(
                min_replicas=args.replicas,
                max_replicas=args.max_replicas or args.replicas,
            ),
        )
    sup = FleetSupervisor(
        _worker_args(args),
        router=router,
        replicas=args.replicas,
        max_replicas=args.max_replicas or args.replicas,
        federation=federation,
        heartbeat_timeout_s=args.heartbeat_timeout,
        breaker_max_restarts=args.breaker_max_restarts,
        breaker_window_s=args.breaker_window,
        spawn_timeout_s=args.spawn_timeout,
    )
    server = None
    if args.metrics_port is not None:
        registry = (
            sup.aggregator.registry if sup.aggregator is not None
            else router.registry
        )
        server = telemetry.MetricsServer(
            registry, port=args.metrics_port,
            health=router.health_snapshot,
            debug=lambda: {
                "router": router.stats(), "supervisor": sup.state(),
            },
        )
        print(
            f"# metrics: http://127.0.0.1:{server.port}/metrics "
            "(also /snapshotz, /healthz, /debugz)",
            file=sys.stderr, flush=True,
        )

    report = {"fleet": the_plan}
    rc = 0
    monkey = None
    try:
        t_up = time.monotonic()
        sup.start()
        sup.wait_ready(timeout_s=args.spawn_timeout)
        report["fleet"]["startup_s"] = time.monotonic() - t_up
        print(
            f"# fleet up: {sup.running_count()} replica(s) in "
            f"{report['fleet']['startup_s']:.1f}s",
            file=sys.stderr, flush=True,
        )

        monkey = ChaosMonkey(parse_chaos_specs(args.chaos), sup)
        monkey.start()
        mix_kw = {}
        if args.class_mix:
            from mpi4dl_tpu.serve.loadgen import ClassMix

            mix_kw["class_mix"] = ClassMix.parse(args.class_mix)
        if args.mode == "closed":
            report["loadgen"] = run_closed_loop(
                router, args.requests, concurrency=args.concurrency,
                deadline_s=args.deadline_ms / 1e3, events=router.events,
                queue_full_retries=args.queue_full_retries, **mix_kw,
            )
        else:
            report["loadgen"] = run_open_loop(
                router, rate_rps=args.rate, duration_s=args.duration,
                deadline_s=args.deadline_ms / 1e3, events=router.events,
                queue_full_retries=args.queue_full_retries, **mix_kw,
            )

        # Post-load: the drill isn't over until every scheduled chaos op
        # has actually fired (a fast load run must not outrun its own
        # drill) AND the supervisor has restored the fleet (or the
        # recovery window expires — reported either way, failed loudly
        # when chaos was requested).
        deadline = time.monotonic() + args.recovery_timeout
        n_ops = len(monkey.ops)
        while time.monotonic() < deadline and len(monkey.log) < n_ops:
            time.sleep(0.1)
        while time.monotonic() < deadline:
            if (
                len(monkey.log) >= n_ops
                and sup.running_count() >= sup.desired_replicas()
            ):
                break
            time.sleep(0.25)
        restored = sup.running_count() >= sup.desired_replicas()
        report["chaos"] = monkey.log
        report["supervisor"] = sup.state()
        report["router"] = router.stats()
        if sup.aggregator is not None:
            # Straggler view (a `delay` drill's verdict surface): which
            # replica drags the fleet tail, per the federated skew score.
            report["straggler"] = sup.aggregator.straggler_state()
        report["recovered"] = restored
        report["recovery_s"] = sup.last_recovery_s
        if args.chaos and not restored:
            rc = 1
    finally:
        if monkey is not None:
            monkey.close()
        sup.close()
        router.stop(drain=False)
        if server is not None:
            server.close()

    line = json.dumps(report)
    print(line, flush=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
