"""``python -m mpi4dl_tpu.fleet`` — spawn a replica fleet, load it, break it.

Builds a router + N supervised replica workers (synthetic calibrated
ResNet each — no artifacts needed), runs the requested load model
THROUGH the router, optionally injects chaos mid-run (``--chaos
kill:1``: the drills of :mod:`mpi4dl_tpu.fleet.chaos`), waits for the
supervisor to restore the fleet, and prints ONE JSON report line to
stdout (bench.py's keep-the-last-line protocol) with the loadgen
numbers, requeue counts, restart log, and recovery latency.

``--plan`` is the pure-dispatch mode: parse everything, print the fleet
plan as JSON, exit — no processes, no compiles, no devices (the CLI
smoke-test surface).

Examples::

    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --requests 128 --concurrency 16
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --chaos kill:1@1.5 --requests 256 --json /tmp/drill.json
    # HA front door: 2 router processes, kill one mid-load — the client
    # fails over, the successor replays the dead router's journal:
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --routers 2 --chaos kill:router@1.5 --requests 256
    # Warm pool: replica deaths promote a standby instead of respawning:
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.fleet --replicas 2 \
        --warm-pool 1 --chaos kill:1@1.5 --requests 256
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.fleet",
        description="mpi4dl_tpu replica fleet: router + supervised "
                    "replicas + chaos drills",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2,
                   help="initial replica count (the autoscale floor)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscale ceiling (default: --replicas)")
    p.add_argument("--routers", type=int, default=1,
                   help="front-door router PROCESSES (the HA front "
                        "door; each journals for router-death replay). "
                        "0 = one in-process router (the pre-HA shape)")
    p.add_argument("--warm-pool", type=int, default=0,
                   help="extra replicas kept warm but unrouted; a "
                        "replica death promotes one (routing flip) "
                        "instead of paying a cold spawn")
    p.add_argument("--replay-grace", type=float, default=1.5,
                   help="seconds a successor router parks journal "
                        "orphans polling replica served-caches before "
                        "re-dispatching")
    p.add_argument("--chaos", action="append", default=[],
                   metavar="SPEC",
                   help="fault injection, repeatable: "
                        "ACTION[:TARGET][=SECONDS][@AT] with actions "
                        "kill, wedge, blackhole, delay-scrape, delay, "
                        "flood — e.g. kill:1@1.5 (SIGKILL replica 1, "
                        "1.5s into load), kill:router (SIGKILL router 0 "
                        "— the successor replays its journal), "
                        "delay:1=0.3 (straggler: slow replica 1's "
                        "serving path by 0.3s per batch), or "
                        "flood:bulk=500@2 (noisy neighbor: offer 500 "
                        "rps as tenant 'bulk' for a 2s burst — needs "
                        "--tenants naming that tenant)")
    p.add_argument("--plan", action="store_true",
                   help="print the fleet plan as JSON and exit without "
                        "spawning anything (pure dispatch)")
    # worker / model
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--mesh", default=None, metavar="HxW",
                   help="every replica claims a tile_h x tile_w device "
                        "subset and serves the spatially-sharded forward "
                        "(worker --mesh): shard for model size, "
                        "replicate for traffic — two orthogonal axes")
    p.add_argument("--depth", type=int, default=None,
                   help="synthetic ResNet-v2 depth (9n+2); default tiny")
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--replica-max-queue", type=int, default=64)
    p.add_argument("--worker-watchdog-min-timeout", type=float, default=1.0,
                   help="replica stall-detector floor; drills keep it "
                        "small so a wedge is declared fast")
    # router
    p.add_argument("--max-queue", type=int, default=256,
                   help="router admission bound")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="dispatch errors per request before its future "
                        "fails (typed)")
    p.add_argument("--inflight-per-replica", type=int, default=4)
    # supervision
    p.add_argument("--heartbeat-timeout", type=float, default=5.0)
    p.add_argument("--breaker-max-restarts", type=int, default=3)
    p.add_argument("--breaker-window", type=float, default=60.0)
    p.add_argument("--no-federation", action="store_true",
                   help="static desired-replica count instead of the "
                        "federated autoscale gauge")
    p.add_argument("--recovery-timeout", type=float, default=180.0,
                   help="post-load wait for the supervisor to restore "
                        "the fleet to the desired count")
    # load
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--requests", type=int, default=128,
                   help="closed loop: total requests")
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--rate", type=float, default=100.0,
                   help="open loop: offered requests/sec")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--deadline-ms", type=float, default=30000.0)
    p.add_argument("--slo-classes", default=None, metavar="SPEC",
                   help="named SLO classes (NAME=THRESHOLD[:TARGET_PCT]"
                        "[@DEADLINE], comma-separated) configured on the "
                        "router AND every worker engine, so slo_class "
                        "propagates client -> router -> replica scheduler")
    p.add_argument("--class-mix", default=None, metavar="MIX",
                   help="loadgen class mix NAME:WEIGHT[:DEADLINE], "
                        "comma-separated; report carries by_class")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant admission on EVERY router and "
                        "worker engine: NAME=RPS:BURST[:WEIGHT]"
                        "[@CLASSES] comma-separated ('NAME=none' = "
                        "unlimited); over-quota floods shed at the "
                        "front door with retry_after_s before taking "
                        "queue slots. NOTE each router refills its own "
                        "buckets, so R router processes admit up to "
                        "R x the configured rate per tenant")
    p.add_argument("--tenant-mix", default=None, metavar="MIX",
                   help="loadgen tenant mix NAME:WEIGHT, comma-"
                        "separated; report carries by_tenant")
    p.add_argument("--queue-full-retries", type=int, default=0)
    # observability
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve the router registry (fleet_* + federated "
                        "view) on this port (0 = ephemeral)")
    p.add_argument("--telemetry-dir", default=None,
                   help="JSONL span logs (router + every replica) land "
                        "here; default: a temp dir, echoed on stderr — "
                        "feed it to `analyze trace-export`")
    p.add_argument("--spawn-timeout", type=float, default=600.0)
    p.add_argument("--json", dest="json_out", default=None)
    return p


def plan(args) -> dict:
    """The pure-dispatch fleet plan (validated chaos specs included) —
    what `--plan` prints and the CLI smoke asserts on."""
    from mpi4dl_tpu.fleet.chaos import parse_chaos_specs
    from mpi4dl_tpu.fleet.replica import worker_cmd

    from mpi4dl_tpu.fleet.frontdoor import router_cmd

    ops = parse_chaos_specs(args.chaos)
    tenant_names = None
    if args.tenants:
        from mpi4dl_tpu.tenancy.model import parse_tenants

        tenant_names = {t.name for t in parse_tenants(args.tenants)}
    for op in ops:
        if op.domain == "tenant":
            if tenant_names is None:
                raise ValueError(
                    f"chaos flood targets tenant {op.tenant!r} but no "
                    "--tenants spec declares any tenants (the flood "
                    "drill needs a quota to shed against)"
                )
            if op.tenant not in tenant_names:
                raise ValueError(
                    f"chaos flood tenant {op.tenant!r} not in --tenants "
                    f"(configured: {sorted(tenant_names)})"
                )
        elif op.domain == "router":
            if op.target >= max(args.routers, 0):
                raise ValueError(
                    f"chaos target router{op.target} outside --routers "
                    f"{args.routers}"
                )
        elif op.target >= args.replicas + args.warm_pool:
            raise ValueError(
                f"chaos target r{op.target} outside --replicas "
                f"{args.replicas} (+{args.warm_pool} warm pool)"
            )
    return {
        "replicas": args.replicas,
        "max_replicas": args.max_replicas or args.replicas,
        "routers": args.routers,
        "warm_pool": args.warm_pool,
        "mode": args.mode,
        "chaos": [op.describe() for op in ops],
        "worker_cmd": worker_cmd(_worker_args(args)),
        "router_cmd": (
            router_cmd(_router_args(args)) if args.routers else None
        ),
        "federation": not args.no_federation,
    }


def _worker_args(args) -> "list[str]":
    out = [
        "--image-size", str(args.image_size),
        "--max-batch", str(args.max_batch),
        "--max-queue", str(args.replica_max_queue),
        "--watchdog-min-timeout", str(args.worker_watchdog_min_timeout),
    ]
    if args.depth is not None:
        out += ["--depth", str(args.depth)]
    if args.mesh:
        out += ["--mesh", args.mesh]
    if args.telemetry_dir:
        out += ["--telemetry-dir", args.telemetry_dir]
    if args.slo_classes:
        out += ["--slo-classes", args.slo_classes]
    if args.tenants:
        out += ["--tenants", args.tenants]
    return out


def _router_args(args) -> "list[str]":
    out = [
        "--image-size", str(args.image_size),
        "--max-queue", str(args.max_queue),
        "--max-attempts", str(args.max_attempts),
        "--inflight-per-replica", str(args.inflight_per_replica),
        "--default-deadline-s", str(args.deadline_ms / 1e3),
        "--replay-grace", str(args.replay_grace),
    ]
    if args.telemetry_dir:
        out += ["--telemetry-dir", args.telemetry_dir]
    if args.slo_classes:
        out += ["--slo-classes", args.slo_classes]
    if args.tenants:
        out += ["--tenants", args.tenants]
    return out


def _journal_replays(sup) -> "dict | None":
    """Sum fleet_router_journal_replays_total across the running router
    processes' /snapshotz — the CLI-report twin of the drill assertion."""
    import urllib.request

    out: "dict[str, float]" = {}
    for name, url in sup.router_metrics_urls().items():
        try:
            with urllib.request.urlopen(url + "/snapshotz", timeout=5) as r:
                snap = json.loads(r.read().decode())
        except Exception:  # noqa: BLE001 — a mid-restart router
            continue
        metric = snap.get("metrics", {}).get(
            "fleet_router_journal_replays_total"
        )
        for series in (metric or {}).get("series", ()):
            key = series.get("labels", {}).get("outcome", "total")
            out[key] = out.get(key, 0) + series.get("value", 0)
    return out or None


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        the_plan = plan(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.plan:
        print(json.dumps(the_plan))
        return 0

    import tempfile

    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.fleet.chaos import ChaosMonkey, parse_chaos_specs
    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.fleet.router import Router
    from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
    from mpi4dl_tpu.serve.loadgen import run_closed_loop, run_open_loop
    from mpi4dl_tpu.telemetry.autoscale import AutoscaleConfig

    if not args.telemetry_dir:
        args.telemetry_dir = tempfile.mkdtemp(prefix="mpi4dl-fleet-tele-")
        print(f"# telemetry: {args.telemetry_dir}", file=sys.stderr,
              flush=True)

    size = args.image_size
    router = None
    if args.routers <= 0:
        # The pre-HA shape: one in-process router (no failure domain of
        # its own, but also no HTTP hop for the client).
        router = Router(
            example_shape=(size, size, 3),
            max_queue=args.max_queue,
            default_deadline_s=args.deadline_ms / 1e3,
            max_attempts=args.max_attempts,
            inflight_per_replica=args.inflight_per_replica,
            telemetry_dir=args.telemetry_dir,
            slo_classes=args.slo_classes,
            tenants=args.tenants,
        )
    federation = None
    if not args.no_federation:
        federation = telemetry.SLOConfig(
            availability=0.999, interval_s=1.0,
            autoscale=AutoscaleConfig(
                min_replicas=args.replicas,
                max_replicas=args.max_replicas or args.replicas,
            ),
        )
    sup = FleetSupervisor(
        _worker_args(args),
        router=router,
        # Supervisor-side event sink: elastic.restart + the chaos
        # self-labels (chaos.injected) land next to the worker logs, so
        # the incident engine's first-cause table can blame an injected
        # op by name. Without it the HA shape (router subprocesses, no
        # in-process router to borrow a writer from) logged nothing.
        events=telemetry.JsonlWriter(
            args.telemetry_dir, filename=f"fleet-events-{os.getpid()}.jsonl"
        ),
        routers=max(args.routers, 0),
        router_args=_router_args(args) if args.routers > 0 else None,
        warm_pool=args.warm_pool,
        replicas=args.replicas,
        max_replicas=args.max_replicas or args.replicas,
        federation=federation,
        heartbeat_timeout_s=args.heartbeat_timeout,
        breaker_max_restarts=args.breaker_max_restarts,
        breaker_window_s=args.breaker_window,
        spawn_timeout_s=args.spawn_timeout,
    )
    incidents = None
    if sup.aggregator is not None and sup.aggregator.incidents is not None:
        # The incident engine's paper trail: lifecycle events land in
        # the fleet telemetry dir (next to every other signal it
        # correlates), and the supervisor's flight ring files dumps
        # under the open incident.
        incidents = sup.aggregator.incidents
        incidents.telemetry_dir = args.telemetry_dir
        incidents.events = telemetry.JsonlWriter(
            args.telemetry_dir, filename=f"incidents-{os.getpid()}.jsonl"
        )
        flight = getattr(sup, "_flight", None)
        if flight is not None:
            flight.incident = incidents.open_incident_id
    server = None
    if args.metrics_port is not None:
        registry = (
            sup.aggregator.registry if sup.aggregator is not None
            else (router.registry if router is not None else sup.registry)
        )
        server = telemetry.MetricsServer(
            registry, port=args.metrics_port,
            health=(router.health_snapshot if router is not None else None),
            debug=lambda: {
                "router": router.stats() if router is not None else None,
                "supervisor": sup.state(),
            },
            alerts=(
                sup.aggregator.alertz_state
                if sup.aggregator is not None else None
            ),
            incidents=incidents.state if incidents is not None else None,
        )
        print(
            f"# metrics: http://127.0.0.1:{server.port}/metrics "
            "(also /snapshotz, /healthz, /debugz"
            + (", /alertz, /incidentz" if sup.aggregator is not None
               else "") + ")",
            file=sys.stderr, flush=True,
        )

    report = {"fleet": the_plan}
    rc = 0
    monkey = None
    client = None
    try:
        t_up = time.monotonic()
        sup.start()
        sup.wait_ready(timeout_s=args.spawn_timeout)
        report["fleet"]["startup_s"] = time.monotonic() - t_up
        print(
            f"# fleet up: {sup.running_count()} replica(s), "
            f"{sup.standby_count()} standby, "
            f"{sup.running_router_count()} router(s) in "
            f"{report['fleet']['startup_s']:.1f}s",
            file=sys.stderr, flush=True,
        )
        if router is not None:
            target = router
        else:
            # The client-side half of the HA front door: failover across
            # the router set, same loadgen surface as one engine.
            target = client = RouterSetClient(
                sup.router_submit_urls(),
                example_shape=(size, size, 3),
                default_deadline_s=args.deadline_ms / 1e3,
                telemetry_dir=args.telemetry_dir,
            )

        def _flood(op):
            # Noisy-neighbor injector: a fixed-length open-loop burst
            # offered THROUGH the front door under the flood tenant,
            # concurrent with the main load run. The returned outcome
            # counts are the drill's evidence: a healthy quota sheds
            # most of the burst as rejected_quota.
            from mpi4dl_tpu.fleet.chaos import FLOOD_DURATION_S
            from mpi4dl_tpu.serve.loadgen import TenantMix

            rep = run_open_loop(
                target, rate_rps=op.rps, duration_s=FLOOD_DURATION_S,
                deadline_s=args.deadline_ms / 1e3,
                tenant_mix=TenantMix({op.tenant: 1.0}),
            )
            return {
                "duration_s": FLOOD_DURATION_S,
                "offered": rep["offered"],
                "served": rep["served"],
                "rejected_quota": rep["rejected_quota"],
                "rejected_queue_full": rep["rejected_queue_full"],
                "deadline_misses": rep["deadline_misses"],
                "errors": rep["errors"],
            }

        monkey = ChaosMonkey(parse_chaos_specs(args.chaos), sup,
                             flood=_flood)
        monkey.start()
        mix_kw = {}
        if args.class_mix:
            from mpi4dl_tpu.serve.loadgen import ClassMix

            mix_kw["class_mix"] = ClassMix.parse(args.class_mix)
        if args.tenant_mix:
            from mpi4dl_tpu.serve.loadgen import TenantMix

            mix_kw["tenant_mix"] = TenantMix.parse(args.tenant_mix)
        if args.mode == "closed":
            report["loadgen"] = run_closed_loop(
                target, args.requests, concurrency=args.concurrency,
                deadline_s=args.deadline_ms / 1e3, events=target.events,
                queue_full_retries=args.queue_full_retries, **mix_kw,
            )
        else:
            report["loadgen"] = run_open_loop(
                target, rate_rps=args.rate, duration_s=args.duration,
                deadline_s=args.deadline_ms / 1e3, events=target.events,
                queue_full_retries=args.queue_full_retries, **mix_kw,
            )

        # Post-load: the drill isn't over until every scheduled chaos op
        # has actually fired (a fast load run must not outrun its own
        # drill) AND the supervisor has restored the fleet — serving
        # replicas, warm pool, AND the router set (or the recovery
        # window expires — reported either way, failed loudly when
        # chaos was requested).
        def _restored() -> bool:
            return (
                sup.running_count() >= sup.desired_replicas()
                and sup.standby_count() >= args.warm_pool
                and sup.running_router_count() >= max(args.routers, 0)
            )

        deadline = time.monotonic() + args.recovery_timeout
        n_ops = len(monkey.ops)
        while time.monotonic() < deadline and len(monkey.log) < n_ops:
            time.sleep(0.1)
        while time.monotonic() < deadline:
            if len(monkey.log) >= n_ops and _restored():
                break
            time.sleep(0.25)
        restored = _restored()
        report["chaos"] = monkey.log
        report["supervisor"] = sup.state()
        report["router"] = (
            router.stats() if router is not None else client.stats()
        )
        report["router_failovers"] = report["loadgen"].get(
            "router_failovers", 0
        )
        if args.routers > 0:
            report["journal_replays"] = _journal_replays(sup)
        if sup.aggregator is not None:
            # Straggler view (a `delay` drill's verdict surface): which
            # replica drags the fleet tail, per the federated skew score.
            report["straggler"] = sup.aggregator.straggler_state()
        report["recovered"] = restored
        report["recovery_s"] = {
            "replica": sup.last_recovery_s,
            "router": sup.last_router_recovery_s,
        }
        report["promotions"] = sup.promotions
        if incidents is not None:
            # The drill's verdict surface: what the incident engine made
            # of the chaos (full timelines live on /incidentz and in the
            # incident-*.json postmortems next to the logs).
            report["incidents"] = {
                "open": (
                    incidents.open_incident["id"]
                    if incidents.open_incident else None
                ),
                "opened_total": incidents.opened_total,
                "closed": [
                    {"id": r["id"], "opened_by": r["opened_by"],
                     "mtta_s": r["mtta_s"], "mttr_s": r["mttr_s"],
                     "members": sorted(r["members"])}
                    for r in incidents.closed
                ],
            }
        if args.chaos and not restored:
            rc = 1
    finally:
        if monkey is not None:
            monkey.close()
        sup.close()
        if client is not None:
            client.close()
        if router is not None:
            router.stop(drain=False)
        if server is not None:
            server.close()

    line = json.dumps(report)
    print(line, flush=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
