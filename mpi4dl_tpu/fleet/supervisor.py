"""Fleet supervisor: reconcile N replica processes toward the desired count.

:func:`mpi4dl_tpu.elastic.supervise` babysits ONE process; this
generalizes it to a fleet. A daemon reconcile loop ticks every
``reconcile_interval_s`` and drives each replica slot's state machine::

    starting ──ready──▶ running ──death/wedge/503──▶ backoff ──▶ starting
        │                   │                           │
        │                   └──scale-down──▶ draining   └─K failures/window─▶
        └──exit/timeout──▶ backoff              │            circuit_open
                                                ▼                (paged)
                                             stopped

- **Deaths** (process exit, heartbeat loss beyond
  ``heartbeat_timeout_s``, ``/healthz`` 503 or unreachable for
  ``unhealthy_after`` straight probes) are remedied the only way a
  single-controller JAX process can be: kill what's left, requeue the
  victim's in-flight work through the router
  (:meth:`Router.remove_replica` — supervisor-confirmed death is the
  one safe moment to requeue), and respawn with exponential backoff +
  full jitter (:func:`elastic.full_jitter_backoff`).
- **Circuit breaker**: ``breaker_max_restarts`` failures within
  ``breaker_window_s`` (:class:`elastic.RestartBreaker`) flips the slot
  to ``circuit_open`` — no more respawns, traffic sheds to survivors —
  and pages through the existing :class:`telemetry.AlertState`
  machinery (``alert_active{alert="fleet_circuit_<slot>"}`` +
  ``alert.transition`` events), the same surface every other page in
  this stack rides.
- **Desired count**: a static target, or — with ``federation=`` (an
  :class:`telemetry.SLOConfig`) — the fleet-wide
  ``autoscale_desired_replicas`` gauge computed by a
  :class:`~mpi4dl_tpu.telemetry.federation.FederatedAggregator` over
  the replicas' ``/snapshotz`` endpoints: the PR-5/6 advisory signal,
  finally actuated. Scale-down drains: stop admissions to the victim
  (router-side), flush its in-flight ledger, then SIGTERM (the worker
  serves its queue and exits 0; drained requests are a lifecycle
  outcome, not an availability failure).

Every restart decision lands as the same schema-valid
``elastic.restart`` JSONL event the single-process supervisor emits.
"""

from __future__ import annotations

import os
import threading
import time

from mpi4dl_tpu import elastic, telemetry
from mpi4dl_tpu.fleet.replica import ReplicaClient, ReplicaProcess

SUPERVISOR_METRICS = (
    "fleet_replicas",
    "fleet_replica_restarts_total",
    "fleet_recovery_seconds",
    "fleet_recovery_phase_seconds",
    "fleet_routers",
    "fleet_standby_replicas",
    "fleet_promotions_total",
)


class _Slot:
    """One supervised slot (stable name across incarnations).

    kind: ``replica`` (a worker engine) or ``router`` (a front-door
        process, :mod:`mpi4dl_tpu.fleet.frontdoor`) — routers ride the
        SAME state machine, backoff, breaker, and paging.
    role: replicas only — ``serving`` (routed) or ``standby`` (warm
        pool: fully warmed, ready handshake passed, but unrouted until
        a promotion flips it in).
    """

    def __init__(self, name: str, index: int, breaker,
                 kind: str = "replica", role: str = "serving"):
        self.name = name
        self.index = index
        self.kind = kind
        self.role = role
        self.proc: "ReplicaProcess | None" = None
        self.state = "new"
        self.breaker = breaker
        self.attempt = 0          # consecutive failed incarnations
        self.respawn_at = 0.0
        self.unhealthy_streak = 0
        self.death_t: "float | None" = None
        self.last_reason: "str | None" = None
        self.ports: "dict | None" = None
        self.alert = telemetry.AlertState(
            f"fleet_circuit_{name}", "page", for_s=0.0
        )

    @property
    def pid(self) -> "int | None":
        return self.proc.pid if self.proc is not None else None

    def kill_hard(self) -> None:
        if self.proc is not None:
            self.proc.kill_hard()

    @property
    def client(self) -> "ReplicaClient | None":
        if self.ports is None:
            return None
        return ReplicaClient(
            self.name,
            f"http://127.0.0.1:{self.ports['predict_port']}",
        )

    def view(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "role": self.role,
            "state": self.state,
            "pid": self.pid,
            "attempt": self.attempt,
            "last_reason": self.last_reason,
            "breaker": self.breaker.state(),
            "ports": self.ports,
        }


class FleetSupervisor:
    """Spawn, watch, replace, and scale a fleet of replica workers.

    worker_args: extra argv for ``python -m mpi4dl_tpu.fleet.worker``
        (model size, watchdog knobs, telemetry dir...).
    router: the :class:`~mpi4dl_tpu.fleet.router.Router` to wire
        replicas into (None = supervision without dispatch — useful in
        drills/tests).
    registry: metrics registry; defaults to the router's so one scrape
        shows admission, dispatch, and supervision together.
    replicas: initial/static desired count (also the autoscale floor
        when ``federation`` is set, unless its config says otherwise).
    max_replicas: autoscale ceiling (static mode: a hard clamp).
    routers: front-door router PROCESSES to run
        (:mod:`mpi4dl_tpu.fleet.frontdoor`) — each gets a slot with the
        same backoff + breaker + ``fleet_circuit_*`` paging a replica
        slot gets; a respawned router recovers its predecessor's journal
        (the router failure domain of the exactly-once story). Replica
        membership is pushed to every running router over its
        ``POST /replicas`` admin feed. 0 = no process routers (the
        in-process ``router=`` keeps working either way).
    router_args: extra argv for the router processes (image size,
        queue bounds, SLO classes...). ``--name``/``--journal-dir`` are
        appended per slot.
    warm_pool: EXTRA replicas kept fully warmed (ready handshake — i.e.
        ``assert_warm`` — passed) but UNROUTED, in the ``standby`` slot
        state. A serving replica's death then promotes a standby
        (health handshake + routing flip, sub-second) instead of paying
        a cold spawn's warm-up compiles, and the pool is backfilled
        asynchronously. A standby that dies (or fails the promotion
        handshake) falls back to the cold-spawn path — promotion never
        routes a corpse, and never routes the same worker twice.
    federation: a :class:`telemetry.SLOConfig` — runs a
        :class:`FederatedAggregator` over the replicas and follows its
        fleet-wide ``autoscale_desired_replicas`` gauge. None = static.
    heartbeat_timeout_s: staleness beyond this kills + replaces (None
        disables; the worker's beats are health-gated, so a wedged
        batcher goes stale even while its process looks alive).
    unhealthy_after: consecutive failed/503 ``/healthz`` probes before
        kill + replace.
    backoff_base_s / backoff_max_s: respawn backoff (full jitter).
    breaker_max_restarts / breaker_window_s: per-slot circuit breaker.
    events / flight: ``elastic.restart`` + ``alert.transition`` sinks.
    """

    def __init__(
        self,
        worker_args: "list[str]",
        router=None,
        registry=None,
        base_dir: "str | None" = None,
        replicas: int = 1,
        max_replicas: "int | None" = None,
        routers: int = 0,
        router_args: "list[str] | None" = None,
        warm_pool: int = 0,
        federation=None,
        env: "dict | None" = None,
        reconcile_interval_s: float = 0.25,
        heartbeat_timeout_s: "float | None" = 5.0,
        unhealthy_after: int = 4,
        scrape_timeout_s: float = 1.0,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        breaker_max_restarts: int = 3,
        breaker_window_s: float = 60.0,
        spawn_timeout_s: float = 600.0,
        drain_timeout_s: float = 10.0,
        events=None,
        flight=None,
        clock=time.monotonic,
    ):
        import tempfile

        from mpi4dl_tpu.fleet.replica import worker_cmd

        self.router = router
        self.registry = (
            registry if registry is not None
            else (router.registry if router is not None
                  else telemetry.MetricsRegistry())
        )
        self._worker_cmd = worker_cmd(worker_args)
        self._routers = int(routers)
        self._router_args = list(router_args or ())
        self._warm_pool = int(warm_pool)
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="mpi4dl-fleet-")
        self._journal_dir = os.path.join(self.base_dir, "journals")
        self._env = dict(env if env is not None else os.environ)
        self._interval = float(reconcile_interval_s)
        self._hb_timeout = heartbeat_timeout_s
        self._unhealthy_after = int(unhealthy_after)
        self._scrape_timeout_s = float(scrape_timeout_s)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._breaker_max = int(breaker_max_restarts)
        self._breaker_window_s = float(breaker_window_s)
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._events = events if events is not None else (
            router.events if router is not None else None
        )
        self._flight = flight
        self._clock = clock
        self._static_desired = int(replicas)
        self._max_replicas = (
            int(max_replicas) if max_replicas is not None else int(replicas)
        )

        self._m_replicas = telemetry.declare(self.registry, "fleet_replicas")
        self._m_restarts = telemetry.declare(
            self.registry, "fleet_replica_restarts_total"
        )
        self._m_recovery = telemetry.declare(
            self.registry, "fleet_recovery_seconds"
        )
        self._m_recovery_phase = telemetry.declare(
            self.registry, "fleet_recovery_phase_seconds"
        )
        self._m_alert = telemetry.declare(self.registry, "alert_active")
        self._m_routers = telemetry.declare(self.registry, "fleet_routers")
        self._m_standby = telemetry.declare(
            self.registry, "fleet_standby_replicas"
        )
        self._m_promotions = telemetry.declare(
            self.registry, "fleet_promotions_total"
        )

        self._lock = threading.RLock()
        self._slots: "dict[str, _Slot]" = {}
        self.restarts = 0
        self.last_recovery_s: "float | None" = None
        self.last_recovery_phases: "dict | None" = None
        self.last_router_recovery_s: "float | None" = None
        self.promotions = 0

        self.aggregator = None
        if federation is not None:
            from mpi4dl_tpu.telemetry.federation import FederatedAggregator

            self.aggregator = FederatedAggregator(
                registry=self.registry,
                slo=federation,
                interval_s=max(0.25, self._interval),
                timeout_s=self._scrape_timeout_s,
            )

        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- public surface -------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            for i in range(self._routers):
                self._ensure_slot(i, kind="router")
            for i in range(self._static_desired):
                self._ensure_slot(i)
            for i in range(self._static_desired,
                           self._static_desired + self._warm_pool):
                self._ensure_slot(i, role="standby")
        if self.aggregator is not None:
            self.aggregator.start()
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run, name="mpi4dl-fleet-supervisor", daemon=True
            )
            self._thread.start()

    def wait_ready(self, timeout_s: float = 600.0) -> None:
        """Block until the fleet reaches the desired running count —
        serving replicas, the warm pool, AND the router set (the CLI's
        before-load barrier)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if (
                self.running_count() >= self.desired_replicas()
                and self.standby_count() >= self._warm_pool
                and self.running_router_count() >= self._routers
            ):
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"fleet not ready within {timeout_s:.0f}s: "
            f"{self.running_count()}/{self.desired_replicas()} serving, "
            f"{self.standby_count()}/{self._warm_pool} standby, "
            f"{self.running_router_count()}/{self._routers} routers"
        )

    def running_count(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._slots.values()
                if s.kind == "replica" and s.role == "serving"
                and s.state == "running"
            )

    def standby_count(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._slots.values()
                if s.kind == "replica" and s.state == "standby"
            )

    def running_router_count(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._slots.values()
                if s.kind == "router" and s.state == "running"
            )

    def router_submit_urls(self) -> "dict[str, str]":
        """``{name: submit_url}`` of the running router processes — what
        a :class:`~mpi4dl_tpu.fleet.frontdoor.RouterSetClient` fronts."""
        with self._lock:
            return {
                s.name: f"http://127.0.0.1:{s.ports['predict_port']}"
                for s in self._slots.values()
                if s.kind == "router" and s.state == "running"
                and s.ports is not None
            }

    def router_metrics_urls(self) -> "dict[str, str]":
        with self._lock:
            return {
                s.name: f"http://127.0.0.1:{s.ports['metrics_port']}"
                for s in self._slots.values()
                if s.kind == "router" and s.state == "running"
                and s.ports is not None and s.ports.get("metrics_port")
            }

    def desired_replicas(self) -> int:
        """The reconcile target: the fleet-wide autoscale gauge when
        federated (the PR-5 advisory signal, actuated), else the static
        count; clamped to ``[1, max_replicas]``."""
        desired = None
        if self.aggregator is not None:
            m = self.aggregator.registry.get("autoscale_desired_replicas")
            if m is not None:
                desired = m.value()
        if desired is None:
            desired = self._static_desired
        return max(1, min(int(desired), self._max_replicas))

    def slot_by_index(self, index: int) -> "_Slot | None":
        with self._lock:
            for s in self._slots.values():
                if s.kind == "replica" and s.index == index:
                    return s
        return None

    def router_slot_by_index(self, index: int) -> "_Slot | None":
        with self._lock:
            for s in self._slots.values():
                if s.kind == "router" and s.index == index:
                    return s
        return None

    def state(self) -> dict:
        with self._lock:
            slots = [s.view() for s in self._slots.values()]
        return {
            "desired": self.desired_replicas(),
            "running": self.running_count(),
            "standby": self.standby_count(),
            "routers": self.running_router_count(),
            "restarts": self.restarts,
            "promotions": self.promotions,
            "last_recovery_s": self.last_recovery_s,
            "last_recovery_phases": self.last_recovery_phases,
            "last_router_recovery_s": self.last_router_recovery_s,
            "slots": slots,
        }

    def close(self, terminate: bool = True) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.aggregator is not None:
            self.aggregator.close()
        if terminate:
            with self._lock:
                slots = list(self._slots.values())
            for s in slots:
                if s.proc is not None and s.proc.alive():
                    s.proc.terminate(wait_s=self._drain_timeout_s)

    # -- slot lifecycle -------------------------------------------------------

    def _ensure_slot(self, index: int, kind: str = "replica",
                     role: str = "serving") -> _Slot:
        name = f"rt{index}" if kind == "router" else f"r{index}"
        slot = self._slots.get(name)
        if slot is None:
            slot = _Slot(name, index, elastic.RestartBreaker(
                self._breaker_max, window_s=self._breaker_window_s,
                clock=self._clock,
            ), kind=kind, role=role)
            self._slots[name] = slot
        if slot.state in ("new", "stopped"):
            self._spawn(slot)
        return slot

    def _slot_cmd(self, slot: _Slot) -> "list[str]":
        if slot.kind == "router":
            from mpi4dl_tpu.fleet.frontdoor import router_cmd

            return router_cmd(self._router_args) + [
                "--name", slot.name, "--journal-dir", self._journal_dir,
            ]
        return self._worker_cmd

    def _spawn(self, slot: _Slot) -> None:
        hb = os.path.join(self.base_dir, f"{slot.name}.heartbeat")
        slot.proc = ReplicaProcess(
            slot.name, self._slot_cmd(slot), self.base_dir,
            env=self._env, heartbeat_path=hb,
            log_path=os.path.join(self.base_dir, f"{slot.name}.log"),
        )
        slot.proc.spawn()
        slot.state = "starting"
        slot.ports = None
        slot.unhealthy_streak = 0

    # -- membership: one replica set, every router ----------------------------

    def _router_admins(self) -> "list":
        from mpi4dl_tpu.fleet.frontdoor import RouterAdminClient

        with self._lock:
            return [
                RouterAdminClient(
                    s.name,
                    f"http://127.0.0.1:{s.ports['predict_port']}",
                )
                for s in self._slots.values()
                if s.kind == "router" and s.state == "running"
                and s.ports is not None
            ]

    def _replica_urls(self, slot: _Slot) -> "tuple[str, str]":
        return (
            f"http://127.0.0.1:{slot.ports['predict_port']}",
            f"http://127.0.0.1:{slot.ports['metrics_port']}",
        )

    def _register_replica(self, slot: _Slot) -> None:
        """Route a ready serving replica: the in-process router, every
        running router process, and the federation aggregator."""
        predict_url, metrics_url = self._replica_urls(slot)
        if self.router is not None:
            self.router.add_replica(
                slot.name, predict_url, health_url=metrics_url
            )
        for admin in self._router_admins():
            try:
                admin.replica_op(
                    "add", name=slot.name, predict_url=predict_url,
                    health_url=metrics_url,
                )
            except Exception:  # noqa: BLE001 — a router mid-restart
                pass  # re-learns the whole set at its ready handshake
        if self.aggregator is not None:
            self.aggregator.add_replica(slot.name, metrics_url)

    def _deregister_replica(self, slot: _Slot, requeue: bool) -> None:
        if self.router is not None:
            self.router.remove_replica(slot.name, requeue=requeue)
        for admin in self._router_admins():
            try:
                admin.replica_op("remove", name=slot.name, requeue=requeue)
            except Exception:  # noqa: BLE001
                pass
        if self.aggregator is not None:
            self.aggregator.remove_replica(slot.name)

    def _register_fleet_with_router(self, router_slot: _Slot) -> None:
        """A (re)started router learns the current serving set — the
        membership half of a successor's recovery (the journal half is
        its own replay)."""
        from mpi4dl_tpu.fleet.frontdoor import RouterAdminClient

        admin = RouterAdminClient(
            router_slot.name,
            f"http://127.0.0.1:{router_slot.ports['predict_port']}",
        )
        with self._lock:
            serving = [
                s for s in self._slots.values()
                if s.kind == "replica" and s.role == "serving"
                and s.state == "running" and s.ports is not None
            ]
        for s in serving:
            predict_url, metrics_url = self._replica_urls(s)
            try:
                admin.replica_op(
                    "add", name=s.name, predict_url=predict_url,
                    health_url=metrics_url,
                )
            except Exception:  # noqa: BLE001 — the next reconcile
                pass  # re-registration catches it

    # -- warm-pool promotion --------------------------------------------------

    def _probe_promotable(self, slot: _Slot) -> bool:
        """The promotion handshake: the standby must ANSWER healthy right
        now — promotion never routes a corpse."""
        import json
        import urllib.request

        if slot.proc is None or not slot.proc.alive():
            return False
        if slot.ports is None:
            return False
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{slot.ports['metrics_port']}/healthz",
                timeout=self._scrape_timeout_s,
            ) as resp:
                return bool(json.loads(resp.read().decode()).get("healthy"))
        except Exception:  # noqa: BLE001 — any non-answer fails the
            return False  # handshake; the caller falls back to cold spawn

    def _promote_standby(self, victim: _Slot) -> bool:
        """Replace a dead serving replica with a warmed standby: health
        handshake, then routing flip. The victim slot inherits the
        standby ROLE (its eventual respawn backfills the pool). Returns
        False — cold-spawn fallback — when no standby passes the
        handshake; a standby that failed it is killed and recycled
        through the normal death path, never routed."""
        with self._lock:
            candidates = [
                s for s in self._slots.values()
                if s.kind == "replica" and s.role == "standby"
                and s.state == "standby"
            ]
        for cand in sorted(candidates, key=lambda s: s.index):
            if not self._probe_promotable(cand):
                # Dead-during-promotion race: recycle it below (its own
                # death will be seen by the next tick) and keep looking.
                continue
            with self._lock:
                if cand.state != "standby":
                    continue  # raced with its own death handling
                cand.role = "serving"
                cand.state = "running"
                victim.role = "standby"
            self._register_replica(cand)
            self.promotions += 1
            self._m_promotions.inc()
            if victim.death_t is not None:
                self.last_recovery_s = self._clock() - victim.death_t
                self._m_recovery.set(self.last_recovery_s)
                # A promotion's whole recovery is routable-again time
                # (handshake + routing flip): compile/warm are honestly
                # zero — the phase-attributed form of the warm pool's
                # 0.05s-vs-7s claim.
                self._publish_recovery_phases(
                    self.last_recovery_s, {"ready": self.last_recovery_s}
                )
                victim.death_t = None
            return True
        return False

    def _publish_recovery_phases(
        self, recovery_s: float, worker_phases: "dict | None"
    ) -> None:
        """Publish fleet_recovery_phase_seconds{phase=} for the recovery
        just measured: the worker's self-reported durations folded into
        the fixed phase vocabulary, spawn = the supervisor-side residual,
        EVERY phase set each time (zeros included) so cold respawns and
        promotions alternating can't leave stale series behind and the
        phases always sum to fleet_recovery_seconds."""
        from mpi4dl_tpu.telemetry.coldstart import (
            recovery_phase_decomposition,
        )

        phases = recovery_phase_decomposition(recovery_s, worker_phases)
        for p, v in phases.items():
            self._m_recovery_phase.set(v, phase=p)
        self.last_recovery_phases = phases

    def _on_ready(self, slot: _Slot, ports: dict) -> None:
        slot.ports = ports
        slot.attempt = 0
        if slot.kind == "router":
            slot.state = "running"
            self._register_fleet_with_router(slot)
            if self.aggregator is not None and ports.get("metrics_port"):
                # The router's /snapshotz merges like any replica's.
                self.aggregator.add_replica(
                    slot.name,
                    f"http://127.0.0.1:{ports['metrics_port']}",
                )
            if slot.death_t is not None:
                self.last_router_recovery_s = self._clock() - slot.death_t
                slot.death_t = None
            return
        if slot.role == "standby":
            # Warm but unrouted: the ready handshake means assert_warm
            # passed, so promotion later is a routing flip, not a spawn.
            slot.state = "standby"
            slot.death_t = None  # a pool backfill is not a recovery
            return
        slot.state = "running"
        self._register_replica(slot)
        if slot.death_t is not None:
            # Death-to-replacement-serving: the fleet's recovery latency
            # (bench-trended via the fleet_2replica extra), decomposed
            # over the worker's self-reported cold-start phases (stub
            # workers report none — the whole recovery lands in spawn).
            self.last_recovery_s = self._clock() - slot.death_t
            self._m_recovery.set(self.last_recovery_s)
            self._publish_recovery_phases(
                self.last_recovery_s, ports.get("phases")
            )
            slot.death_t = None

    def _on_death(self, slot: _Slot, reason: str, kind: str) -> None:
        """A confirmed-dead incarnation: requeue its work, count it,
        promote a standby if one is warm, decide between backoff-respawn
        and tripping the breaker."""
        now = self._clock()
        self.restarts += 1
        slot.last_reason = reason
        if slot.death_t is None:
            slot.death_t = now
        if slot.kind == "replica":
            # The process is gone (exited or just SIGKILLed): requeueing
            # its ledger cannot double-execute.
            self._deregister_replica(slot, requeue=True)
            if slot.role == "serving" and self._warm_pool:
                self._promote_standby(slot)
        elif self.aggregator is not None:
            # Router death: its journal is its ledger — the successor
            # replays it; nothing to requeue here.
            self.aggregator.remove_replica(slot.name)
        self._m_restarts.inc(replica=slot.name, reason=kind)
        slot.breaker.record_failure()
        slot.attempt += 1
        if slot.breaker.allow():
            backoff = elastic.full_jitter_backoff(
                slot.attempt, base_s=self._backoff_base_s,
                max_s=self._backoff_max_s,
            )
            slot.respawn_at = now + backoff
            slot.state = "backoff"
        else:
            backoff = 0.0
            slot.state = "circuit_open"
        elastic.restart_event(
            slot.attempt, backoff, reason,
            events=self._events, flight=self._flight,
            replica=slot.name, circuit_open=slot.state == "circuit_open",
        )
        self._step_alert(slot, now)

    def _step_alert(self, slot: _Slot, now: float) -> None:
        """The circuit-open page rides the stock AlertState machinery:
        alert_active gauge + alert.transition events — one /alertz-shaped
        runbook for burn alerts, memory pages, and fleet pages alike.
        A FIRING transition auto-files the evidence the runbook used to
        collect by hand: the dead incarnation's worker-log tail and the
        latest ``oom.report`` from the fleet's telemetry event log."""
        moved = slot.alert.step(slot.state == "circuit_open", now)
        self._m_alert.set(
            1.0 if slot.alert.state == "firing" else 0.0,
            alert=slot.alert.name, severity=slot.alert.severity,
        )
        if moved is None:
            return
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "alert.transition",
            "attrs": {
                "alert": slot.alert.name,
                "severity": slot.alert.severity,
                "from": moved[0],
                "to": moved[1],
                "replica": slot.name,
                "reason": slot.last_reason,
                "breaker": slot.breaker.state(),
            },
        }
        if moved[1] == "firing":
            ev["attrs"]["evidence"] = self._breaker_evidence(slot)
        if self._flight is not None:
            self._flight.record(ev)
        if self._events is not None and getattr(self._events, "enabled", False):
            self._events.write(ev)

    # -- breaker-page evidence -------------------------------------------------

    def _breaker_evidence(self, slot: _Slot, max_bytes: int = 2048) -> dict:
        """Evidence bundle for a circuit-open page: the worker log tail
        (this slot's incarnations append to one file) and the latest
        ``oom.report`` event in the fleet's JSONL telemetry log (the
        worker env's ``MPI4DL_TPU_TELEMETRY_DIR``), if either exists.
        Best-effort by construction — the page must fire even when the
        evidence is unreadable."""
        out: dict = {}
        log_path = (
            getattr(slot.proc, "log_path", None)
            if slot.proc is not None else None
        )
        if log_path:
            try:
                with open(log_path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - max_bytes))
                    out["log_tail"] = f.read().decode("utf-8", "replace")
                    out["log_path"] = log_path
            except OSError:
                pass
        oom = self._latest_oom_report()
        if oom is not None:
            out["oom_report"] = oom
        return out

    def _latest_oom_report(self, tail_bytes: int = 262144) -> "dict | None":
        """Newest ``oom.report`` event across the fleet telemetry dir's
        JSONL files (newest file first, last matching line wins; only the
        final ``tail_bytes`` of each file are scanned — evidence, not an
        audit)."""
        import glob
        import json as _json

        from mpi4dl_tpu.telemetry import jsonl as _jsonl

        tdir = self._env.get(_jsonl.ENV_DIR)
        if not tdir or not os.path.isdir(tdir):
            return None
        paths = sorted(
            glob.glob(os.path.join(tdir, "*.jsonl")),
            key=lambda p: os.path.getmtime(p), reverse=True,
        )
        for path in paths:
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - tail_bytes))
                    chunk = f.read().decode("utf-8", "replace")
            except OSError:
                continue
            lines = chunk.splitlines()
            if size > tail_bytes and lines:
                lines = lines[1:]  # drop the possibly-truncated first line
            for line in reversed(lines):
                if '"oom.report"' not in line:
                    continue
                try:
                    ev = _json.loads(line)
                except ValueError:
                    continue
                if ev.get("name") == "oom.report":
                    return ev
        return None

    def reset_breaker(self, name: str) -> None:
        """Operator override: close a slot's circuit and let the next
        reconcile tick respawn it."""
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                return
            slot.breaker.reset()
            slot.attempt = 0
            if slot.state == "circuit_open":
                slot.state = "backoff"
                slot.respawn_at = self._clock()
            self._step_alert(slot, self._clock())

    # -- health probing -------------------------------------------------------

    def _probe_health(self, slot: _Slot) -> "dict | None":
        """One supervisor-side ``/healthz`` probe: the payload dict when
        the replica answered (200 OR 503 — a 503 body still carries the
        numerics fence evidence), None when it didn't answer at all
        (black-holed probes count — the timeout IS the signal)."""
        import json
        import urllib.error
        import urllib.request

        if slot.ports is None:
            return {"healthy": True}
        url = (
            f"http://127.0.0.1:{slot.ports['metrics_port']}/healthz"
        )
        try:
            with urllib.request.urlopen(
                url, timeout=self._scrape_timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:  # 503: reachable and saying NO — keep the evidence
                return json.loads(e.read().decode())
            except Exception:  # noqa: BLE001 — unparseable 503 body
                return {"healthy": False}
        except Exception:  # noqa: BLE001 — unreachable/black-holed
            return None

    def _probe_unhealthy(self, slot: _Slot) -> bool:
        """True when the replica answered 503 or didn't answer."""
        payload = self._probe_health(slot)
        return payload is None or not payload.get("healthy")

    # -- reconcile loop -------------------------------------------------------

    def _run(self) -> None:
        while not self._stop_evt.wait(self._interval):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the reconciler must
                pass  # outlive any single bad tick

    def _tick(self) -> None:
        now = self._clock()
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            if slot.state in ("running", "standby"):
                # Standby replicas get the same death/wedge/503 watch —
                # a rotten pool must be replaced BEFORE it is needed.
                self._check_running(slot, now)
            elif slot.state == "starting":
                self._check_starting(slot, now)
            elif slot.state == "backoff" and now >= slot.respawn_at:
                self._spawn(slot)
        self._reconcile_count()
        self._publish_gauges()

    def _check_running(self, slot: _Slot, now: float) -> None:
        if not slot.proc.alive():
            self._on_death(
                slot, f"process exited rc={slot.proc.returncode}", "exit"
            )
            return
        if self._hb_timeout:
            stale = slot.proc.heartbeat_stale_s()
            if stale is not None and stale > self._hb_timeout:
                slot.proc.kill_hard()
                self._on_death(
                    slot,
                    f"heartbeat stale {stale:.1f}s (> {self._hb_timeout}s)",
                    "heartbeat",
                )
                return
        payload = self._probe_health(slot)
        if payload is not None and payload.get("fenced"):
            # Numerics quarantine: the replica's own sentinel proved
            # corruption and latched the fence — a self-report, not a
            # flaky probe, so it skips the unhealthy streak entirely.
            # One tick from fence to replacement spawning.
            self._quarantine(slot, payload)
            return
        if payload is None or not payload.get("healthy"):
            slot.unhealthy_streak += 1
            if slot.unhealthy_streak >= self._unhealthy_after:
                slot.proc.kill_hard()
                self._on_death(
                    slot,
                    f"/healthz unhealthy x{slot.unhealthy_streak}",
                    "unhealthy",
                )
        else:
            slot.unhealthy_streak = 0

    def _quarantine(self, slot: _Slot, payload: dict) -> None:
        """Remove a numerics-fenced replica from service: tell every
        router to stop pulling (the fence already 503s anything in
        flight — the drain is about the routers' books, and is bounded
        by one drain timeout), kill it, and route into the standard
        death path with the distinct ``reason="numerics"`` restart
        label. Repeat offenders trip the same RestartBreaker /
        circuit-open page as any crash loop — a replica that corrupts
        every incarnation must stop being respawned."""
        evidence = payload.get("fence_evidence") or {}
        if self.router is not None:
            self.router.drain_replica(
                slot.name, timeout_s=self._drain_timeout_s
            )
        for admin in self._router_admins():
            try:
                admin.replica_op(
                    "drain", name=slot.name,
                    timeout_s=self._drain_timeout_s,
                )
            except Exception:  # noqa: BLE001 — the kill below still
                pass  # removes the replica from every router's scrape
        slot.proc.kill_hard()
        self._on_death(
            slot,
            "numerics fence: "
            + str(evidence.get("check") or "canary divergence"),
            "numerics",
        )

    def _check_starting(self, slot: _Slot, now: float) -> None:
        del now  # the spawn age is measured on the process handle's own
        # monotonic clock (spawned_age_s) — mixing an injected test clock
        # with a real monotonic stamp would mis-measure the timeout
        ports = slot.proc.poll_ready()
        if ports is not None:
            self._on_ready(slot, ports)
        elif not slot.proc.alive():
            self._on_death(
                slot,
                f"exited during start rc={slot.proc.returncode}", "exit",
            )
        elif slot.proc.spawned_age_s() > self._spawn_timeout_s:
            slot.proc.kill_hard()
            self._on_death(slot, "start timeout", "exit")

    def _reconcile_count(self) -> None:
        desired = self.desired_replicas()
        with self._lock:
            serving = [
                s for s in self._slots.values()
                if s.kind == "replica" and s.role == "serving"
                and s.state in ("starting", "running", "backoff", "draining")
            ]
            standby = [
                s for s in self._slots.values()
                if s.kind == "replica" and s.role == "standby"
                and s.state in ("starting", "standby", "backoff")
            ]
            routers = [
                s for s in self._slots.values()
                if s.kind == "router"
                and s.state in ("starting", "running", "backoff")
            ]
            replica_used = {
                s.index for s in self._slots.values()
                if s.kind == "replica"
                and s.state in ("starting", "running", "standby",
                                "backoff", "draining")
            }
            if len(serving) < desired:
                # Fill the lowest free indexes (stable names).
                i = 0
                while len(serving) < desired:
                    slot = self._slots.get(f"r{i}")
                    if i not in replica_used or (
                        slot is not None
                        and slot.state in ("new", "stopped")
                    ):
                        if slot is not None:
                            slot.role = "serving"
                        slot = self._ensure_slot(i)
                        if slot not in serving:
                            serving.append(slot)
                        replica_used.add(i)
                    i += 1
                    if i > self._max_replicas + len(self._slots):
                        break  # everything else is circuit_open
            elif len(serving) > desired:
                # Scale down: drain the highest-index running replicas.
                excess = len(serving) - desired
                victims = sorted(
                    (s for s in serving if s.state == "running"),
                    key=lambda s: -s.index,
                )[:excess]
                for slot in victims:
                    slot.state = "draining"
                    threading.Thread(
                        target=self._drain_and_stop, args=(slot,),
                        name=f"mpi4dl-fleet-drain-{slot.name}", daemon=True,
                    ).start()
            # Backfill the warm pool (a promotion consumed one, or a
            # standby died and its slot went circuit_open): new standby
            # slots take the lowest free replica indexes.
            i = 0
            while len(standby) < self._warm_pool:
                slot = self._slots.get(f"r{i}")
                if i not in replica_used or (
                    slot is not None and slot.state in ("new", "stopped")
                ):
                    if slot is not None:
                        slot.role = "standby"
                    slot = self._ensure_slot(i, role="standby")
                    standby.append(slot)
                    replica_used.add(i)
                i += 1
                if i > self._max_replicas + self._warm_pool \
                        + len(self._slots):
                    break
            # Router slots: static count, same respawn machinery.
            if len(routers) < self._routers:
                router_used = {
                    s.index for s in self._slots.values()
                    if s.kind == "router"
                    and s.state in ("starting", "running", "backoff")
                }
                for i in range(self._routers):
                    if len(routers) >= self._routers:
                        break
                    if i not in router_used:
                        slot = self._slots.get(f"rt{i}")
                        if slot is None or slot.state in ("new", "stopped"):
                            routers.append(
                                self._ensure_slot(i, kind="router")
                            )

    def _drain_and_stop(self, slot: _Slot) -> None:
        """Scale-down drain: stop admissions (every router), flush the
        in-flight ledgers, SIGTERM (the worker drains its engine queue
        and exits 0), then deregister."""
        if self.router is not None:
            self.router.drain_replica(
                slot.name, timeout_s=self._drain_timeout_s
            )
        for admin in self._router_admins():
            try:
                admin.replica_op(
                    "drain", name=slot.name,
                    timeout_s=self._drain_timeout_s,
                )
            except Exception:  # noqa: BLE001
                pass
        if slot.proc is not None:
            slot.proc.terminate(wait_s=self._drain_timeout_s)
        # Ledgers flushed (or timed out) and the process is gone;
        # anything left requeues rather than hangs.
        self._deregister_replica(slot, requeue=True)
        slot.ports = None
        slot.state = "stopped"

    def _publish_gauges(self) -> None:
        with self._lock:
            by_state: "dict[str, int]" = {}
            router_by_state: "dict[str, int]" = {}
            standby = 0
            for s in self._slots.values():
                if s.kind == "router":
                    router_by_state[s.state] = (
                        router_by_state.get(s.state, 0) + 1
                    )
                    continue
                if s.state == "standby":
                    standby += 1
                by_state[s.state] = by_state.get(s.state, 0) + 1
        self._m_replicas.set(self.desired_replicas(), state="desired")
        for state in ("running", "starting", "backoff", "draining",
                      "circuit_open"):
            self._m_replicas.set(by_state.get(state, 0), state=state)
        self._m_standby.set(standby)
        self._m_routers.set(self._routers, state="desired")
        for state in ("running", "starting", "backoff", "circuit_open"):
            self._m_routers.set(router_by_state.get(state, 0), state=state)
