"""Fault-tolerant replica fleet: router, supervised replicas, chaos drills.

The "one engine is a component, the fleet is the product" layer
(ROADMAP). PRs 4-7 built the sensors — ``/healthz`` + watchdog,
health-gated heartbeats, ``/snapshotz`` federation, the fleet-wide
``autoscale_desired_replicas`` gauge, cross-process trace ids — and this
package actuates on them:

- :class:`Router` (:mod:`.router`) — front-end admission + health-aware
  dispatch over N replica processes, with a per-replica in-flight ledger
  and requeue-on-death (exactly-once completion by construction);
- :class:`FleetSupervisor` (:mod:`.supervisor`) — reconciles the fleet
  toward the desired-replica gauge: spawn with backoff + jitter, drain
  on scale-down, replace on heartbeat loss / ``/healthz`` 503, per-slot
  circuit breaker paged through the stock alert machinery;
- :mod:`.worker` — the replica process (``python -m
  mpi4dl_tpu.fleet.worker``): one ServingEngine + predict RPC endpoint
  + the chaos hooks;
- :mod:`.frontdoor` — the HA front door: the router AS a supervised
  process (``python -m mpi4dl_tpu.fleet.frontdoor``, no JAX — respawn
  is handshake-bound) plus :class:`RouterSetClient`, the failover
  client over an N-router set (``router_failovers`` on
  connection-refused, the typed :class:`FleetUnreachableError` only
  when every router is down);
- :mod:`.journal` — the router's fsync'd recovery journal: a successor
  replays a dead router's accepted-but-uncompleted requests, dedupes
  against replica-reported completions, and re-dispatches the rest
  with fresh epochs (exactly-once across the ROUTER failure domain);
- :mod:`.chaos` — the fault-injection harness (``--chaos kill:1``,
  ``--chaos kill:router``...): the drills the tier-1 tests run, on tap
  against a live fleet;
- ``python -m mpi4dl_tpu.fleet`` — spawn a fleet, load it, optionally
  break it, print one JSON report.

See ``docs/FLEET.md`` for topology, requeue/exactly-once semantics,
breaker parameters, and the chaos runbook.
"""

from mpi4dl_tpu.fleet.chaos import (  # noqa: F401
    ChaosMonkey,
    ChaosOp,
    parse_chaos_spec,
    parse_chaos_specs,
)
from mpi4dl_tpu.fleet.frontdoor import (  # noqa: F401
    RouterAdminClient,
    RouterServer,
    RouterSetClient,
    router_cmd,
)
from mpi4dl_tpu.fleet.journal import (  # noqa: F401
    RouterJournal,
)
from mpi4dl_tpu.fleet.replica import (  # noqa: F401
    FleetUnreachableError,
    ReplicaClient,
    ReplicaDeadline,
    ReplicaError,
    ReplicaProcess,
    ReplicaQueueFull,
    ReplicaRemoteError,
    ReplicaUnreachable,
    worker_cmd,
)
from mpi4dl_tpu.fleet.router import (  # noqa: F401
    ROUTER_METRICS,
    FleetRequestError,
    Router,
)
from mpi4dl_tpu.fleet.supervisor import (  # noqa: F401
    SUPERVISOR_METRICS,
    FleetSupervisor,
)


def declare_metrics(registry) -> None:
    """Declare every ``fleet_*`` metric on ``registry`` (the router and
    supervisor each declare their own subset at construction; this is
    the one-call version for catalog pins and dashboards that want the
    names present before a fleet exists)."""
    from mpi4dl_tpu import telemetry

    for name in sorted({*ROUTER_METRICS, *SUPERVISOR_METRICS}):
        telemetry.declare(registry, name)
