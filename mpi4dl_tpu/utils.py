"""Small helpers (parity with reference ``src/torchgems/utils.py``)."""

import os
import re


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` / ``--xla_force_host_platform_device_count``
    even when a site-initialized TPU plugin has already force-set
    ``jax_platforms`` through ``jax.config`` (which silently overrides the
    environment). Call before first device use in CLI entry points.
    """
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        jax.config.update("jax_num_cpu_devices", int(m.group(1)))


def is_power_two(n: int) -> bool:
    """True iff n is a positive power of two (ref ``utils.py:20-21``)."""
    return n > 0 and (n & (n - 1)) == 0


def get_depth(version: int, n: int) -> int:
    """ResNet depth from block multiplier n (ref ``utils.py:26-30``).

    v1: depth = 6n + 2, v2 (bottleneck): depth = 9n + 2.
    """
    if version == 1:
        return n * 6 + 2
    elif version == 2:
        return n * 9 + 2
    raise ValueError(f"unknown resnet version {version}")
