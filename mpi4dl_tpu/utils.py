"""Small helpers (parity with reference ``src/torchgems/utils.py``)."""


def is_power_two(n: int) -> bool:
    """True iff n is a positive power of two (ref ``utils.py:20-21``)."""
    return n > 0 and (n & (n - 1)) == 0


def get_depth(version: int, n: int) -> int:
    """ResNet depth from block multiplier n (ref ``utils.py:26-30``).

    v1: depth = 6n + 2, v2 (bottleneck): depth = 9n + 2.
    """
    if version == 1:
        return n * 6 + 2
    elif version == 2:
        return n * 9 + 2
    raise ValueError(f"unknown resnet version {version}")
