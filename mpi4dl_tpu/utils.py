"""Small helpers (parity with reference ``src/torchgems/utils.py``)."""

import logging
import os
import re

# Last enable_compilation_cache decision — read back by
# telemetry.coldstart.publish_cache_status so fleet runs are honest about
# cache state instead of silently paying compiles they believe cached.
_CACHE_STATUS = {"enabled": False, "reason": "never attempted"}
_CACHE_GATE_LOGGED = False


def compilation_cache_status() -> dict:
    """``{"enabled": bool, "reason": str, "dir": str|absent}`` of the last
    :func:`enable_compilation_cache` call (reason "never attempted" when
    nothing ever called it)."""
    return dict(_CACHE_STATUS)


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` / ``--xla_force_host_platform_device_count``
    even when a site-initialized TPU plugin has already force-set
    ``jax_platforms`` through ``jax.config`` (which silently overrides the
    environment). Call before first device use in CLI entry points.
    """
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        from mpi4dl_tpu.compat import set_cpu_devices

        set_cpu_devices(int(m.group(1)))


def enable_compilation_cache(default_dir: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (verified to work through
    the tunneled remote-compile helper: 3.0s → 1.1s on a toy program).

    The multi-minute XLA compiles of the 1024-2048px training programs
    dominate benchmark wall time; with a warm cache the whole bench suite
    fits in any driver budget. Directory: ``JAX_COMPILATION_CACHE_DIR`` env,
    else ``default_dir``, else ``<repo>/.cache/jax`` (persists across runs).

    No-op on jax 0.4.x: EXECUTING a persistent-cache-deserialized
    executable on that line's multi-device CPU backend segfaults/aborts
    the process (reproduced via checkpoint-restore + cache-hit train step;
    the same sequence runs clean with the cache off). Paying the compiles
    again is strictly better than dying mid-suite/mid-bench.
    """
    global _CACHE_GATE_LOGGED
    import jax

    if tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5):
        reason = (
            f"jax {jax.__version__} < 0.5: executing a persistent-cache-"
            "deserialized executable segfaults on this line's multi-device "
            "CPU backend — cache stays OFF, every compile is paid"
        )
        _CACHE_STATUS.clear()
        _CACHE_STATUS.update({"enabled": False, "reason": reason})
        if not _CACHE_GATE_LOGGED:
            _CACHE_GATE_LOGGED = True
            logging.getLogger("mpi4dl_tpu").warning(
                "compilation cache disabled: %s", reason
            )
        return

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or default_dir
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".cache",
            "jax",
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    _CACHE_STATUS.clear()
    _CACHE_STATUS.update(
        {"enabled": True, "reason": "persistent cache on", "dir": cache_dir}
    )


def is_power_two(n: int) -> bool:
    """True iff n is a positive power of two (ref ``utils.py:20-21``)."""
    return n > 0 and (n & (n - 1)) == 0


def get_depth(version: int, n: int) -> int:
    """ResNet depth from block multiplier n (ref ``utils.py:26-30``).

    v1: depth = 6n + 2, v2 (bottleneck): depth = 9n + 2.
    """
    if version == 1:
        return n * 6 + 2
    elif version == 2:
        return n * 9 + 2
    raise ValueError(f"unknown resnet version {version}")
