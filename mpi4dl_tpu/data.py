"""Datasets for the benchmark entry points.

Reference parity (``benchmark_amoebanet_sp.py:264-306``): ``--app`` selects
1 = real medical images via ImageFolder at ``--datapath``, 2 = CIFAR-10,
3 = synthetic fake data. The reference uses torchvision loaders; here the
synthetic path is pure numpy (the benchmarks' hot path — every reference
benchmark defaults to it), and the torchvision-backed paths are used when
torchvision + data are actually present, else fall back to synthetic with a
warning (the benchmark cluster has no egress).
"""

from __future__ import annotations

import sys

import numpy as np


class SyntheticImages:
    """Deterministic fake-data stream (ref ``torchvision.datasets.FakeData``
    with ``transforms.ToTensor``: uniform [0,1) pixels). NHWC float32.

    Batch synthesis runs in the native multithreaded runtime when built
    (:mod:`mpi4dl_tpu.native`; counter-based RNG, thread-count independent),
    with a one-batch-deep background prefetch thread so host synthesis
    overlaps device compute — the role of the reference's DataLoader
    ``--num-workers``. Falls back to single-threaded numpy.
    """

    def __init__(
        self,
        batch_size,
        image_size,
        num_classes,
        length=60000,
        seed=0,
        prefetch=True,
    ):
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_classes = num_classes
        self.length = length
        self.seed = seed
        self.prefetch = prefetch

    def __len__(self):
        return max(self.length // self.batch_size, 1)

    def _make_batch(self, i):
        from mpi4dl_tpu import native

        x = native.fill_uniform(
            (self.batch_size, self.image_size, self.image_size, 3),
            seed=self.seed * 1_000_003 + i,
        )
        y = native.fill_labels(
            self.batch_size, self.num_classes, seed=self.seed * 7_000_003 + i
        )
        return x, y

    def __iter__(self):
        if not self.prefetch:
            for i in range(len(self)):
                yield self._make_batch(i)
            return

        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=2)
        stop = threading.Event()
        n = len(self)

        def producer():
            try:
                for i in range(n):
                    item = (None, self._make_batch(i))
                    # Bounded put so an abandoned consumer (early break in the
                    # epoch loop) doesn't pin this thread + batches forever.
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate instead of hanging q.get
                q.put((e, None))
                return
            q.put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                err, batch = item
                if err is not None:
                    raise err
                yield batch
        finally:
            stop.set()  # runs on generator close/GC too — unblocks producer


class ClassPatternImages:
    """Learnable deterministic dataset: each class has a fixed smooth
    pattern template, each sample = its class template + Gaussian noise.

    This exists for convergence evidence (the reference's ``--app 2``
    CIFAR-10 path, ``benchmark_amoebanet_sp.py:264-306``, plays this role
    on a cluster with data; the benchmark machine has no egress, so the
    learnable signal is synthesized): a model that learns ANYTHING drives
    loss below ln(num_classes) and accuracy above 1/num_classes within a
    few hundred SGD steps, and a resumed run must continue the same curve.
    Pure numpy, fully determined by ``seed`` — two processes construct
    bit-identical streams, which is what makes kill/resume curves
    comparable across process boundaries.
    """

    def __init__(
        self,
        batch_size,
        image_size,
        num_classes,
        length=60000,
        seed=0,
        noise=0.25,
    ):
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_classes = num_classes
        self.length = length
        self.seed = seed
        self.noise = noise
        # Low-frequency templates: random coarse grids upsampled to the
        # image size, so the signal survives pooling/striding.
        rng = np.random.default_rng(seed ^ 0x5EED)
        coarse = rng.standard_normal((num_classes, 4, 4, 3)).astype(np.float32)
        reps = (image_size + 3) // 4
        up = np.repeat(np.repeat(coarse, reps, axis=1), reps, axis=2)
        self._templates = up[:, :image_size, :image_size, :]

    def __len__(self):
        return max(self.length // self.batch_size, 1)

    def batch(self, i):
        # SeedSequence over the (seed, batch) pair: genuinely independent
        # per-pair streams. The old ``seed * 1_000_003 + i`` mix collided
        # across seeds ((0, 1000003) == (1, 0)) and degenerated to
        # ``default_rng(i)`` at seed 0 (ADVICE r5).
        rng = np.random.default_rng(np.random.SeedSequence((self.seed, i)))
        y = rng.integers(0, self.num_classes, size=(self.batch_size,))
        x = self._templates[y] + self.noise * rng.standard_normal(
            (self.batch_size, self.image_size, self.image_size, 3)
        ).astype(np.float32)
        return x.astype(np.float32), y.astype(np.int32)

    def __iter__(self):
        for i in range(len(self)):
            yield self.batch(i)


def _torchvision_loader(kind, args, batch_size, shard_id=0, num_shards=1):
    import torch
    import torchvision
    from torchvision import transforms

    transform = transforms.Compose(
        [
            transforms.Resize((args.image_size, args.image_size)),
            transforms.ToTensor(),
        ]
    )
    if kind == "imagefolder":
        ds = torchvision.datasets.ImageFolder(args.datapath, transform=transform)
    else:
        ds = torchvision.datasets.CIFAR10(
            root=args.datapath, train=True, transform=transform, download=False
        )
    sampler = None
    if num_shards > 1:
        # Multi-host: each data shard reads a disjoint subset (hosts at the
        # same data coordinate pass the same shard_id and stay identical).
        sampler = torch.utils.data.distributed.DistributedSampler(
            ds,
            num_replicas=num_shards,
            rank=shard_id,
            shuffle=False,
            # Without drop_last the sampler pads by wrapping, handing the
            # same leading samples to several shards — shards must stay
            # disjoint.
            drop_last=True,
        )
    loader = torch.utils.data.DataLoader(
        ds,
        batch_size=batch_size,
        shuffle=False,
        sampler=sampler,
        num_workers=args.num_workers,
        drop_last=True,
    )

    def gen():
        for xb, yb in loader:
            # torch NCHW -> NHWC numpy
            yield (
                np.ascontiguousarray(xb.numpy().transpose(0, 2, 3, 1)),
                yb.numpy().astype(np.int32),
            )

    class _Wrap:
        def __len__(self):
            return len(loader)

        def __iter__(self):
            return gen()

    return _Wrap()


def get_dataset(args, batch_size, num_classes, shard_id=0, num_shards=1):
    """Dataset iterable of (x NHWC f32, y i32) host batches.

    ``shard_id``/``num_shards`` shard the stream for multi-process runs
    along the batch axis (``run_training`` passes ``multihost.data_shard``,
    which keeps model-parallel co-hosts — same data coordinates — on the
    SAME shard)."""
    if args.app in (1, 2):
        kind = "imagefolder" if args.app == 1 else "cifar"
        try:
            return _torchvision_loader(
                kind, args, batch_size, shard_id=shard_id, num_shards=num_shards
            )
        except Exception as e:  # no torchvision / no data on this machine
            print(
                f"app={args.app} dataset unavailable ({e}); using synthetic",
                file=sys.stderr,
            )
    return SyntheticImages(batch_size, args.image_size, num_classes, seed=shard_id)
