"""``python -m mpi4dl_tpu.analyze`` — entry shim for the static HLO linter.

The implementation lives in :mod:`mpi4dl_tpu.analysis.cli`; this module
exists so the documented invocation stays a flat ``-m`` target.
"""

import sys

from mpi4dl_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
