"""Parallelism configuration + device mesh factory.

This is the TPU-native replacement for the reference's ``MPIComm``
(``src/torchgems/comm.py:44-309``) and ``verify_spatial_config``
(``src/torchgems/train_spatial.py:33-58``). Instead of MPI process groups we
build one ``jax.sharding.Mesh`` with axes ``("data", "pipe", "tile_h",
"tile_w")``:

- ``data``   — data-parallel replicas (ref ``create_allreduce_comm_basic``);
- ``pipe``   — pipeline/layer-parallel stages (ref linear send/recv topology,
  ``mp_pipeline.py:238-248``);
- ``tile_h`` / ``tile_w`` — spatial image tiling (ref ``num_spatial_parts``;
  square → 2-D grid, vertical → tile_w only, horizontal → tile_h only, per
  ``split_input`` ``train_spatial.py:241-290``).

Device-count mapping note: the reference uses ``mp_size = num_spatial_parts +
(split_size - 1)`` ranks (spatial stage is "wide", later LP stages use one GPU
each, ``comm.py:59-67``). A TPU mesh is rectangular, so we use ``pipe ×
tile_h × tile_w`` devices per replica; non-spatial stages run replicated over
the tile axes, or batch-sharded over them when ``local_dp > 1`` (the
reference's LOCAL_DP_LP, ``train_spatial.py:809-1028``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from mpi4dl_tpu.utils import is_power_two

SLICE_SQUARE = "square"
SLICE_VERTICAL = "vertical"
SLICE_HORIZONTAL = "horizontal"
SLICE_METHODS = (SLICE_SQUARE, SLICE_VERTICAL, SLICE_HORIZONTAL)

# Canonical mesh axis names, used across the package.
AXIS_DATA = "data"
AXIS_PIPE = "pipe"
AXIS_TILE_H = "tile_h"
AXIS_TILE_W = "tile_w"


def tile_grid(num_spatial_parts: int, slice_method: str) -> tuple[int, int]:
    """(tile_h, tile_w) grid extents for one SP stage.

    Mirrors the reference's neighbor model (``spatial.py:941-1017``): square
    slices form a √p × √p grid, vertical slices split width only, horizontal
    slices split height only.
    """
    if slice_method == SLICE_SQUARE:
        side = int(math.isqrt(num_spatial_parts))
        if side * side != num_spatial_parts:
            raise ValueError(
                f"square slicing needs a perfect-square part count, got {num_spatial_parts}"
            )
        return side, side
    if slice_method == SLICE_VERTICAL:
        return 1, num_spatial_parts
    if slice_method == SLICE_HORIZONTAL:
        return num_spatial_parts, 1
    raise ValueError(f"slice_method must be one of {SLICE_METHODS}, got {slice_method!r}")


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Full parallelism plan for one training run.

    Field names follow the reference CLI (``parser.py:21-143``) so benchmark
    scripts translate flag-for-flag.
    """

    batch_size: int = 32
    parts: int = 1  # micro-batches per pipeline step (GPipe fill-drain)
    split_size: int = 2  # pipeline stages
    num_spatial_parts: Sequence[int] = (4,)
    spatial_size: int = 0  # how many leading stages are spatially partitioned
    slice_method: str = SLICE_SQUARE
    times: int = 1  # GEMS replication factor
    image_size: int = 32
    num_classes: int = 10
    balance: Sequence[int] | None = None
    local_dp: int = 1
    halo_d2: bool = False
    fused_layers: int = 1
    data_parallel: int = 1
    precision: str = "bf16"

    def __post_init__(self):
        if isinstance(self.num_spatial_parts, int):
            object.__setattr__(self, "num_spatial_parts", (self.num_spatial_parts,))
        else:
            object.__setattr__(self, "num_spatial_parts", tuple(self.num_spatial_parts))
        if self.balance is not None:
            object.__setattr__(self, "balance", tuple(self.balance))
        self.validate()

    # -- validation (parity with verify_spatial_config, train_spatial.py:33-58)
    def validate(self) -> None:
        if self.parts < 1 or self.split_size < 1:
            raise ValueError("parts and split_size must be >= 1")
        if self.batch_size % self.parts != 0:
            raise ValueError("batch_size must divide evenly into `parts` micro-batches")
        if self.spatial_size:
            if self.slice_method not in SLICE_METHODS:
                raise ValueError(f"slice_method must be one of {SLICE_METHODS}")
            if not is_power_two(self.image_size):
                raise ValueError("image size must be a power of two for SP")
            if self.spatial_size > self.split_size:
                raise ValueError("spatial_size cannot exceed split_size")
            if len(self.num_spatial_parts) not in (1, self.spatial_size):
                raise ValueError(
                    "num_spatial_parts must have one entry or spatial_size entries"
                )
            # Skewed multi-stage SP (ref ``--num-spatial-parts 4,2``: later
            # spatial stages on fewer ranks, with skewed tile-redistribution
            # between stages — machinery at train_spatial.py:453-641, though
            # the reference's own config check rejects non-uniform lists
            # outright, train_spatial.py:55-58). On a TPU mesh, tiling is
            # decoupled from device count: running every SP stage on the
            # finest grid produces identical numerics (halo-exchanged fine
            # tiles compute the same global convolution as coarser tiles)
            # with no idle devices and no redistribution collective. So we
            # accept decreasing lists — a superset of the reference — and
            # execute on the max-parts grid; increasing lists stay rejected.
            prev = None
            for p in self.num_spatial_parts:
                if prev is not None and p > prev:
                    # Non-increasing powers of two always divide each other,
                    # so the reference's coarsening re-tile is well defined.
                    raise ValueError(
                        "spatial part counts must be non-increasing "
                        f"(got {self.num_spatial_parts})"
                    )
                prev = p
            for p in self.num_spatial_parts:
                if not is_power_two(p):
                    raise ValueError("each spatial part count must be a power of two")
            # Geometry checks apply to the executed (max-parts) grid; smaller
            # later-stage entries only describe the reference's rank mapping.
            th, tw = tile_grid(self.spatial_parts, self.slice_method)
            if self.image_size % th or self.image_size % tw:
                raise ValueError("image size must divide evenly into tiles")
            if not (
                is_power_two(self.image_size // th)
                and is_power_two(self.image_size // tw)
            ):
                raise ValueError("per-partition image size must be a power of two")
        if self.balance is not None:
            if len(self.balance) != self.split_size:
                raise ValueError("balance list length must equal split_size")
        if self.local_dp < 1:
            raise ValueError("local_dp must be >= 1")
        if self.local_dp > 1:
            # LBANN-style local DP (ref LOCAL_DP_LP, train_spatial.py:809-1028):
            # the post-join LP stages batch-shard over the spatial devices.
            if not self.spatial_size:
                raise ValueError("local_dp > 1 requires a spatial front")
            if self.spatial_size >= self.split_size:
                # Without at least one LP stage after the front there is
                # nothing to batch-shard — such configs previously routed to
                # the non-pipeline Trainer, which silently ignored the flag
                # (round-1 VERDICT weak #6). Fail loudly instead.
                raise ValueError(
                    "local_dp > 1 requires at least one LP stage after the "
                    "spatial front (spatial_size < split_size)"
                )
            th, tw = tile_grid(self.spatial_parts, self.slice_method)
            if self.local_dp != th * tw:
                raise ValueError(
                    f"local_dp must equal the spatial device count {th * tw} "
                    "(the LP stages batch-shard over the tile axes)"
                )

    # -- derived geometry ---------------------------------------------------
    @property
    def spatial_parts(self) -> int:
        """Tile-device count: max over SP stages (skewed lists execute every
        stage on this finest grid — see validate())."""
        return max(self.num_spatial_parts) if self.spatial_size else 1

    @property
    def tile_shape(self) -> tuple[int, int]:
        if not self.spatial_size:
            return (1, 1)
        return tile_grid(self.spatial_parts, self.slice_method)

    @property
    def lp_stages(self) -> int:
        """Pipeline stages AFTER the spatial front (the ``pipe`` mesh axis
        extent). The spatial stages don't occupy pipe coordinates: the spatial
        front runs on ALL devices (tile axes for H/W, pipe axis reused as
        extra micro-batch parallelism) before the LP pipeline drains — see
        ``parallel/pipeline.py``. The reference instead gives the spatial
        stage its own ranks (``mp_size = num_spatial_parts + split_size - 1``,
        ``comm.py:59-67``), which idle during LP compute."""
        return max(self.split_size - self.spatial_size, 1)

    @property
    def mesh_shape(self) -> tuple[int, int, int, int]:
        th, tw = self.tile_shape
        return (self.data_parallel, self.lp_stages, th, tw)

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh_shape))

    def make_mesh(self, devices=None) -> Mesh:
        """Build the 4-axis device mesh (replaces MPIComm group construction)."""
        if devices is None:
            devices = jax.devices()
        n = self.num_devices
        if len(devices) < n:
            raise ValueError(
                f"config needs {n} devices (mesh {self.mesh_shape}), "
                f"have {len(devices)}"
            )
        dev = np.asarray(devices[:n]).reshape(self.mesh_shape)
        return Mesh(dev, (AXIS_DATA, AXIS_PIPE, AXIS_TILE_H, AXIS_TILE_W))

    def micro_batch_size(self) -> int:
        return self.batch_size // self.parts
