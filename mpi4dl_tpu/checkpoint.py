"""Checkpoint / resume.

The reference has NO persistence at all — no ``torch.save``/``load`` anywhere
(SURVEY.md §5.4); a crashed run restarts from scratch, and initial weight
consistency is re-established by broadcast every launch
(``src/torchgems/comm.py:368-400``). A framework for multi-day
high-resolution training needs real checkpointing, so this subsystem is a
deliberate capability *addition* over the reference.

Format: one directory per step (``step_0000100/``) holding

- ``state.msgpack`` — the full ``TrainState`` pytree (params, optimizer
  state, step) via ``flax.serialization`` (framework-independent msgpack,
  no pickling of code);
- ``meta.json`` — step number + user metadata.

Arrays are pulled to host before writing (``jax.device_get``), so saving
works identically for sharded (multi-chip) and single-device states; on
restore the caller re-shards by construction (``Trainer``/``PipelineTrainer``
place params via their own ``NamedSharding``s on the next ``train_step``).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any

import jax
from flax import serialization

_STEP_DIR = re.compile(r"^step_(\d+)$")


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    step: int | None = None,
    keep: int = 3,
    metadata: dict | None = None,
) -> str:
    """Write ``state`` under ``ckpt_dir/step_{step}``; prune to ``keep``
    newest. Returns the checkpoint path. ``step`` defaults to
    ``int(state.step)``."""
    if step is None:
        step = int(jax.device_get(state.step))
    path = os.path.join(ckpt_dir, f"step_{step:08d}")
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    host_state = jax.device_get(state)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_state))
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"step": step, **(metadata or {})}, f)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)  # atomic publish — no torn checkpoints on crash
    _prune(ckpt_dir, keep)
    return path


def _prune(ckpt_dir: str, keep: int) -> None:
    steps = all_checkpoints(ckpt_dir)
    for step, path in steps[: max(len(steps) - keep, 0)]:
        shutil.rmtree(path, ignore_errors=True)


def all_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """Sorted ``(step, path)`` list of complete checkpoints."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_DIR.match(name)
        if m and os.path.exists(os.path.join(ckpt_dir, name, "state.msgpack")):
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return sorted(out)


def latest_checkpoint(ckpt_dir: str) -> str | None:
    steps = all_checkpoints(ckpt_dir)
    return steps[-1][1] if steps else None


def restore_checkpoint(path_or_dir: str, target: Any) -> Any:
    """Restore a state pytree. ``target`` supplies the structure (a freshly
    ``init()``-ed ``TrainState``); pass a checkpoint path or a directory (→
    newest). Raises ``FileNotFoundError`` when nothing is there."""
    path = path_or_dir
    if not os.path.exists(os.path.join(path, "state.msgpack")):
        newest = latest_checkpoint(path_or_dir)
        if newest is None:
            raise FileNotFoundError(f"no checkpoint under {path_or_dir!r}")
        path = newest
    with open(os.path.join(path, "state.msgpack"), "rb") as f:
        return serialization.from_bytes(target, f.read())


def checkpoint_metadata(path: str) -> dict:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)
