"""Checkpoint / resume.

The reference has NO persistence at all — no ``torch.save``/``load`` anywhere
(SURVEY.md §5.4); a crashed run restarts from scratch, and initial weight
consistency is re-established by broadcast every launch
(``src/torchgems/comm.py:368-400``). A framework for multi-day
high-resolution training needs real checkpointing, so this subsystem is a
deliberate capability *addition* over the reference.

Format: one directory per step (``step_0000100/``) holding

- ``state.msgpack`` — the full ``TrainState`` pytree (params, optimizer
  state, step) via ``flax.serialization`` (framework-independent msgpack,
  no pickling of code);
- ``meta.json`` — step number + user metadata (see :func:`model_metadata`
  for the canonical model-config block);
- ``batch_stats.msgpack`` (optional) — calibrated BN statistics
  (:func:`mpi4dl_tpu.evaluate.collect_batch_stats` output), so an
  inference/serving process can restore a ready-to-predict model without
  re-running calibration.

A checkpoint whose ``meta.json`` carries a :func:`model_metadata` block is
*self-describing*: :func:`rebuild_from_checkpoint` reconstructs the cell
list from the metadata alone, so eval and the serving engine
(:mod:`mpi4dl_tpu.serve`) start from a checkpoint path with no side-channel
model config.

Arrays are pulled to host before writing (``jax.device_get``), so saving
works identically for sharded (multi-chip) and single-device states; on
restore the caller re-shards by construction (``Trainer``/``PipelineTrainer``
place params via their own ``NamedSharding``s on the next ``train_step``).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any

import jax
import jax.numpy as jnp
from flax import serialization

_STEP_DIR = re.compile(r"^step_(\d+)$")


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    step: int | None = None,
    keep: int = 3,
    metadata: dict | None = None,
    batch_stats: Any | None = None,
) -> str:
    """Write ``state`` under ``ckpt_dir/step_{step}``; prune to ``keep``
    newest. Returns the checkpoint path. ``step`` defaults to
    ``int(state.step)``. ``batch_stats`` (calibrated BN statistics) ride
    along in ``batch_stats.msgpack`` when given."""
    if step is None:
        step = int(jax.device_get(state.step))
    path = os.path.join(ckpt_dir, f"step_{step:08d}")
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    host_state = jax.device_get(state)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_state))
    if batch_stats is not None:
        with open(os.path.join(tmp, "batch_stats.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(jax.device_get(batch_stats)))
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"step": step, **(metadata or {})}, f)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)  # atomic publish — no torn checkpoints on crash
    _prune(ckpt_dir, keep)
    return path


def _prune(ckpt_dir: str, keep: int) -> None:
    steps = all_checkpoints(ckpt_dir)
    for step, path in steps[: max(len(steps) - keep, 0)]:
        shutil.rmtree(path, ignore_errors=True)


def all_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """Sorted ``(step, path)`` list of complete checkpoints."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_DIR.match(name)
        if m and os.path.exists(os.path.join(ckpt_dir, name, "state.msgpack")):
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return sorted(out)


def latest_checkpoint(ckpt_dir: str) -> str | None:
    steps = all_checkpoints(ckpt_dir)
    return steps[-1][1] if steps else None


def restore_checkpoint(path_or_dir: str, target: Any) -> Any:
    """Restore a state pytree. ``target`` supplies the structure (a freshly
    ``init()``-ed ``TrainState``); pass a checkpoint path or a directory (→
    newest). Raises ``FileNotFoundError`` when nothing is there."""
    path = resolve_checkpoint(path_or_dir)
    with open(os.path.join(path, "state.msgpack"), "rb") as f:
        return serialization.from_bytes(target, f.read())


def checkpoint_metadata(path: str) -> dict:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def resolve_checkpoint(path_or_dir: str) -> str:
    """Exact checkpoint path for a checkpoint dir (→ newest) or a direct
    ``step_*`` path (→ itself). Raises ``FileNotFoundError`` when empty."""
    if os.path.exists(os.path.join(path_or_dir, "state.msgpack")):
        return path_or_dir
    newest = latest_checkpoint(path_or_dir)
    if newest is None:
        raise FileNotFoundError(f"no checkpoint under {path_or_dir!r}")
    return newest


# -- self-describing checkpoints: model metadata + rebuild --------------------

# family -> builder resolver, lazily imported so checkpoint stays cheap to
# import (the model zoo pulls in flax modules).
_MODEL_FAMILIES = ("resnet_v1", "resnet_v2", "amoebanet")


def model_metadata(family: str, image_size: int, **spec) -> dict:
    """Canonical ``{"model": {...}}`` metadata block for
    :func:`save_checkpoint`: everything :func:`rebuild_cells` needs to
    reconstruct the cell list, plus the input geometry
    (``image_size``/``channels``) a restore-time ``init`` needs to build
    the target pytree. ``spec`` holds the family builder's kwargs (depth /
    num_layers / num_filters / num_classes / pool_kernel / layout ...);
    a ``dtype`` entry may be a dtype object — it is stored by name.

    A ``spatial_cells`` entry records the SPATIAL twin's builder arg (how
    many leading cells run halo-exchanged when the model is sharded over a
    tile mesh): :func:`rebuild_cells` ignores it — the plain rebuild stays
    single-chip-clean — while :func:`rebuild_spatial_twin` uses it, which
    is what lets ``python -m mpi4dl_tpu.serve --ckpt ... --mesh HxW``
    shard a checkpoint with no side-channel model config."""
    if family not in _MODEL_FAMILIES:
        raise ValueError(
            f"unknown model family {family!r}; expected one of {_MODEL_FAMILIES}"
        )
    if "dtype" in spec:
        spec["dtype"] = jnp.dtype(spec["dtype"]).name
    return {"model": {"family": family, "image_size": int(image_size), **spec}}


def rebuild_cells(meta: dict, spatial_cells: int | None = None) -> list:
    """Reconstruct the cell list from a :func:`model_metadata` block (the
    ``meta.json`` of a self-describing checkpoint). The default rebuilds
    the PLAIN twin (any stored ``spatial_cells`` is ignored — restored
    single-chip serving must stay collective-free); pass ``spatial_cells``
    to build the halo-exchanged spatial variant instead."""
    try:
        spec = dict(meta["model"])
    except KeyError:
        raise ValueError(
            "checkpoint metadata has no 'model' block — it was saved without "
            "model_metadata(...) and cannot be rebuilt from the path alone"
        ) from None
    family = spec.pop("family")
    spec.pop("image_size", None)
    spec.pop("channels", None)
    spec.pop("spatial_cells", None)
    if spatial_cells:
        spec["spatial_cells"] = int(spatial_cells)
    if "dtype" in spec:
        spec["dtype"] = jnp.dtype(spec["dtype"])
    if family == "resnet_v1":
        from mpi4dl_tpu.models.resnet import get_resnet_v1

        return get_resnet_v1(**spec)
    if family == "resnet_v2":
        from mpi4dl_tpu.models.resnet import get_resnet_v2

        return get_resnet_v2(**spec)
    if family == "amoebanet":
        from mpi4dl_tpu.models.amoebanet import amoebanetd

        return amoebanetd(**spec)
    raise ValueError(
        f"unknown model family {family!r}; expected one of {_MODEL_FAMILIES}"
    )


def rebuild_spatial_twin(
    meta: dict, spatial_cells: int | None = None
) -> tuple:
    """``(spatial_cells_list, plain_cells_list, n_spatial)`` from a
    :func:`model_metadata` block — the triple the sharded serving path
    (:func:`mpi4dl_tpu.serve.sharded.sharded_engine`) consumes. The
    spatial-cell count comes from the explicit argument, else the
    checkpoint's stored ``spatial_cells`` builder arg; a checkpoint saved
    without one refuses loudly (guessing a halo boundary the trainer never
    validated would silently change which cells exchange halos)."""
    stored = (meta.get("model") or {}).get("spatial_cells")
    n_sp = int(spatial_cells) if spatial_cells is not None else stored
    if not n_sp:
        raise ValueError(
            "checkpoint metadata records no spatial_cells builder arg and "
            "none was given — re-save with model_metadata(..., "
            "spatial_cells=N) or pass --spatial-cells to shard this "
            "checkpoint over a mesh"
        )
    plain = rebuild_cells(meta)
    n_sp = min(int(n_sp), len(plain) - 1)
    return rebuild_cells(meta, spatial_cells=n_sp), plain, n_sp


def restore_batch_stats(path_or_dir: str):
    """Calibrated BN ``batch_stats`` from a checkpoint, or ``None`` when the
    checkpoint was saved without them. Returned as the same list-of-dicts
    :func:`mpi4dl_tpu.evaluate.collect_batch_stats` produces (flax msgpack
    stores lists as index-keyed dicts; this undoes that)."""
    path = resolve_checkpoint(path_or_dir)
    fname = os.path.join(path, "batch_stats.msgpack")
    if not os.path.exists(fname):
        return None
    with open(fname, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    return [raw[str(i)] for i in range(len(raw))]


def rebuild_from_checkpoint(path_or_dir: str):
    """``(cells, state, batch_stats, meta)`` from a checkpoint path alone.

    The cell list comes from the metadata model block; the restore target
    (params + optimizer-state structure) is built by initializing those
    cells at the recorded input geometry — callers need no side-channel
    model config. ``batch_stats`` is ``None`` for train-only checkpoints."""
    path = resolve_checkpoint(path_or_dir)
    meta = checkpoint_metadata(path)
    cells = rebuild_cells(meta)
    spec = meta["model"]
    shape = (
        1, spec["image_size"], spec["image_size"], spec.get("channels", 3)
    )

    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.train import TrainState, make_optimizer

    x = jnp.zeros(shape, jnp.dtype(spec.get("dtype", "float32")))
    params = init_cells(cells, jax.random.PRNGKey(0), x)
    target = TrainState(
        params=params,
        opt_state=make_optimizer().init(params),
        step=jnp.zeros((), jnp.int32),
    )
    state = restore_checkpoint(path, target)
    return cells, state, restore_batch_stats(path), meta
