"""Closed/open-loop load generation against a :class:`ServingEngine`.

Two standard load models (the serving-benchmark split popularized by
ycsb/mlperf-inference):

- **closed loop** — ``concurrency`` synthetic clients, each submitting its
  next request the moment the previous one resolves. Measures achievable
  throughput at a fixed concurrency; offered load self-regulates.
- **open loop** — requests arrive on a fixed-rate clock regardless of
  completions (the "millions of users" shape: arrivals don't wait for your
  tail). Overload shows up as queue-full rejections and deadline misses
  instead of silently stretching the measurement.

Both produce one JSON-serializable report with tail percentiles
(p50/p90/p99 — the numbers serving is judged by) and the engine's own
counter snapshot. :func:`serial_throughput` is the batch-size-1 baseline
the dynamic-batching win is measured against.

Client-observed outcomes and latency also land in a telemetry registry
(``loadgen_*`` metrics, docs/OBSERVABILITY.md) — by default the engine's
own :attr:`ServingEngine.registry`, so one Prometheus scrape of
``--metrics-port`` shows the server-side spans AND the client-side view
they must reconcile with. The gap between the two views is now measured
per request, not eyeballed across percentile tables: the engine reports
its own e2e latency on the resolved future, and the client publishes
``client latency − engine e2e`` into ``serve_client_overhead_seconds`` —
the hop cost a fleet router adds, attributable per replica once
federated.

Distributed tracing: the client mints each request's ``trace_id``
(:func:`mpi4dl_tpu.telemetry.new_trace_id`) and hands it to
``engine.submit(trace_id=...)`` — the propagation seam a cross-process
router will use unchanged. With ``events=`` (a
:class:`telemetry.JsonlWriter`, e.g. ``engine.events``), the client also
emits its own ``client.request`` span segment per resolved request, so
``python -m mpi4dl_tpu.analyze trace-export`` renders the full client →
queue → batch → device lifetime under one id.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from mpi4dl_tpu.fleet.replica import FleetUnreachableError
from mpi4dl_tpu.profiling import percentiles
from mpi4dl_tpu.serve.engine import (
    DeadlineExceededError,
    QueueFullError,
    ServingEngine,
)


class ClassMix:
    """Deterministic class-mix traffic: smooth weighted round-robin over
    named SLO classes, so a ``{"tight": 1, "bulk": 3}`` mix emits
    ``bulk, tight, bulk, bulk, ...`` identically on every run (no RNG —
    A/B arms must see the SAME arrival pattern).

    mix: ``{name: weight}`` or ``{name: (weight, deadline_s)}`` — a
    per-class deadline overrides the run's global ``deadline_s`` for
    that class's requests (None defers to the engine's class default).
    """

    def __init__(self, mix: dict):
        self._entries = []
        for name, spec in mix.items():
            if isinstance(spec, (tuple, list)):
                weight, deadline_s = spec
            else:
                weight, deadline_s = spec, None
            weight = float(weight)
            if weight <= 0:
                raise ValueError(f"class {name}: weight must be > 0")
            self._entries.append({
                "name": str(name), "weight": weight,
                "deadline_s": deadline_s, "current": 0.0,
            })
        if not self._entries:
            raise ValueError("empty class mix")
        self._total = sum(e["weight"] for e in self._entries)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "ClassMix":
        """``"tight:1:250ms,bulk:3"`` → ClassMix
        (``NAME:WEIGHT[:DEADLINE]``)."""
        from mpi4dl_tpu.serve.scheduler import parse_duration_s

        mix = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            toks = part.split(":")
            if len(toks) not in (2, 3):
                raise ValueError(
                    f"bad mix entry {part!r}: expected NAME:WEIGHT[:DEADLINE]"
                )
            mix[toks[0]] = (
                float(toks[1]),
                parse_duration_s(toks[2]) if len(toks) == 3 else None,
            )
        return cls(mix)

    def next(self) -> "tuple[str, float | None]":
        """The next request's ``(slo_class, deadline_s_override)``."""
        with self._lock:
            for e in self._entries:
                e["current"] += e["weight"]
            best = max(self._entries, key=lambda e: e["current"])
            best["current"] -= self._total
            return best["name"], best["deadline_s"]


class TenantMix:
    """Deterministic tenant-mix traffic: the same smooth weighted
    round-robin as :class:`ClassMix`, over tenant names — a
    ``{"bulk": 10, "tight": 1}`` mix emits the identical arrival
    pattern on every run, which is what makes the noisy-neighbor
    fairness drills (and their goldens) reproducible."""

    def __init__(self, mix: dict):
        self._entries = []
        for name, weight in mix.items():
            weight = float(weight)
            if weight <= 0:
                raise ValueError(f"tenant {name}: weight must be > 0")
            self._entries.append({
                "name": str(name), "weight": weight, "current": 0.0,
            })
        if not self._entries:
            raise ValueError("empty tenant mix")
        self._total = sum(e["weight"] for e in self._entries)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "TenantMix":
        """``"bulk:10,tight:1"`` → TenantMix (``NAME:WEIGHT``)."""
        mix = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, weight = part.partition(":")
            if not sep:
                raise ValueError(
                    f"bad tenant-mix entry {part!r}: expected NAME:WEIGHT"
                )
            mix[name] = float(weight)
        return cls(mix)

    def next(self) -> str:
        """The next request's tenant name."""
        with self._lock:
            for e in self._entries:
                e["current"] += e["weight"]
            best = max(self._entries, key=lambda e: e["current"])
            best["current"] -= self._total
            return best["name"]


def _default_example(engine: ServingEngine):
    rng = np.random.default_rng(0)

    def make(i: int) -> np.ndarray:
        del i
        return rng.standard_normal(engine.example_shape).astype(
            engine._np_dtype
        )

    return make


def serial_throughput(
    engine: ServingEngine, num_requests: int, make_example=None
) -> dict:
    """Requests served one at a time, batch size 1, synchronously — the
    no-batching baseline (requests/sec == images/sec)."""
    make_example = make_example or _default_example(engine)
    lat = []
    t0 = time.perf_counter()
    for i in range(num_requests):
        s = time.perf_counter()
        engine.predict_one(make_example(i))
        lat.append(time.perf_counter() - s)
    dt = time.perf_counter() - t0
    return {
        "mode": "serial_bs1",
        "requests": num_requests,
        "duration_s": dt,
        "throughput_rps": num_requests / dt,
        "latency_s": {**percentiles(lat), "mean": float(np.mean(lat))},
    }


class _Tally:
    def __init__(self, registry=None, events=None):
        self.lock = threading.Lock()
        self.latencies: list[float] = []
        self.overheads: list[float] = []
        self.served = 0
        self.rejected_queue_full = 0
        self.queue_full_retries = 0
        self.rejected_quota = 0
        self.quota_shed_retries = 0
        self.router_failovers = 0
        self.deadline_misses = 0
        self.errors = 0
        # Per-SLO-class outcome/latency split (class-mix runs): the
        # per-class p99 the EDF-vs-FIFO A/B is judged by.
        self.by_class: "dict[str, dict]" = {}
        # Per-tenant split (tenant-mix runs): the noisy-neighbor
        # fairness drills are judged by the victim tenant's p99 here.
        self.by_tenant: "dict[str, dict]" = {}
        self._events = events
        self._m_requests = self._m_latency = self._m_overhead = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_requests = telemetry.declare(
                registry, "loadgen_requests_total"
            )
            self._m_latency = telemetry.declare(
                registry, "loadgen_request_latency_seconds"
            )
            self._m_overhead = telemetry.declare(
                registry, "serve_client_overhead_seconds"
            )

    def _count(self, outcome: str) -> None:
        if self._m_requests is not None:
            self._m_requests.inc(outcome=outcome)

    def _cls(self, slo_class: "str | None") -> "dict | None":
        if slo_class is None:
            return None
        rec = self.by_class.get(slo_class)
        if rec is None:
            rec = self.by_class[slo_class] = {
                "latencies": [], "served": 0, "deadline_misses": 0,
                "errors": 0, "rejected_queue_full": 0,
            }
        return rec

    def _ten(self, tenant: "str | None") -> "dict | None":
        if tenant is None:
            return None
        rec = self.by_tenant.get(tenant)
        if rec is None:
            rec = self.by_tenant[tenant] = {
                "latencies": [], "served": 0, "deadline_misses": 0,
                "errors": 0, "rejected_queue_full": 0,
                "rejected_quota": 0, "quota_shed_retries": 0,
            }
        return rec

    def reject(self, slo_class: "str | None" = None,
               tenant: "str | None" = None) -> None:
        with self.lock:
            self.rejected_queue_full += 1
            rec = self._cls(slo_class)
            if rec is not None:
                rec["rejected_queue_full"] += 1
            trec = self._ten(tenant)
            if trec is not None:
                trec["rejected_queue_full"] += 1
        self._count("rejected_queue_full")

    def quota_reject(self, tenant: "str | None" = None) -> None:
        """A quota shed that exhausted the retry budget — terminal for
        this request, billed to the over-quota tenant."""
        with self.lock:
            self.rejected_quota += 1
            trec = self._ten(tenant)
            if trec is not None:
                trec["rejected_quota"] += 1
        self._count("rejected_quota")

    def retried(self) -> None:
        """A queue-full bounce the client absorbed with a backoff-retry
        (not a terminal outcome — the request is still in play)."""
        with self.lock:
            self.queue_full_retries += 1

    def quota_retried(self, tenant: "str | None" = None) -> None:
        """A quota shed absorbed with a refill-hint wait — the
        quota-convergence behavior: a client that sleeps exactly
        ``retry_after_s`` converges on the tenant's configured rate."""
        with self.lock:
            self.quota_shed_retries += 1
            trec = self._ten(tenant)
            if trec is not None:
                trec["quota_shed_retries"] += 1

    def router_failover(self, n: int = 1) -> None:
        """A connection-refused/reset on a front-door router the client
        absorbed by retrying elsewhere (or later) — counted SEPARATELY
        from queue pressure: failovers are a router-death signal, not a
        capacity one."""
        with self.lock:
            self.router_failovers += int(n)

    def resolve(
        self,
        future,
        t_submit: float,
        trace_id: "str | None" = None,
        t_submitted: "float | None" = None,
        slo_class: "str | None" = None,
        tenant: "str | None" = None,
    ) -> None:
        from mpi4dl_tpu.tenancy.model import QuotaExceededError

        outcome = "served"
        try:
            future.result()
        except DeadlineExceededError:
            outcome = "deadline_miss"
            with self.lock:
                self.deadline_misses += 1
                rec = self._cls(slo_class)
                if rec is not None:
                    rec["deadline_misses"] += 1
                trec = self._ten(tenant)
                if trec is not None:
                    trec["deadline_misses"] += 1
        except QuotaExceededError:
            # A router-set future resolved with a quota shed (the
            # client-side typed surface of a 429 quota_exceeded).
            self.quota_reject(tenant)
            return
        except Exception:  # noqa: BLE001 — tallied, surfaced in the report
            outcome = "error"
            with self.lock:
                self.errors += 1
                rec = self._cls(slo_class)
                if rec is not None:
                    rec["errors"] += 1
                trec = self._ten(tenant)
                if trec is not None:
                    trec["errors"] += 1
        t_done = time.monotonic()
        self._count(outcome)
        # A router-set future reports how many router failovers it
        # absorbed in flight (RouterSetClient); plain engine futures
        # don't carry the attribute.
        failovers = getattr(future, "failovers", 0)
        if failovers:
            self.router_failover(failovers)
        engine_e2e = getattr(future, "e2e_latency_s", None)
        overhead = None
        if outcome == "served":
            lat = t_done - t_submit
            with self.lock:
                self.served += 1
                self.latencies.append(lat)
                rec = self._cls(slo_class)
                if rec is not None:
                    rec["served"] += 1
                    rec["latencies"].append(lat)
                trec = self._ten(tenant)
                if trec is not None:
                    trec["served"] += 1
                    trec["latencies"].append(lat)
            if self._m_latency is not None:
                self._m_latency.observe(lat)
            if engine_e2e is not None:
                # The client/router-hop cost: what THIS side added on top
                # of the engine's own submit→result latency.
                overhead = max(0.0, lat - engine_e2e)
                with self.lock:
                    self.overheads.append(overhead)
                if self._m_overhead is not None:
                    self._m_overhead.observe(overhead)
        self._client_span(
            trace_id, outcome, t_submit, t_submitted, t_done,
            engine_e2e, overhead,
        )

    def _client_span(
        self, trace_id, outcome, t_submit, t_submitted, t_done,
        engine_e2e, overhead,
    ) -> None:
        """The client-side span segment of a distributed trace — joins
        the engine's segment under the shared trace_id at export."""
        if self._events is None or not self._events.enabled or not trace_id:
            return
        from mpi4dl_tpu import telemetry

        attrs = {"outcome": outcome, "pid": os.getpid(), "role": "client"}
        if engine_e2e is not None:
            attrs["engine_e2e_s"] = engine_e2e
        if overhead is not None:
            attrs["client_overhead_s"] = overhead
        marks = [("issue", t_submit)]
        if t_submitted is not None:
            marks.append(("client_submit", t_submitted))
        marks.append(("client_wait", t_done))
        self._events.write(telemetry.span_event(
            "client.request", trace_id,
            telemetry.spans_from_marks(marks), attrs=attrs,
        ))


def _submit_with_retry(
    engine, x, deadline_s, tid, tally: _Tally,
    queue_full_retries: int, retry_backoff_s: "float | None",
    slo_class: "str | None" = None,
    tenant: "str | None" = None,
):
    """Submit with opt-in bounded retry on queue-full — and on the
    router-set client's typed all-routers-down signal. Each bounce waits
    the engine's ``retry_after_s`` cadence hint (or the explicit
    ``retry_backoff_s``) doubled per attempt — open-loop overload then
    measures shed-AND-retry behavior (what a real client with a retry
    policy experiences) instead of counting instant failures.
    Connection-refused rides the SAME backoff budget but is counted as
    ``router_failovers`` (a death signal), never as queue pressure.
    A quota shed (:class:`~mpi4dl_tpu.tenancy.QuotaExceededError`)
    sleeps the token bucket's OWN refill hint, undoubled — a client that
    honors it converges on exactly the tenant's configured rate (the
    quota-convergence property the tenancy tests pin).
    Returns the future, or None when the bounces exhausted the budget
    (tallied as a terminal rejection)."""
    from mpi4dl_tpu.tenancy.model import QuotaExceededError

    attempts = 0
    kw = {"slo_class": slo_class} if slo_class is not None else {}
    if tenant is not None:
        kw["tenant"] = tenant
    while True:
        try:
            return engine.submit(x, deadline_s=deadline_s, trace_id=tid, **kw)
        except QuotaExceededError as e:
            if attempts >= queue_full_retries:
                tally.quota_reject(tenant)
                return None
            tally.quota_retried(tenant)
            time.sleep(min(e.retry_after_s or 0.01, 1.0))
            attempts += 1
        except (QueueFullError, FleetUnreachableError) as e:
            if attempts >= queue_full_retries:
                tally.reject(slo_class, tenant)
                return None
            base = (
                retry_backoff_s if retry_backoff_s is not None
                else (e.retry_after_s or 0.01)
            )
            if isinstance(e, FleetUnreachableError):
                tally.router_failover()
            else:
                tally.retried()
            time.sleep(min(base * (2.0 ** attempts), 1.0))
            attempts += 1


def run_closed_loop(
    engine: ServingEngine,
    num_requests: int,
    concurrency: int = 8,
    deadline_s: float = 10.0,
    make_example=None,
    registry=None,
    events=None,
    queue_full_retries: int = 0,
    retry_backoff_s: "float | None" = None,
    class_mix: "ClassMix | dict | None" = None,
    tenant_mix: "TenantMix | dict | None" = None,
) -> dict:
    """``concurrency`` clients ping-ponging until ``num_requests`` total
    have been submitted. High concurrency >> max batch keeps the queue
    deep enough that the engine forms full buckets — the regime where
    dynamic batching must beat serial bs-1 throughput. ``registry``
    defaults to the engine's own, so client-side metrics share its scrape
    endpoint; ``events`` (a JsonlWriter, e.g. ``engine.events``) adds a
    ``client.request`` span segment per request to the trace log.
    ``queue_full_retries`` (opt-in) bounds per-request backoff-retries on
    admission bounces, honoring ``QueueFullError.retry_after_s``.
    ``class_mix`` (a :class:`ClassMix` or its dict form) tags each
    request with a deterministically-rotated SLO class (and optional
    per-class deadline); the report then carries ``by_class``."""
    from mpi4dl_tpu import telemetry

    make_example = make_example or _default_example(engine)
    if class_mix is not None and not isinstance(class_mix, ClassMix):
        class_mix = ClassMix(class_mix)
    if tenant_mix is not None and not isinstance(tenant_mix, TenantMix):
        tenant_mix = TenantMix(tenant_mix)
    tally = _Tally(
        registry if registry is not None else engine.registry, events=events,
    )
    ticket = iter(range(num_requests))
    ticket_lock = threading.Lock()

    def client():
        while True:
            with ticket_lock:
                i = next(ticket, None)
            if i is None:
                return
            cls, cls_deadline = (
                class_mix.next() if class_mix is not None else (None, None)
            )
            ten = tenant_mix.next() if tenant_mix is not None else None
            tid = telemetry.new_trace_id("client")
            t = time.monotonic()
            fut = _submit_with_retry(
                engine, make_example(i),
                cls_deadline if cls_deadline is not None else deadline_s,
                tid, tally, queue_full_retries, retry_backoff_s,
                slo_class=cls, tenant=ten,
            )
            if fut is None:
                continue
            tally.resolve(
                fut, t, trace_id=tid, t_submitted=time.monotonic(),
                slo_class=cls, tenant=ten,
            )

    threads = [
        threading.Thread(target=client, name=f"loadgen-closed-{i}")
        for i in range(concurrency)
    ]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0
    return _report("closed", num_requests, dt, tally, engine,
                   concurrency=concurrency, deadline_s=deadline_s)


def run_open_loop(
    engine: ServingEngine,
    rate_rps: float,
    duration_s: float,
    deadline_s: float = 10.0,
    make_example=None,
    registry=None,
    events=None,
    queue_full_retries: int = 0,
    retry_backoff_s: "float | None" = None,
    class_mix: "ClassMix | dict | None" = None,
    tenant_mix: "TenantMix | dict | None" = None,
) -> dict:
    """Fixed-rate arrivals for ``duration_s`` seconds; completions are
    collected by worker threads so a slow tail never throttles arrivals.
    With ``queue_full_retries`` > 0, admission bounces retry with
    backoff INSIDE the per-request worker thread — the arrival clock
    stays open-loop (arrivals never wait on a retry), which is exactly
    the overload regime where shed-and-retry behavior is measured.
    ``class_mix`` tags arrivals with rotated SLO classes (see
    :func:`run_closed_loop`)."""
    from mpi4dl_tpu import telemetry

    make_example = make_example or _default_example(engine)
    if class_mix is not None and not isinstance(class_mix, ClassMix):
        class_mix = ClassMix(class_mix)
    if tenant_mix is not None and not isinstance(tenant_mix, TenantMix):
        tenant_mix = TenantMix(tenant_mix)
    tally = _Tally(
        registry if registry is not None else engine.registry, events=events,
    )
    waiters: list[threading.Thread] = []
    period = 1.0 / rate_rps
    n = 0
    t0 = time.perf_counter()
    start = time.monotonic()

    def submit_and_resolve(x, tid, t, cls, cls_deadline, ten):
        fut = _submit_with_retry(
            engine, x,
            cls_deadline if cls_deadline is not None else deadline_s,
            tid, tally, queue_full_retries, retry_backoff_s, slo_class=cls,
            tenant=ten,
        )
        if fut is not None:
            tally.resolve(
                fut, t, trace_id=tid, t_submitted=time.monotonic(),
                slo_class=cls, tenant=ten,
            )

    from mpi4dl_tpu.tenancy.model import QuotaExceededError

    while time.perf_counter() - t0 < duration_s:
        target = start + n * period
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        cls, cls_deadline = (
            class_mix.next() if class_mix is not None else (None, None)
        )
        ten = tenant_mix.next() if tenant_mix is not None else None
        tid = telemetry.new_trace_id("client")
        t = time.monotonic()
        n += 1
        if queue_full_retries > 0:
            # Retries sleep; they must do so off the arrival clock.
            w = threading.Thread(
                target=submit_and_resolve,
                args=(make_example(n), tid, t, cls, cls_deadline, ten),
                name=f"loadgen-open-retry-{n}",
            )
            w.start()
            waiters.append(w)
            continue
        try:
            fut = engine.submit(
                make_example(n),
                deadline_s=(
                    cls_deadline if cls_deadline is not None else deadline_s
                ),
                trace_id=tid,
                **({"slo_class": cls} if cls is not None else {}),
                **({"tenant": ten} if ten is not None else {}),
            )
        except QuotaExceededError:
            tally.quota_reject(ten)
            continue
        except QueueFullError:
            tally.reject(cls, ten)
            continue
        w = threading.Thread(
            target=tally.resolve, args=(fut, t),
            kwargs={"trace_id": tid, "t_submitted": time.monotonic(),
                    "slo_class": cls, "tenant": ten},
            name=f"loadgen-open-waiter-{n}",
        )
        w.start()
        waiters.append(w)
    for w in waiters:
        w.join()
    dt = time.perf_counter() - t0
    return _report("open", n, dt, tally, engine,
                   rate_rps=rate_rps, deadline_s=deadline_s)


def _report(mode, offered, dt, tally: _Tally, engine, **extra) -> dict:
    lat = tally.latencies
    ov = tally.overheads
    return {
        "mode": mode,
        "offered": offered,
        "served": tally.served,
        "rejected_queue_full": tally.rejected_queue_full,
        "queue_full_retries": tally.queue_full_retries,
        "router_failovers": tally.router_failovers,
        "deadline_misses": tally.deadline_misses,
        "errors": tally.errors,
        "duration_s": dt,
        "throughput_rps": tally.served / dt if dt > 0 else 0.0,
        "latency_s": {
            **percentiles(lat),
            "mean": float(np.mean(lat)) if lat else None,
        },
        # Client latency minus engine e2e, per request — the measured
        # client/router-hop gap (PR 3 could only juxtapose the two p50s).
        "client_overhead_s": (
            {**percentiles(ov), "mean": float(np.mean(ov))} if ov else None
        ),
        # Class-mix runs: the per-class split the EDF A/B is judged by.
        "by_class": {
            name: {
                "served": rec["served"],
                "deadline_misses": rec["deadline_misses"],
                "errors": rec["errors"],
                "rejected_queue_full": rec["rejected_queue_full"],
                "latency_s": percentiles(rec["latencies"]),
            }
            for name, rec in sorted(tally.by_class.items())
        } or None,
        "rejected_quota": tally.rejected_quota,
        "quota_shed_retries": tally.quota_shed_retries,
        # Tenant-mix runs: the per-tenant split noisy-neighbor fairness
        # is judged by (victim p99 vs solo, Jain's index over served).
        "by_tenant": {
            name: {
                "served": rec["served"],
                "deadline_misses": rec["deadline_misses"],
                "errors": rec["errors"],
                "rejected_queue_full": rec["rejected_queue_full"],
                "rejected_quota": rec["rejected_quota"],
                "quota_shed_retries": rec["quota_shed_retries"],
                "latency_s": percentiles(rec["latencies"]),
            }
            for name, rec in sorted(tally.by_tenant.items())
        } or None,
        "engine": engine.stats(),
        **extra,
    }
