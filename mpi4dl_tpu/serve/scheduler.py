"""Continuous batching + SLO-class EDF scheduling for the serving engine.

The PR-2 batch former was a fixed window: pop the first waiting request,
collect up to ``max_batch`` or ``max_wait_s``, dispatch. Every request —
tight deadline or bulk backfill — waited in ONE FIFO queue, so a
50 ms-deadline request queued behind whatever batch-filling traffic
arrived first, and a new arrival waited out the window even when the
device was about to go idle. This module replaces that former with a
continuous scheduler (the vLLM-style upgrade, specialized to fixed-shape
image inference):

- **SLO classes.** The queue is partitioned by named classes
  (:class:`SLOClass`). Each class with a latency threshold is a real
  :func:`mpi4dl_tpu.telemetry.slo.latency_objective` over the per-class
  ``serve_class_latency_seconds{slo_class=}`` histogram, so the SLO
  evaluator publishes ``slo_burn_rate{slo="latency_<class>"}`` per class
  — the same burn math that pages a human now also steers the scheduler.
- **EDF ordering.** Within and across classes, requests dispatch in
  earliest-deadline-first order (a per-class heap keyed by absolute
  deadline, merged at pop time). A tight-deadline request jumps bulk
  traffic *by construction*; bulk cannot starve because its deadlines
  keep advancing toward the front (the starvation bound is the bulk
  deadline itself — tested in ``tests/test_scheduler.py``).
- **In-flight re-admission (continuous batching).** ``take()`` returns
  whatever is queued the moment the device can accept work instead of
  holding a formation window open: while batch *k* computes, every new
  arrival lands in the queue and joins batch *k+1* immediately. The old
  windowed former survives as ``mode="fifo"`` — it is the measured
  baseline the EDF arm's tail claims are judged against (bench.py
  ``sched_ab``).
- **Burn-rate feedback.** :class:`ClassFeedback` reads the per-class
  ``slo_burn_rate`` gauges back off the registry. When some class is in
  danger (burn above ``protect_factor``), the classes burning budget
  SLOWEST (burn under ``shed_floor`` x factor, or no objective at all)
  are *deprioritized* — they only fill batch slots after every
  protected class's queue is empty — and their admissions are *shed*
  early (at ``shed_ratio`` of the class queue bound instead of the full
  bound), counted in ``serve_class_shed_total``. The fleet router
  applies the same :class:`ClassFeedback` policy at ITS admission edge,
  so shedding happens before a doomed request crosses a process
  boundary.

Per-class admission isolation: each class owns ``max_queue`` slots, so a
bulk flood can fill bulk's queue without consuming a single tight slot.
``QueueFullError.retry_after_s`` is computed per class by the engine
(the batch cadence scaled by that class's backlog).
"""

from __future__ import annotations

import dataclasses
import heapq
import re
import threading
import time
from typing import Sequence

#: Class names must survive as metric label values and CLI tokens.
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: The burn window the feedback reads — the page-severity long window,
#: i.e. the same signal that would page a human (telemetry/slo.py
#: DEFAULT_BURN_WINDOWS).
FEEDBACK_BURN_WINDOW = "fast_long"

DEFAULT_CLASS_NAME = "default"


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One named SLO class: a latency objective + scheduling identity.

    name: label value on every per-class metric and the ``slo_class``
        argument of ``submit``.
    latency_threshold_s: the class's latency objective threshold over
        ``serve_class_latency_seconds{slo_class=name}``; None declares a
        class with no objective (pure scheduling bucket — it can never
        be "in danger", so under pressure it is first to yield).
    target: objective target ratio (0.99 = 99% under the threshold).
    deadline_s: default per-request deadline for submissions in this
        class when ``submit`` passes none; None falls back to the
        engine default.
    """

    name: str
    latency_threshold_s: "float | None" = None
    target: float = 0.99
    deadline_s: "float | None" = None

    def __post_init__(self):
        if not _NAME_RE.match(self.name):
            raise ValueError(
                f"SLO class name {self.name!r} must match {_NAME_RE.pattern}"
            )
        if self.latency_threshold_s is not None and self.latency_threshold_s <= 0:
            raise ValueError(
                f"class {self.name}: latency threshold must be > 0, got "
                f"{self.latency_threshold_s}"
            )
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"class {self.name}: target must be in (0, 1), got "
                f"{self.target} — pass 0.99, not 99"
            )

    @property
    def slo_name(self) -> str:
        """The ``slo=`` label value the evaluator publishes burn under."""
        return f"latency_{self.name}"

    def objective(self, tenant: str = "default"):
        """The class's latency :class:`~mpi4dl_tpu.telemetry.slo.
        Objective` over the per-class histogram; None when the class
        declares no threshold. ``tenant`` scopes the objective to one
        tenant's series (a tenancy-enabled engine builds one objective
        per (class, tenant), so each tenant burns its OWN budget)."""
        if self.latency_threshold_s is None:
            return None
        from mpi4dl_tpu.telemetry.slo import latency_objective

        return latency_objective(
            self.target,
            self.latency_threshold_s,
            metric="serve_class_latency_seconds",
            name=self.slo_name,
            labels=(("slo_class", self.name), ("tenant", tenant)),
            tenant=tenant,
        )


def default_classes() -> "tuple[SLOClass, ...]":
    """The implicit single-class configuration: one ``default`` class,
    no objective — exactly the pre-class engine behavior."""
    return (SLOClass(DEFAULT_CLASS_NAME),)


def parse_duration_s(tok: str) -> float:
    """``"50ms"``/``"2s"``/bare seconds → float seconds (the CLI's
    duration token, shared by the class spec and the load mix)."""
    tok = tok.strip()
    if tok.endswith("ms"):
        return float(tok[:-2]) / 1e3
    if tok.endswith("s"):
        return float(tok[:-1])
    return float(tok)


def parse_slo_classes(spec: str) -> "tuple[SLOClass, ...]":
    """``"tight=50ms:99.9@200ms,bulk=2s"`` → SLOClass tuple.

    Per class: ``NAME=THRESHOLD[:TARGET_PCT][@DEADLINE]`` —
    ``THRESHOLD``/``DEADLINE`` accept ``ms``/``s`` suffixes (bare
    numbers are seconds), ``TARGET_PCT`` is a percent (99.9, not
    0.999). ``NAME=none`` declares an objective-less class. Order
    matters: unclassed submissions land in the LAST class (list your
    bulk class last).
    """
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad SLO class {part!r}: expected NAME=THRESHOLD"
                "[:TARGET_PCT][@DEADLINE]"
            )
        name, rest = part.split("=", 1)
        deadline_s = None
        if "@" in rest:
            rest, ddl = rest.split("@", 1)
            deadline_s = parse_duration_s(ddl)
        target = 0.99
        if ":" in rest:
            rest, pct = rest.split(":", 1)
            target = float(pct) / 100.0
        threshold = None if rest.strip() in ("none", "") else parse_duration_s(rest)
        out.append(SLOClass(
            name=name.strip(), latency_threshold_s=threshold,
            target=target, deadline_s=deadline_s,
        ))
    if not out:
        raise ValueError(f"no SLO classes in {spec!r}")
    names = [c.name for c in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO class names in {spec!r}")
    return tuple(out)


def normalize_classes(classes) -> "tuple[SLOClass, ...]":
    """Engine/router constructor input → SLOClass tuple: None → the
    implicit default class, a spec string → parsed, a sequence →
    validated as-is."""
    if classes is None:
        return default_classes()
    if isinstance(classes, str):
        return parse_slo_classes(classes)
    out = tuple(classes)
    if not out:
        return default_classes()
    names = [c.name for c in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO class names: {names}")
    return out


class SchedulerFull(Exception):
    """Internal admission bounce: the class queue is full (``shed=False``)
    or the burn-feedback policy shed the admission early (``shed=True``).
    The engine/router wraps this into the public
    :class:`~mpi4dl_tpu.serve.QueueFullError` with a retry hint."""

    def __init__(self, slo_class: str, depth: int, capacity: int,
                 shed: bool = False):
        super().__init__(
            f"class {slo_class!r} queue "
            + ("shed by burn-rate feedback" if shed else "full")
            + f" ({depth}/{capacity} waiting)"
        )
        self.slo_class = slo_class
        self.depth = depth
        self.capacity = capacity
        self.shed = shed


class ClassFeedback:
    """Reads per-class burn back off the registry; decides who yields.

    The SLO evaluator publishes ``slo_burn_rate{slo="latency_<class>",
    window="fast_long"}`` every tick; this class turns those gauges into
    a scheduling policy:

    - a class is **in danger** when its burn exceeds ``protect_factor``
      (1.0 = spending exactly its error budget);
    - while ANY class is in danger, every class that is NOT in danger
      and is burning at or under ``shed_floor`` x ``protect_factor`` —
      or has no objective at all (burn unknowable) — is
      **deprioritized**: it fills batch slots only after the protected
      classes' queues are empty, and its admissions shed early.

    No burn data (evaluator not running, cold start) means no class is
    in danger and nothing is deprioritized — feedback can only engage on
    evidence. Evaluation is rate-limited (``min_interval_s``) so the
    dispatch hot path never pays more than a dict lookup.
    """

    def __init__(
        self,
        registry,
        classes: "Sequence[SLOClass]",
        protect_factor: float = 1.0,
        shed_floor: float = 0.5,
        min_interval_s: float = 0.25,
        clock=time.monotonic,
    ):
        self._registry = registry
        self._classes = tuple(classes)
        self.protect_factor = float(protect_factor)
        self.shed_floor = float(shed_floor)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_eval = float("-inf")
        self._states = {c.name: "normal" for c in self._classes}
        self._tenant_states: "dict[tuple[str, str], str]" = {}
        self._burns: "dict[str, float | None]" = {
            c.name: None for c in self._classes
        }

    def burns_by_tenant(self) -> "dict[str, dict[str, float]]":
        """Per-class, per-tenant page-window burn, straight off the
        gauges (``slo_burn_rate{slo=latency_<class>, tenant=}``); a
        class/tenant pair with no published series is simply absent."""
        out: "dict[str, dict[str, float]]" = {
            c.name: {} for c in self._classes
        }
        m = self._registry.get("slo_burn_rate") if self._registry else None
        if m is None:
            return out
        by_slo: "dict[str, dict[str, float]]" = {}
        for s in m.snapshot_series():
            if s["labels"].get("window") != FEEDBACK_BURN_WINDOW:
                continue
            by_slo.setdefault(s["labels"].get("slo"), {})[
                s["labels"].get("tenant", "default")
            ] = float(s["value"])
        for c in self._classes:
            if c.slo_name in by_slo:
                out[c.name] = dict(by_slo[c.slo_name])
        return out

    def burns(self) -> "dict[str, float | None]":
        """Per-class page-window burn (the default tenant's series, or
        the worst tenant when only per-tenant series exist); None for a
        class with no published series."""
        out: "dict[str, float | None]" = {}
        bbt = self.burns_by_tenant()
        for c in self._classes:
            per = bbt[c.name]
            if "default" in per:
                out[c.name] = per["default"]
            else:
                out[c.name] = max(per.values()) if per else None
        return out

    def _recompute(self, now: float) -> None:
        """One rate-limited evaluation: burn protection scoped PER
        TENANT — tenant t's slow-burning classes are deprioritized only
        while one of t's OWN classes is in danger, so a burning tenant
        cannot demote anyone else's bulk traffic."""
        bbt = self.burns_by_tenant()
        tenants = {t for per in bbt.values() for t in per}
        tenants.add("default")
        floor = self.shed_floor * self.protect_factor
        tstates: "dict[tuple[str, str], str]" = {}
        for t in tenants:
            burns_t = {c.name: bbt[c.name].get(t) for c in self._classes}
            danger = {
                n for n, b in burns_t.items()
                if b is not None and b > self.protect_factor
            }
            depri = set()
            if danger:
                depri = {
                    n for n, b in burns_t.items()
                    if n not in danger and (b is None or b <= floor)
                }
            for c in self._classes:
                tstates[(c.name, t)] = (
                    "deprioritized" if c.name in depri else "normal"
                )
        states = {
            c.name: tstates.get((c.name, "default"), "normal")
            for c in self._classes
        }
        burns = {
            c.name: bbt[c.name].get(
                "default",
                max(bbt[c.name].values()) if bbt[c.name] else None,
            )
            for c in self._classes
        }
        with self._lock:
            self._tenant_states = tstates
            self._states = states
            self._burns = burns

    def states(self, now: "float | None" = None) -> "dict[str, str]":
        """Per-class ``"normal" | "deprioritized"`` for the default
        tenant, recomputed at most every ``min_interval_s``."""
        now = self._clock() if now is None else now
        with self._lock:
            fresh = now - self._last_eval < self.min_interval_s
            if not fresh:
                self._last_eval = now
        if not fresh:
            self._recompute(now)
        with self._lock:
            return dict(self._states)

    def tenant_states(
        self, now: "float | None" = None
    ) -> "dict[tuple[str, str], str]":
        """Per-(class, tenant) states — the scheduler's and router's
        tenancy-aware view; same rate limit as :meth:`states`."""
        now = self._clock() if now is None else now
        with self._lock:
            fresh = now - self._last_eval < self.min_interval_s
            if not fresh:
                self._last_eval = now
        if not fresh:
            self._recompute(now)
        with self._lock:
            return dict(self._tenant_states)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "burn_window": FEEDBACK_BURN_WINDOW,
                "protect_factor": self.protect_factor,
                "shed_floor": self.shed_floor,
                "burns": dict(self._burns),
                "states": dict(self._states),
                "states_by_tenant": {
                    f"{c}/{t}": st
                    for (c, t), st in self._tenant_states.items()
                    if st != "normal"
                },
            }


class ClassScheduler:
    """Per-class EDF admission queues + the continuous batch former.

    Request contract (duck-typed — the engine's ``_Request`` and any
    test stub): requests expose ``.deadline`` (absolute monotonic) and
    ``.slo_class``; the scheduler stamps ``.form_t`` at pop time (the
    queue_wait → batch_form span boundary).

    classes: normalized :class:`SLOClass` tuple; unclassed submissions
        resolve to the class named ``default`` when present, else the
        LAST class (configure bulk last).
    max_queue: per-class admission bound (a bulk flood cannot consume a
        tight slot).
    mode: ``"edf"`` (deadline order, feedback honored — the continuous
        scheduler) or ``"fifo"`` (arrival order, feedback ignored — the
        PR-2 baseline arm).
    registry: when given, publishes ``serve_queue_depth`` (total),
        ``serve_class_queue_depth{slo_class=}``,
        ``serve_class_shed_total{slo_class=}`` and
        ``serve_class_deprioritized{slo_class=}``.
    feedback: a :class:`ClassFeedback`; None disables deprioritization
        and shedding (single-class engines).
    shed_ratio: fraction of the class queue bound at which a
        DEPRIORITIZED class starts shedding admissions.
    tenants: normalized :class:`~mpi4dl_tpu.tenancy.Tenant` tuple (or a
        spec string / None). When set, each class's queue is
        sub-partitioned per tenant and batch slots are filled across
        tenants by deficit-weighted round robin — in-quota traffic from
        one tenant cannot monopolize batch formation. None = tenancy
        off (single implicit ``default`` tenant, DWRR skipped).
    """

    def __init__(
        self,
        classes: "Sequence[SLOClass]",
        max_queue: int,
        registry=None,
        mode: str = "edf",
        feedback: "ClassFeedback | None" = None,
        shed_ratio: float = 0.5,
        tenants=None,
        clock=time.monotonic,
    ):
        if mode not in ("edf", "fifo"):
            raise ValueError(f"scheduler mode must be edf|fifo, got {mode!r}")
        self.classes = tuple(classes)
        if not self.classes:
            raise ValueError("need at least one SLO class")
        self._by_name = {c.name: c for c in self.classes}
        self._default = self._by_name.get(
            DEFAULT_CLASS_NAME, self.classes[-1]
        )
        self.capacity = int(max_queue)
        self.mode = mode
        self.feedback = feedback
        self.shed_ratio = float(shed_ratio)
        self._clock = clock
        self._cond = threading.Condition()
        # class -> tenant -> heap; tenant sub-heaps appear on first use
        # (an engine without tenancy only ever grows the default one).
        self._heaps: "dict[str, dict[str, list]]" = {
            c.name: {} for c in self.classes
        }
        self._dwrr = None
        from mpi4dl_tpu.tenancy.model import (
            DeficitRoundRobin,
            normalize_tenants,
        )

        self.tenants = normalize_tenants(tenants)
        if self.tenants is not None and mode == "edf":
            weights = {t.name: t.weight for t in self.tenants}
            self._dwrr = {
                c.name: DeficitRoundRobin(weights) for c in self.classes
            }
        self._seq = 0
        self.shed_counts = {c.name: 0 for c in self.classes}
        self._m_depth = self._m_class_depth = None
        self._m_shed = self._m_depri = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_depth = telemetry.declare(registry, "serve_queue_depth")
            self._m_class_depth = telemetry.declare(
                registry, "serve_class_queue_depth"
            )
            self._m_shed = telemetry.declare(
                registry, "serve_class_shed_total"
            )
            self._m_depri = telemetry.declare(
                registry, "serve_class_deprioritized"
            )
            self._m_depth.set(0)
            for c in self.classes:
                self._m_class_depth.set(0, slo_class=c.name)
                self._m_depri.set(0, slo_class=c.name)

    # -- class resolution ------------------------------------------------------

    def resolve(self, name: "str | None") -> SLOClass:
        """``slo_class`` argument → SLOClass. Unknown names raise — a
        router/engine class-config mismatch is a deployment bug and
        must be loud, not silently misfiled."""
        if name is None:
            return self._default
        cls = self._by_name.get(str(name))
        if cls is None:
            raise ValueError(
                f"unknown SLO class {name!r} (configured: "
                f"{sorted(self._by_name)})"
            )
        return cls

    # -- admission -------------------------------------------------------------

    def _states(self) -> "dict[tuple[str, str], str]":
        """Per-(class, tenant) feedback states (empty dict = feedback
        off); the internal key shape every admission/pop site uses."""
        if self.feedback is None or self.mode == "fifo":
            return {}
        return self.feedback.tenant_states(self._clock())

    def put_many(self, reqs: "list") -> int:
        """Admit a group of same-class requests atomically: all enqueue
        or none do (a multi-image split must never half-admit). Returns
        the class queue depth after the enqueue. Raises
        :class:`SchedulerFull` on a full class queue or an early
        feedback shed."""
        if not reqs:
            return 0
        name = reqs[0].slo_class
        tenant = getattr(reqs[0], "tenant", "default") or "default"
        states = self._states()
        with self._cond:
            tmap = self._heaps[name]
            heap = tmap.setdefault(tenant, [])
            depth = sum(len(h) for h in tmap.values())
            if states.get((name, tenant)) == "deprioritized":
                shed_at = max(1, int(self.shed_ratio * self.capacity))
                if depth + len(reqs) > shed_at:
                    self.shed_counts[name] += len(reqs)
                    if self._m_shed is not None:
                        self._m_shed.inc(len(reqs), slo_class=name)
                    raise SchedulerFull(
                        name, depth, shed_at, shed=True
                    )
            if depth + len(reqs) > self.capacity:
                raise SchedulerFull(name, depth, self.capacity)
            for r in reqs:
                self._seq += 1
                pri = r.deadline if self.mode == "edf" else float(self._seq)
                heapq.heappush(heap, (pri, self._seq, r))
            depth = sum(len(h) for h in tmap.values())
            self._cond.notify()
        self._publish_depths(states)
        return depth

    def put(self, req) -> int:
        return self.put_many([req])

    # -- the batch former ------------------------------------------------------

    def _pop_best(self, now: float, states: "dict[tuple[str, str], str]",
                  expired: "list") -> "object | None":
        """Pop the globally best request under the mode's ordering:
        fifo → lowest sequence; edf → protected (class, tenant) queues
        first, then earliest deadline (sequence breaks ties). With
        tenancy configured, the EDF/depri key still chooses WHICH CLASS
        the slot goes to, but WHICH TENANT fills it is the class's
        deficit-weighted round robin — so a tenant flooding in-quota
        traffic still cannot take more than its weighted share of batch
        slots. Requests whose deadline already passed are stamped and
        moved to ``expired`` (they never occupy a batch slot). Caller
        holds the lock."""
        while True:
            best = None  # (key, class, tenant)
            for name, tmap in self._heaps.items():
                for tenant, heap in tmap.items():
                    if not heap:
                        continue
                    pri, seq, _ = heap[0]
                    if self.mode == "fifo":
                        key = (seq,)
                    else:
                        key = (
                            1 if states.get((name, tenant))
                            == "deprioritized" else 0,
                            pri, seq,
                        )
                    if best is None or key < best[0]:
                        best = (key, name, tenant)
            if best is None:
                return None
            key, name, tenant = best
            if self._dwrr is not None:
                # Fair fill across tenants at the SAME depri level —
                # DWRR must never promote a deprioritized tenant's
                # queue over a protected one.
                level = key[0] if self.mode == "edf" else 0
                active = [
                    t for t, h in self._heaps[name].items()
                    if h and (
                        1 if states.get((name, t)) == "deprioritized"
                        else 0
                    ) == level
                ]
                pick = self._dwrr[name].pick(active)
                if pick is not None:
                    tenant = pick
            _, _, req = heapq.heappop(self._heaps[name][tenant])
            req.form_t = now
            if now > req.deadline:
                expired.append(req)
                continue
            return req

    def take(
        self,
        max_n: int,
        first_timeout_s: float,
        window_s: float = 0.0,
    ) -> "tuple[list, list]":
        """Form one batch: ``(reqs, expired)``.

        Blocks up to ``first_timeout_s`` for the first request. With
        ``window_s == 0`` (continuous mode) it then returns everything
        immediately available up to ``max_n`` — a new arrival during
        the in-flight batch's compute joins the NEXT take with no
        window to wait out. With ``window_s > 0`` (the fifo baseline)
        it keeps collecting until the window closes or ``max_n`` is
        reached — the PR-2 former's exact shape. ``expired`` are
        requests whose deadline passed while queued; the engine rejects
        them without serving."""
        reqs: list = []
        expired: list = []
        states = self._states()
        with self._cond:
            deadline = self._clock() + first_timeout_s
            while not any(
                h for tmap in self._heaps.values() for h in tmap.values()
            ):
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return [], []
                self._cond.wait(remaining)
            window_end = self._clock() + window_s
            while len(reqs) < max_n:
                req = self._pop_best(self._clock(), states, expired)
                if req is not None:
                    reqs.append(req)
                    continue
                remaining = window_end - self._clock()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        self._publish_depths(states)
        return reqs, expired

    # -- bulk operations / introspection ---------------------------------------

    def drain(self) -> "list":
        """Pop everything (stop/flush); returns the requests in no
        particular order."""
        out = []
        with self._cond:
            for tmap in self._heaps.values():
                for heap in tmap.values():
                    out.extend(req for _, _, req in heap)
                    heap.clear()
        self._publish_depths({})
        return out

    def qsize(self) -> int:
        with self._cond:
            return sum(
                len(h) for tmap in self._heaps.values()
                for h in tmap.values()
            )

    def qsize_by_class(self) -> "dict[str, int]":
        with self._cond:
            return {
                name: sum(len(h) for h in tmap.values())
                for name, tmap in self._heaps.items()
            }

    def qsize_by_tenant(self) -> "dict[str, dict[str, int]]":
        """class → tenant → depth (the tenancy debug view)."""
        with self._cond:
            return {
                name: {t: len(h) for t, h in tmap.items() if h}
                for name, tmap in self._heaps.items()
            }

    def empty(self) -> bool:
        return self.qsize() == 0

    def _publish_depths(
        self, states: "dict[tuple[str, str], str]"
    ) -> None:
        if self._m_depth is None:
            return
        depths = self.qsize_by_class()
        self._m_depth.set(sum(depths.values()))
        for name, d in depths.items():
            self._m_class_depth.set(d, slo_class=name)
        if states:
            for name in self._heaps:
                depri = any(
                    st == "deprioritized"
                    for (c, _t), st in states.items() if c == name
                )
                self._m_depri.set(1.0 if depri else 0.0, slo_class=name)

    def state(self) -> dict:
        """The stats()/debugz payload: per-class depths, shed counts,
        the live feedback + tenancy view."""
        out = {
            "mode": self.mode,
            "capacity_per_class": self.capacity,
            "depth_by_class": self.qsize_by_class(),
            "shed_by_class": dict(self.shed_counts),
            "feedback": (
                self.feedback.snapshot() if self.feedback is not None
                else None
            ),
        }
        if self.tenants is not None:
            out["depth_by_tenant"] = self.qsize_by_tenant()
            if self._dwrr is not None:
                out["dwrr"] = {
                    name: rr.state() for name, rr in self._dwrr.items()
                }
        return out
