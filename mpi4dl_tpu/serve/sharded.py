"""Multi-chip sharded serving: the trainer's spatial forward on the hot loop.

The paper's whole point is spatial parallelism for images too large for
one device — partition H×W across chips with a halo exchange at every
conv/pool — yet serving was single-chip-per-replica: training peaked at
4096² per chip and anything bigger could not be *served* at all. This
module closes that gap by plugging the sharded frozen-stats forward
(:func:`mpi4dl_tpu.evaluate.aot_compile_spatial_predict`, the
``make_spatial_eval_step``-style ``shard_map`` program over the trainer's
``tile_h×tile_w`` mesh) into the :class:`~mpi4dl_tpu.serve.ServingEngine`
through its predictor seam. Everything above the forward — continuous
batcher, EDF class scheduler, deadlines, spans, SLO evaluator, tail
watcher — is byte-for-byte the single-chip stack; the fleet then
replicates sharded replicas for traffic, so **shard for model size,
replicate for traffic** are two orthogonal scaling axes.

Three existing subsystems become load-bearing on this path:

- **lint** — :meth:`ShardedPredictor.expectations` derives the hlolint
  gate from the mesh: the tile grid plus the counted forward halo shifts
  (``Trainer.halo_shift_count``), so every warmed bucket's HLO is gated
  by the partition-math halo-permute window (the train step's rule)
  instead of the single-chip zero-collectives rule.
- **overlap** — ``conv_overlap="decomposed"`` (or
  ``MPI4DL_TPU_CONV_OVERLAP``) compiles every bucket with the PR-9
  interior/boundary decomposition, putting the T3/FLUX
  interior-hides-permute trade on a latency-critical path; the output is
  bit-identical to the monolithic arm (same invariant as training) and
  ``analyze serving-sharded`` measures both arms' ``trace_overlap_ratio``
  with the ``trace-overlap-crosscheck`` gate.
- **memory** — each bucket's compile-time footprint lands in the engine's
  ledger as the PER-CHIP share (``shard_map`` peak is per device), so
  ``analyze memory-plan`` math and the opt-in ``memory_guard`` answer
  "which px/bucket fits a chip's share" before warm-up and refuse unfit
  sharded buckets with reasons in ``stats()``.

Bit-identity scope (same boundary as everywhere in this repo): the
sharded forward is a DIFFERENT program from the plain one (tile-local
convs + halo exchange vs one full-image conv), so sharded-vs-single-chip
parity holds at the documented f32 reduction-order tolerance; the two
OVERLAP arms of the *same* mesh are bit-identical to each other.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Sequence

import numpy as np

from mpi4dl_tpu.serve.engine import ServingEngine


@contextlib.contextmanager
def conv_overlap_env(impl: "str | None"):
    """Pin ``MPI4DL_TPU_CONV_OVERLAP`` while tracing one arm's program
    (the selector is read at trace time, per spatial windowed op).
    ``None`` leaves the process environment alone."""
    if impl is None:
        yield
        return
    prev = os.environ.get("MPI4DL_TPU_CONV_OVERLAP")
    os.environ["MPI4DL_TPU_CONV_OVERLAP"] = impl
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("MPI4DL_TPU_CONV_OVERLAP", None)
        else:
            os.environ["MPI4DL_TPU_CONV_OVERLAP"] = prev


def parse_mesh(spec: str) -> tuple[int, int]:
    """``"2x2"`` / ``"1x2"`` → ``(tile_h, tile_w)``. The CLI surface of
    the mesh axis (worker ``--mesh``, serve ``--mesh``)."""
    try:
        th, tw = (int(p) for p in str(spec).lower().split("x"))
    except ValueError:
        raise ValueError(
            f"mesh must look like HxW (e.g. 2x2, 1x2), got {spec!r}"
        ) from None
    if th < 1 or tw < 1:
        raise ValueError(f"mesh extents must be >= 1, got {th}x{tw}")
    return th, tw


def serving_mesh_config(
    mesh_shape: Sequence[int], image_size: int, num_classes: int = 10
):
    """A :class:`~mpi4dl_tpu.config.ParallelConfig` for a serving-only
    spatial front on a ``tile_h×tile_w`` grid: square meshes slice
    square, ``1×W`` vertical, ``H×1`` horizontal (the reference's three
    ``slice_method``\\ s — a non-square non-strip grid has no slicing
    rule and is rejected). ``data_parallel=1``: the whole bucket rides
    every tile; the FLEET replicates for traffic."""
    from mpi4dl_tpu.config import ParallelConfig

    th, tw = (int(d) for d in mesh_shape)
    if th == tw == 1:
        raise ValueError(
            "1x1 mesh is the single-chip engine — construct ServingEngine "
            "directly instead of the sharded path"
        )
    if th == tw:
        slice_method, parts = "square", th * tw
    elif th == 1:
        slice_method, parts = "vertical", tw
    elif tw == 1:
        slice_method, parts = "horizontal", th
    else:
        raise ValueError(
            f"unsupported mesh {th}x{tw}: spatial slicing needs a square "
            "grid, 1xW (vertical), or Hx1 (horizontal)"
        )
    return ParallelConfig(
        batch_size=1, split_size=1, spatial_size=1,
        num_spatial_parts=(parts,), slice_method=slice_method,
        image_size=int(image_size), num_classes=num_classes,
        data_parallel=1,
    )


class ShardedPredictor:
    """Compile/stage/run backend running every bucket as the trainer's
    spatially-partitioned forward over its ``tile_h×tile_w`` mesh.

    trainer: a spatial :class:`~mpi4dl_tpu.train.Trainer` (its cells,
        mesh, and ``x_spec`` define the program; no training state is
        touched).
    params / batch_stats: the calibrated triple's arrays; placed
        replicated on the mesh here.
    example_shape: per-request ``(H, W, C)`` — H/W must match the
        trainer config's ``image_size`` (the tile geometry).
    conv_overlap: ``"monolithic"`` / ``"decomposed"`` pins the spatial
        conv/pool impl for every bucket compile (PR-9
        ``overlap_decompose``); None inherits ``MPI4DL_TPU_CONV_OVERLAP``.
    """

    program = "serve_sharded"

    def __init__(
        self,
        trainer,
        params,
        batch_stats,
        example_shape: Sequence[int],
        dtype=None,
        conv_overlap: "str | None" = None,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi4dl_tpu.config import AXIS_TILE_H, AXIS_TILE_W

        if conv_overlap not in (None, "monolithic", "decomposed"):
            raise ValueError(
                f"conv_overlap must be monolithic/decomposed/None, "
                f"got {conv_overlap!r}"
            )
        self.trainer = trainer
        self.example_shape = tuple(int(d) for d in example_shape)
        self.dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
        self.conv_overlap = conv_overlap
        mesh = trainer.mesh
        self.mesh_shape = (
            mesh.shape[AXIS_TILE_H], mesh.shape[AXIS_TILE_W]
        )
        h, w = self.example_shape[0], self.example_shape[1]
        th, tw = self.mesh_shape
        if h % th or w % tw:
            raise ValueError(
                f"example {h}x{w} does not tile over the {th}x{tw} mesh"
            )
        # Params/stats live replicated on the mesh once; per-request
        # traffic is the tile-sharded input batch only.
        repl = NamedSharding(mesh, P())
        self.params = jax.device_put(params, repl)
        self.stats = jax.device_put(batch_stats, repl)
        self._x_sharding = NamedSharding(mesh, trainer.x_spec)
        self._halo_shifts: "int | None" = None
        # Per-bucket cold-start facts (trace_s/compile_s/fingerprint —
        # the fingerprint folds the tile-mesh shape in) from the last
        # compile_bucket; the engine merges them into the ledger entry.
        self.compile_timings: "dict[int, dict]" = {}

    @property
    def num_devices(self) -> int:
        return self.mesh_shape[0] * self.mesh_shape[1]

    def halo_shifts(self) -> int:
        """Forward halo-shift permutes in one pass over the cells
        (``Trainer.halo_shift_count``, an abstract trace) — the
        partition-math input of the lint window. The decomposed overlap
        arm calls ``halo_exchange`` exactly once per windowed op too
        (the PR-9 invariant), so one count covers both arms."""
        if self._halo_shifts is None:
            self._halo_shifts = self.trainer.halo_shift_count(
                self.params, (1, *self.example_shape), dtype=self.dtype
            )
        return self._halo_shifts

    def compile_bucket(self, bucket: int):
        from mpi4dl_tpu.evaluate import aot_compile_spatial_predict

        timings: dict = {}
        with conv_overlap_env(self.conv_overlap):
            out = aot_compile_spatial_predict(
                self.trainer, self.params, self.stats, self.example_shape,
                [bucket], dtype=self.dtype, timings=timings,
            )[bucket]
        self.compile_timings[bucket] = timings.get(bucket, {})
        return out

    def stage(self, batch):
        """Async host→mesh transfer: the bucket lands tile-sharded
        (H over ``tile_h``, W over ``tile_w``) exactly as compiled."""
        import jax

        return jax.device_put(batch, self._x_sharding)

    def run(self, compiled, staged):
        if isinstance(staged, np.ndarray):
            staged = self.stage(staged)
        return compiled(self.params, self.stats, staged)

    def expectations(self):
        """Algebra-derived hlolint expectations: the spatial layer delta
        (partition-math halo window off the counted forward shifts)
        composes to the permute-window gate — the flip from the
        single-chip zero-collectives rule."""
        from mpi4dl_tpu.analysis.expectations import compose

        return compose(self.collective_deltas())

    def collective_deltas(self):
        """One spatial layer delta over this predictor's tile mesh
        (:mod:`mpi4dl_tpu.analysis.expectations`)."""
        from mpi4dl_tpu.analysis.expectations import spatial_delta

        return (spatial_delta(self.mesh_shape, self.halo_shifts()),)

    def platform(self) -> str:
        return self.limit_device().platform

    def limit_device(self):
        """One tile device: the memory guard compares each bucket's
        PER-CHIP footprint share against a single chip's limit."""
        return self.trainer.mesh.devices.flat[0]

    def param_tree(self):
        """``(params, batch_stats)`` live trees, for the numerics
        sentinel's integrity checksum (telemetry/canary.py)."""
        return self.params, self.stats

    def reload_params(self, params) -> None:
        """Replace the live parameter tree (replicated across the mesh,
        like construction). ``run`` passes ``self.params`` on every
        call, so the swap takes effect on the next dispatch."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.params = jax.device_put(
            params, NamedSharding(self.trainer.mesh, P())
        )


def sharded_engine(
    cells: Sequence[Any],
    plain_cells: Sequence[Any],
    num_spatial_cells: int,
    params,
    batch_stats,
    example_shape: Sequence[int],
    mesh_shape: Sequence[int] = (2, 2),
    conv_overlap: "str | None" = None,
    dtype=None,
    mesh=None,
    num_classes: int = 10,
    **engine_kw,
) -> ServingEngine:
    """Build a spatially-sharded :class:`ServingEngine` from a calibrated
    model: spatial cell list (first ``num_spatial_cells`` flagged
    spatial), its plain twin, params, and BN stats — the same triple the
    trainer and the single-chip engine consume. Calibrate small models
    with :func:`~mpi4dl_tpu.evaluate.collect_batch_stats` on the plain
    twin, or :func:`~mpi4dl_tpu.evaluate.spatial_collect_batch_stats`
    when the full image does not fit one device."""
    from mpi4dl_tpu.train import Trainer

    h, w = int(example_shape[0]), int(example_shape[1])
    if h != w:
        raise ValueError(
            f"sharded serving tiles square images, got example {h}x{w}"
        )
    cfg = serving_mesh_config(mesh_shape, h, num_classes=num_classes)
    with conv_overlap_env(conv_overlap):
        trainer = Trainer(
            cells, num_spatial_cells=num_spatial_cells, config=cfg,
            plain_cells=plain_cells, mesh=mesh,
        )
    predictor = ShardedPredictor(
        trainer, params, batch_stats, example_shape,
        dtype=dtype, conv_overlap=conv_overlap,
    )
    return ServingEngine.from_predictor(predictor, **engine_kw)


def sharded_engine_from_checkpoint(
    path_or_dir: str,
    mesh_shape: Sequence[int],
    spatial_cells: "int | None" = None,
    conv_overlap: "str | None" = None,
    **engine_kw,
) -> ServingEngine:
    """Spatially-sharded engine from a self-describing checkpoint path
    alone: the metadata's model block rebuilds BOTH twins (the plain one
    for params/BN structure, the spatial one for the tile mesh — the
    ``spatial_cells`` builder arg rides in the checkpoint, see
    :func:`mpi4dl_tpu.checkpoint.model_metadata`), restored params and
    calibrated ``batch_stats`` plug into :func:`sharded_engine`
    unchanged. This is what ``python -m mpi4dl_tpu.serve --ckpt ...
    --mesh HxW`` builds (previously refused loudly)."""
    from mpi4dl_tpu.checkpoint import (
        rebuild_from_checkpoint,
        rebuild_spatial_twin,
    )

    cells, state, stats, meta = rebuild_from_checkpoint(path_or_dir)
    del cells  # the twins below are rebuilt with the spatial split
    if stats is None:
        raise ValueError(
            "checkpoint has no batch_stats.msgpack — calibrate with "
            "evaluate.collect_batch_stats and save_checkpoint(..., "
            "batch_stats=...) before serving"
        )
    spatial, plain, n_sp = rebuild_spatial_twin(
        meta, spatial_cells=spatial_cells
    )
    spec = meta["model"]
    size = int(spec["image_size"])
    engine_kw.setdefault("dtype", spec.get("dtype", "float32"))
    return sharded_engine(
        spatial, plain, n_sp, state.params, stats,
        example_shape=(size, size, spec.get("channels", 3)),
        mesh_shape=mesh_shape, conv_overlap=conv_overlap,
        num_classes=int(spec.get("num_classes", 10)), **engine_kw,
    )


def synthetic_sharded_engine(
    mesh_shape: Sequence[int],
    image_size: int = 32,
    depth: int = 8,
    num_classes: int = 10,
    spatial_cells: int = 3,
    calib_batches: int = 1,
    conv_overlap: "str | None" = None,
    seed: int = 0,
    **engine_kw,
) -> ServingEngine:
    """Zero-artifact sharded engine: a spatial ResNet-v1 front (depth
    6n+2) calibrated on random batches — the sharded twin of the serve
    CLI's synthetic single-chip path, and what ``--mesh HxW`` builds."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.partition import init_cells

    size = int(image_size)
    plain = get_resnet_v1(
        depth=depth, num_classes=num_classes, pool_kernel=size // 4
    )
    n_sp = min(int(spatial_cells), len(plain) - 1)
    cells = get_resnet_v1(
        depth=depth, num_classes=num_classes, pool_kernel=size // 4,
        spatial_cells=n_sp,
    )
    rng = np.random.default_rng(seed)
    params = init_cells(
        plain, jax.random.PRNGKey(seed), jnp.zeros((1, size, size, 3))
    )
    cal = [
        jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)
        for _ in range(max(1, int(calib_batches)))
    ]
    stats = collect_batch_stats(plain, params, cal)
    return sharded_engine(
        cells, plain, n_sp, params, stats,
        example_shape=(size, size, 3), mesh_shape=mesh_shape,
        conv_overlap=conv_overlap, num_classes=num_classes, **engine_kw,
    )
