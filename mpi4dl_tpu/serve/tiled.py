"""Gigapixel tiled inference: a halo-correct tile-streaming forward.

The paper's workload is very-high-resolution images, yet the single-chip
forward peaks at what one device's HBM holds (training measured 4096² per
chip; 8192² dies RESOURCE_EXHAUSTED), and the multi-chip sharded path
(serve/sharded.py) needs a mesh. *Inference* under frozen batch statistics
has none of the gradient coupling that killed H-strip training
(docs/PERF.md round 5): every conv/pool/BN/ReLU in the pre-head stack is
spatially LOCAL, so the forward decomposes into overlap-read tiles whose
results stitch exactly. This module serves arbitrarily large images on ONE
chip at bounded memory:

- **Tile margin from partition math.** The overlap each tile must read
  beyond its core is the cumulative receptive-field growth of the
  conv/pool stack up to the head split — the same per-op ``padding ×
  cumulative-stride`` sum the spatial trainer's halo exchange carries
  (``Trainer.halo_shift_count`` counts the permutes; here there is no
  wire, so the "exchange" is an overlapped host-array read). It is
  derived by abstractly tracing the section under
  :func:`mpi4dl_tpu.ops.layers.record_windowed_ops` (``jax.eval_shape``,
  no device work), never hardcoded per model.
- **Exact stitching.** Tile windows are clamped inside the image: an
  interior window edge carries ≥ margin rows of REAL neighbor pixels (the
  conv's own zero padding contaminates at most the margin, which is
  cropped), and a window edge at the image boundary coincides with it, so
  the conv's zero padding there IS the monolithic padding. Every kept
  output element therefore sees exactly the bytes the monolithic forward
  saw — the stitched result is bit-identical wherever the monolithic
  forward fits (tier-1-asserted, the PR-9 ``overlap_decompose``
  equivalence bar).
- **One AOT-warmed tile executable.** Interior, edge, corner, and ragged
  tiles all run the SAME fixed ``window × window`` program (clamping
  keeps the shape constant), batched into power-of-two TILE buckets and
  streamed with double-buffered H2D staging: batch *k+1* stages and
  dispatches before batch *k*'s result is harvested, so transfers overlap
  device compute and the live set is bounded at two tile batches — peak
  HBM is the tile executable's, not the image's. The stitched feature map
  (1/stride² of the image) then runs the head once.

Serving surface: :func:`tiled_engine` puts a :class:`TiledPredictor`
behind the PR-13 predictor seam — batcher, EDF scheduler, deadlines,
spans, SLO evaluator, tail watcher all unchanged — with single-image
buckets and its own SLO class (default ``tiled``), so a 60-second
gigapixel request burns its own error budget, never the tight class's.
``python -m mpi4dl_tpu.serve --tiled HxW`` and the fleet worker's
``POST /predict_tiled`` (router/front-door passthrough included) expose
it; ``python -m mpi4dl_tpu.analyze memory-plan --bisect tile`` answers
"what tile size fits this chip" before anything runs, and the
``device_hbm_*`` gauges verify the bounded-memory claim live.

Scope: models whose pre-head section is a plain NHWC conv/pool stack
(every zoo ResNet). The packed activation layout folds image columns into
channels — its extents cannot be re-read as overlapping windows — and is
refused loudly at geometry time.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Sequence

import numpy as np

from mpi4dl_tpu.serve.batching import bucket_for, power_of_two_buckets
from mpi4dl_tpu.serve.engine import ServingEngine

#: Default SLO class of a tiled engine: its own latency objective so the
#: scheduler's burn-rate feedback and the SLO evaluator account gigapixel
#: requests separately from any interactive class.
DEFAULT_TILED_CLASS = "tiled"
DEFAULT_TILED_THRESHOLD_S = 120.0

#: The tiled_* metric names the predictor publishes (all cataloged —
#: declared in one call by :func:`declare_metrics`, the
#: ``fleet.declare_metrics`` pattern, so the catalog==runtime pin stays
#: honest without spawning a tiled engine in the full-stack fixture;
#: live series are exercised by ``tests/test_serve_tiled.py``).
TILED_METRICS = (
    "tiled_tiles_total",
    "tiled_tile_batches_total",
    "tiled_tiles_per_request",
    "tiled_stitch_seconds",
    "tiled_tile_stream_seconds",
)


def declare_metrics(registry) -> None:
    """Declare every tiled_* metric on ``registry`` (names only — the
    predictor's :meth:`TiledPredictor.bind_telemetry` publishes the live
    series on its engine's registry)."""
    from mpi4dl_tpu import telemetry

    for name in TILED_METRICS:
        telemetry.declare(registry, name)


@dataclasses.dataclass(frozen=True)
class TileGeometry:
    """The derived plan of one tiled forward: per-axis core/window tiling
    plus the section's stride/margin facts. ``tiles_h``/``tiles_w`` hold
    ``(core_start, core_len, window_start)`` per tile — every window has
    extent ``window_hw`` (clamped inside the image), cores partition it
    exactly."""

    image_hw: tuple
    tile_hw: tuple          # requested core extent (multiple of stride)
    margin_hw: tuple        # overlap read beyond the core, input px
    stride_hw: tuple        # cumulative section downsampling
    window_hw: tuple        # core + 2*margin, clamped to the image
    feat_hw: tuple          # stitched feature-map extent (pre-head)
    feat_channels: int
    feat_dtype: Any
    split: int              # cells[:split] = section, cells[split:] = head
    ops: tuple              # recorded windowed-op geometry (forensics)
    tiles_h: tuple
    tiles_w: tuple

    @property
    def n_tiles(self) -> int:
        return len(self.tiles_h) * len(self.tiles_w)

    @property
    def grid(self) -> tuple:
        return (len(self.tiles_h), len(self.tiles_w))

    def describe(self) -> dict:
        return {
            "image": list(self.image_hw),
            "tile": list(self.tile_hw),
            "margin": list(self.margin_hw),
            "stride": list(self.stride_hw),
            "window": list(self.window_hw),
            "grid": list(self.grid),
            "tiles_per_request": self.n_tiles,
            "feature_hw": list(self.feat_hw),
            "feature_channels": self.feat_channels,
        }


def _pair(v) -> tuple:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def section_margin(ops, image_hw) -> tuple:
    """Cumulative receptive-field growth of a recorded windowed-op stack,
    in input pixels per dim: ``Σ max(pad, kernel-1-pad) × downsampling``
    over the ops, where downsampling is the op's input extent relative to
    the image (the ``Trainer.halo_shift_count`` partition math without the
    wire). A tile core flanked by this many rows/cols of real neighbor
    data is untouched by the window-edge zero padding after the whole
    stack (the induction the stitch-exactness suite pins)."""
    margin = [0, 0]
    for op in ops:
        if op["kind"] == "packed":
            raise ValueError(
                "tiled inference does not support the packed activation "
                "layout: packed columns fold image W into channels, so "
                "overlap-read windows cannot be sliced from the input — "
                "build the model with layout='nhwc'"
            )
        for d in (0, 1):
            n, h = int(image_hw[d]), int(op["input_hw"][d])
            if h <= 0 or n % h:
                raise ValueError(
                    f"non-uniform downsampling: op input extent {h} does "
                    f"not divide the image extent {n} — tiled inference "
                    "needs stride-aligned section shapes"
                )
            k, p = op["kernel"][d], op["padding"][d]
            margin[d] += max(p, k - 1 - p) * (n // h)
    return tuple(margin)


def _round_up(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _axis_plan(n: int, tile: int, margin: int) -> tuple:
    """Per-dim tiling: cores ``[i*tile, ...)`` (last one ragged), windows
    of constant extent ``tile + 2*margin`` clamped inside ``[0, n]`` so a
    window edge is either the image edge (conv padding == monolithic
    padding) or ≥ margin rows of real data from its core. Returns
    ``(entries, window)`` with entries ``(core0, core_len, win0)``."""
    win = tile + 2 * margin
    if win >= n:
        return ((0, n, 0),), n
    entries = []
    c0 = 0
    while c0 < n:
        clen = min(tile, n - c0)
        a = min(max(c0 - margin, 0), n - win)
        entries.append((c0, clen, a))
        c0 += clen
    return tuple(entries), win


def tile_geometry(
    cells: Sequence[Any],
    params: Sequence[Any],
    batch_stats,
    example_shape: Sequence[int],
    tile,
    split: "int | None" = None,
    dtype=None,
) -> TileGeometry:
    """Derive the tiled-forward plan for a model: abstractly trace the
    pre-head section (``jax.eval_shape`` — zero device work, works on
    ``ShapeDtypeStruct`` params too, which is what ``analyze memory-plan
    --bisect tile`` feeds it), collect every windowed op's geometry, and
    turn it into margin/stride/tile plans. Raises ``ValueError`` on
    layouts it cannot stitch exactly (packed layout, non-NHWC section
    output, stride-misaligned extents or tile sizes)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import _apply_running
    from mpi4dl_tpu.ops.layers import record_windowed_ops

    cells = tuple(cells)
    split = len(cells) - 1 if split is None else int(split)
    if not 0 < split < len(cells):
        raise ValueError(
            f"split must leave a non-empty section and head, got {split} "
            f"of {len(cells)} cells"
        )
    for i, cell in enumerate(cells):
        pack = getattr(cell, "pack", None)
        packed = (
            any(int(f) != 1 for f in pack)
            if isinstance(pack, (tuple, list))
            else (pack is not None and int(pack) != 1)
        )
        if packed:
            raise ValueError(
                "tiled inference does not support the packed activation "
                f"layout (cell {i} is packed): packed columns fold image "
                "W into channels, so overlap-read windows cannot be "
                "sliced from the input — build the model with "
                "layout='nhwc'"
            )
    h, w, c = (int(d) for d in example_shape)
    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)

    def sec_fwd(p, s, x):
        return _apply_running(cells[:split], p, s, x)

    xs = jax.ShapeDtypeStruct((1, h, w, c), dtype)
    with record_windowed_ops() as ops:
        feat = jax.eval_shape(
            sec_fwd, list(params[:split]), list(batch_stats[:split]), xs
        )
    if not hasattr(feat, "shape") or len(feat.shape) != 4:
        raise ValueError(
            "tiled inference needs an NHWC section output to stitch; the "
            f"section before cell {split} produced {feat!r} — move the "
            "split to the conv/pool stack's end"
        )
    fh, fw, fc = int(feat.shape[1]), int(feat.shape[2]), int(feat.shape[3])
    if fh <= 0 or fw <= 0 or h % fh or w % fw:
        raise ValueError(
            f"section output {fh}x{fw} does not divide the image {h}x{w} "
            "— tiled inference needs image extents divisible by the "
            "section's cumulative stride"
        )
    sh, sw = h // fh, w // fw
    mh, mw = section_margin(ops, (h, w))
    mh, mw = _round_up(mh, sh), _round_up(mw, sw)
    if tile is None:
        # Default core: a quarter of each extent (16 tiles/request),
        # stride-aligned — callers that care pick their own (or ask
        # `analyze memory-plan --bisect tile` for the largest that fits).
        tile = (max(sh, _round_up(h // 4, sh)), max(sw, _round_up(w // 4, sw)))
    th, tw = _pair(tile)
    if th < sh or tw < sw or th % sh or tw % sw:
        raise ValueError(
            f"tile {th}x{tw} must be a positive multiple of the section "
            f"stride {sh}x{sw}"
        )
    tiles_h, win_h = _axis_plan(h, th, mh)
    tiles_w, win_w = _axis_plan(w, tw, mw)
    return TileGeometry(
        image_hw=(h, w), tile_hw=(th, tw), margin_hw=(mh, mw),
        stride_hw=(sh, sw), window_hw=(win_h, win_w), feat_hw=(fh, fw),
        feat_channels=fc, feat_dtype=np.dtype(feat.dtype),
        split=split, ops=tuple(dict(o) for o in ops),
        tiles_h=tiles_h, tiles_w=tiles_w,
    )


class _TiledExecutable:
    """The compile_bucket handle of one tiled forward: the per-tile-bucket
    section executables plus the head. Duck-types the single executable
    the engine's footprint ledger and hlolint gate expect — both delegate
    to the LARGEST tile-bucket section program, because that is the hot
    loop whose peak bounds a request's memory (the head is recorded as
    its own ledger entry by the predictor)."""

    def __init__(self, tile: dict, head):
        self.tile = dict(tile)
        self.head = head
        self._lint = self.tile[max(self.tile)]

    def as_text(self) -> str:
        return self._lint.as_text()

    def memory_analysis(self):
        return self._lint.memory_analysis()


class TiledPredictor:
    """Compile/stage/run backend that serves one FIXED large example shape
    by streaming overlap-read tiles through a single AOT-warmed section
    executable and stitching exactly (module docstring has the math).

    cells / params / batch_stats: the calibrated plain-twin triple (the
        same artifacts the single-chip engine consumes).
    example_shape: the served ``(H, W, C)`` — the LARGE size; requests
        are validated against it by the engine as usual.
    tile: core tile extent in input px (int or ``(th, tw)``), a multiple
        of the section's cumulative stride. Bigger tiles amortize
        dispatch overhead, smaller ones bound memory —
        ``analyze memory-plan --bisect tile`` computes the largest that
        fits a chip.
    split: section/head cell boundary (default: everything but the last
        cell — the head the model builders emit).
    tile_batch: largest tile bucket; tile buckets are the powers of two
        up to it (``/predict_tiled``'s own buckets, orthogonal to the
        engine's per-IMAGE buckets, which default to 1). Default 1 —
        the EXACT path: every window runs the one batch-1 section
        executable, whose outputs are bit-identical to the monolithic
        forward (tier-1-asserted). Raising it batches windows per
        dispatch (a throughput lever for small tiles), at the repo's
        documented cross-executable boundary: rows computed by a
        batch-b program agree with the batch-1/monolithic program at
        f32 reduction-order tolerance, not bitwise (the same ~1e-7
        boundary as cross-BUCKET rows in the plain engine).
    """

    program = "serve_tiled"
    mesh_shape = (1, 1)
    #: Engine warm-up flag: while True, runs execute normally but are
    #: excluded from the per-request stats/metrics (zeros warm traffic
    #: must not skew the stitch/stream percentiles the reports carry).
    warming = False

    def __init__(
        self,
        cells: Sequence[Any],
        params: Sequence[Any],
        batch_stats,
        example_shape: Sequence[int],
        tile,
        split: "int | None" = None,
        tile_batch: int = 1,
        dtype=None,
    ):
        import jax
        import jax.numpy as jnp

        self.cells = tuple(cells)
        self.example_shape = tuple(int(d) for d in example_shape)
        self.dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
        self.geometry = tile_geometry(
            self.cells, params, batch_stats, self.example_shape, tile,
            split=split, dtype=self.dtype,
        )
        # The grid is FIXED per engine, so only the tile buckets a
        # request actually dispatches exist: full chunks of the largest
        # bucket plus one padded remainder bucket — at most two compiled
        # shapes, never the whole power-of-two ladder.
        pow2 = power_of_two_buckets(max(1, int(tile_batch)))
        full, rem = divmod(self.geometry.n_tiles, max(pow2))
        used = set()
        if full:
            used.add(max(pow2))
        if rem:
            used.add(bucket_for(rem, pow2))
        self._tile_buckets = tuple(sorted(used))
        self.device = jax.devices()[0]
        split = self.geometry.split
        # Params/stats live on the device once, pre-split so the section
        # and head executables take exactly their own halves.
        self._p_sec = jax.device_put(list(params[:split]), self.device)
        self._s_sec = jax.device_put(list(batch_stats[:split]), self.device)
        self._p_head = jax.device_put(list(params[split:]), self.device)
        self._s_head = jax.device_put(list(batch_stats[split:]), self.device)
        self._np_dtype = np.dtype(self.dtype.name)
        # Per-image-bucket cold-start aggregates (summed over the tile
        # section executables + head compiled for that bucket) — the
        # engine merges them into its own ledger entry for the handle.
        self.compile_timings: "dict[int, dict]" = {}
        # Telemetry bindings (engine seam: bind_telemetry).
        self._ledger = None
        self._m_tiles = self._m_batches = None
        self._m_stitch = self._m_stream = None
        self._lock = threading.Lock()
        self._requests = 0
        self._tiles_total = 0
        self._stitch_s: "list[float]" = []
        self._stream_s: "list[float]" = []
        self.last_run: "dict | None" = None

    # -- engine seam ----------------------------------------------------------

    @property
    def num_devices(self) -> int:
        return 1

    def halo_shifts(self) -> int:
        """One chip exchanges nothing over the wire — the tile overlap is
        an overlapped HOST read, invisible to the permute window."""
        return 0

    def bind_telemetry(self, registry=None, ledger=None, events=None) -> None:
        """Engine-injected observability (called before warm-up): the
        footprint ledger the tile/head executables are recorded into and
        the registry the ``tiled_*`` series publish through. ``events``
        is accepted for symmetry (per-request facts ride the engine's own
        ``serve.request`` span events via ``last_run``)."""
        del events
        self._ledger = ledger
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_tiles = telemetry.declare(registry, "tiled_tiles_total")
            self._m_batches = telemetry.declare(
                registry, "tiled_tile_batches_total"
            )
            self._m_stitch = telemetry.declare(
                registry, "tiled_stitch_seconds"
            )
            self._m_stream = telemetry.declare(
                registry, "tiled_tile_stream_seconds"
            )
            telemetry.declare(registry, "tiled_tiles_per_request").set(
                self.geometry.n_tiles
            )

    def compile_bucket(self, bucket: int):
        """AOT-compile the used tile-bucket section executables + the
        head for one image bucket and record every executable's
        compile-time footprint: the handle itself lands in the engine's
        ledger as ``serve_tiled[bucket]`` (the TILE executable's peak —
        the number ``memory_guard`` and ``analyze memory-plan`` gate
        on), the head as its own ``serve_tiled_head`` entry (its
        footprint scales with image/stride², the residual term of the
        bounded-memory claim). First-exec setup is paid by the engine's
        own warm-up pass, which streams the SAME buckets this grid
        dispatches — no extra zeros runs here, so a gigapixel engine's
        warm-up costs one pass, not two."""
        from mpi4dl_tpu.evaluate import aot_compile_tiled_predict

        g = self.geometry
        timings: dict = {}
        exe = aot_compile_tiled_predict(
            self.cells,
            list(self._p_sec) + list(self._p_head),
            list(self._s_sec) + list(self._s_head),
            g.split,
            (*g.window_hw, self.example_shape[2]),
            (*g.feat_hw, g.feat_channels),
            self._tile_buckets,
            dtype=self.dtype,
            feature_dtype=g.feat_dtype,
            timings=timings,
        )
        handle = _TiledExecutable(exe["tile"], exe["head"])
        if self._ledger is not None:
            for tb, compiled in sorted(handle.tile.items()):
                self._ledger.record_compiled(
                    "serve_tiled_tile", compiled, bucket=tb,
                    window=list(g.window_hw), **timings.get(tb, {}),
                )
            self._ledger.record_compiled(
                "serve_tiled_head", handle.head,
                feature_hw=list(g.feat_hw), **timings.get("head", {}),
            )
        # The engine's own ledger entry for this image bucket gets the
        # SUMMED trace/compile seconds of every executable compiled here
        # (the cost a cold respawn pays for this bucket; the per-
        # executable split lives in the serve_tiled_* entries above).
        # rollup=True keeps the sums out of the compile_seconds gauge and
        # the analyzer's totals — the serve_tiled_* entries already
        # carry every second once.
        self.compile_timings[int(bucket)] = {
            "trace_s": round(
                sum(t.get("trace_s", 0.0) for t in timings.values()), 6
            ),
            "compile_s": round(
                sum(t.get("compile_s", 0.0) for t in timings.values()), 6
            ),
            "rollup": True,
        }
        del bucket  # every image bucket shares the tile/head executables
        return handle

    def stage(self, batch):
        """No-op by design: the full image must NEVER land on the device —
        :meth:`run` slices overlap-read windows from the host array and
        stages only those (double-buffered)."""
        return np.asarray(batch, self._np_dtype)

    def run(self, compiled, staged):
        staged = np.asarray(staged, self._np_dtype)
        outs = [self._run_one(compiled, staged[i])
                for i in range(staged.shape[0])]
        return np.stack(outs)

    def expectations(self):
        """Algebra-derived: the tiled zero-collective delta composes to
        the single-chip gate — any collective in a tile executable is a
        resharding regression."""
        from mpi4dl_tpu.analysis.expectations import compose

        return compose(self.collective_deltas())

    def collective_deltas(self):
        """One tiled zero-collective section delta
        (:mod:`mpi4dl_tpu.analysis.expectations`)."""
        from mpi4dl_tpu.analysis.expectations import tiled_delta

        return (tiled_delta(),)

    def platform(self) -> str:
        return self.device.platform

    def limit_device(self):
        return self.device

    def param_tree(self):
        """``(params, batch_stats)`` live trees, for the numerics
        sentinel's integrity checksum (telemetry/canary.py). Rejoins
        the section/head split in cell order, so the checksum matches a
        single-chip replica of the same checkpoint."""
        return (
            list(self._p_sec) + list(self._p_head),
            list(self._s_sec) + list(self._s_head),
        )

    def reload_params(self, params) -> None:
        """Replace the live parameter lists, re-split at the geometry
        boundary. The tile/head executables take params as call
        arguments (not closure captures), so the swap takes effect on
        the next dispatch."""
        import jax

        split = self.geometry.split
        self._p_sec = jax.device_put(list(params[:split]), self.device)
        self._p_head = jax.device_put(list(params[split:]), self.device)

    # -- the tile-streaming hot loop ------------------------------------------

    def _run_one(self, handle: _TiledExecutable, img: np.ndarray):
        import jax

        g = self.geometry
        wh, ww = g.window_hw
        sh, sw = g.stride_hw
        c = img.shape[-1]
        max_b = max(self._tile_buckets)
        jobs = [(th, tw) for th in g.tiles_h for tw in g.tiles_w]
        feat = np.empty((*g.feat_hw, g.feat_channels), g.feat_dtype)
        t0 = time.perf_counter()
        stitch_s = 0.0
        batch_counts: "dict[int, int]" = {}
        pending = None  # the double-buffer: one (group, device_out) in flight
        for i in range(0, len(jobs), max_b):
            group = jobs[i: i + max_b]
            bucket = bucket_for(len(group), self._tile_buckets)
            batch = (
                np.zeros((bucket, wh, ww, c), self._np_dtype)
                if len(group) < bucket
                else np.empty((bucket, wh, ww, c), self._np_dtype)
            )
            for j, ((_, _, ha), (_, _, wa)) in enumerate(group):
                batch[j] = img[ha: ha + wh, wa: wa + ww, :]
            staged = jax.device_put(batch, self.device)    # async H2D
            out = handle.tile[bucket](self._p_sec, self._s_sec, staged)
            batch_counts[bucket] = batch_counts.get(bucket, 0) + 1
            if pending is not None:
                # Harvest batch k while batch k+1 transfers/computes —
                # the live set never exceeds two staged tile batches.
                stitch_s += self._harvest(feat, *pending)
            pending = (group, out)
        if pending is not None:
            stitch_s += self._harvest(feat, *pending)
        t1 = time.perf_counter()
        hstaged = jax.device_put(
            np.ascontiguousarray(feat[None]), self.device
        )
        logits = np.asarray(
            handle.head(self._p_head, self._s_head, hstaged)
        )[0]
        t2 = time.perf_counter()
        stream_s = (t1 - t0) - stitch_s
        stitch_s += t2 - t1  # stitch = assembly copies + the head forward
        facts = {
            "tiles": len(jobs),
            "tile_batches": sum(batch_counts.values()),
            "stitch_s": stitch_s,
            "tile_stream_s": stream_s,
        }
        if self.warming:
            return logits
        with self._lock:
            self._requests += 1
            self._tiles_total += len(jobs)
            self._stitch_s.append(stitch_s)
            self._stream_s.append(stream_s)
            if len(self._stitch_s) > 2048:
                del self._stitch_s[:1024]
                del self._stream_s[:1024]
            self.last_run = facts
        if self._m_tiles is not None:
            self._m_tiles.inc(len(jobs))
            for b, n in batch_counts.items():
                self._m_batches.inc(n, bucket=b)
            self._m_stitch.observe(stitch_s)
            self._m_stream.observe(stream_s)
        return logits

    def _harvest(self, feat: np.ndarray, group, out) -> float:
        """Block on one tile batch and stitch its cores into the feature
        map; returns the host-side assembly time (the D2H wait is stream
        time, not stitch time)."""
        g = self.geometry
        sh, sw = g.stride_hw
        arr = np.asarray(out)  # blocks until the device batch finishes
        t = time.perf_counter()
        for j, ((hc0, hlen, ha), (wc0, wlen, wa)) in enumerate(group):
            fh0, fw0 = hc0 // sh, wc0 // sw
            oh0, ow0 = (hc0 - ha) // sh, (wc0 - wa) // sw
            nh, nw = hlen // sh, wlen // sw
            feat[fh0: fh0 + nh, fw0: fw0 + nw] = (
                arr[j, oh0: oh0 + nh, ow0: ow0 + nw]
            )
        return time.perf_counter() - t

    # -- observability --------------------------------------------------------

    def run_stats(self) -> dict:
        """Cumulative tiled-run facts (``engine.stats()["tiled"]``, the
        loadgen/CLI report's ``tiled`` block): geometry, request/tile
        totals, and per-request stitch/stream latency percentiles."""
        from mpi4dl_tpu.profiling import percentiles

        with self._lock:
            out = {
                **self.geometry.describe(),
                "requests": self._requests,
                "tiles_total": self._tiles_total,
                "stitch_s": percentiles(list(self._stitch_s)),
                "tile_stream_s": percentiles(list(self._stream_s)),
            }
        return out


def tiled_engine(
    cells: Sequence[Any],
    params: Sequence[Any],
    batch_stats,
    example_shape: Sequence[int],
    tile,
    split: "int | None" = None,
    tile_batch: int = 1,
    dtype=None,
    slo_class: "str | None" = DEFAULT_TILED_CLASS,
    slo_threshold_s: "float | None" = DEFAULT_TILED_THRESHOLD_S,
    **engine_kw,
) -> ServingEngine:
    """A :class:`ServingEngine` over a :class:`TiledPredictor`: the
    ``/predict_tiled`` surface. Image buckets default to ``(1,)`` (one
    gigapixel image per dispatch — batching them would multiply the
    first request's latency and the live set for no occupancy win; the
    TILE buckets inside the predictor are where batching pays), the
    default deadline stretches to minutes, and the engine declares its
    own SLO class (default ``tiled`` with a latency objective) so the
    PR-11 scheduler accounts this traffic's burn separately from any
    tight interactive class."""
    predictor = TiledPredictor(
        cells, params, batch_stats, example_shape, tile,
        split=split, tile_batch=tile_batch, dtype=dtype,
    )
    engine_kw.setdefault("buckets", (1,))
    engine_kw.setdefault("default_deadline_s", 600.0)
    if slo_class and engine_kw.get("slo_classes") is None:
        from mpi4dl_tpu.serve.scheduler import SLOClass

        engine_kw["slo_classes"] = (
            SLOClass(slo_class, latency_threshold_s=slo_threshold_s),
        )
    return ServingEngine.from_predictor(predictor, **engine_kw)


def tiled_engine_from_checkpoint(
    path_or_dir: str, tile, **engine_kw
) -> ServingEngine:
    """Tiled engine from a self-describing checkpoint path alone — the
    gigapixel twin of ``ServingEngine.from_checkpoint``: same rebuild, but
    the forward streams tiles instead of requiring the whole image (plus
    its activations) to fit the chip."""
    from mpi4dl_tpu.checkpoint import rebuild_from_checkpoint

    cells, state, stats, meta = rebuild_from_checkpoint(path_or_dir)
    if stats is None:
        raise ValueError(
            "checkpoint has no batch_stats.msgpack — calibrate with "
            "evaluate.collect_batch_stats and save_checkpoint(..., "
            "batch_stats=...) before serving"
        )
    spec = meta["model"]
    shape = (spec["image_size"], spec["image_size"], spec.get("channels", 3))
    engine_kw.setdefault("dtype", spec.get("dtype", "float32"))
    return tiled_engine(
        cells, state.params, stats, example_shape=shape, tile=tile,
        **engine_kw,
    )


def synthetic_tiled_engine(
    image_size: int,
    tile,
    depth: int = 8,
    num_classes: int = 10,
    calib_size: "int | None" = None,
    calib_batches: int = 1,
    seed: int = 0,
    **engine_kw,
) -> ServingEngine:
    """Zero-artifact tiled engine: a ResNet-v1 (depth 6n+2) with a
    global-average-pool head served at ``image_size``. Because the pooled
    head input is size-independent (the pool covers the whole feature
    map), parameters are initialized and BN-calibrated at a SMALL twin of
    the model (``calib_size``, default 64 px) — identical parameter tree,
    no need to run a full-image forward just to mint synthetic weights —
    then served at the large size through the tile stream."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.partition import init_cells

    size = int(image_size)
    small = int(calib_size) if calib_size else min(64, size)
    # pool_kernel = size // 4 pools the WHOLE post-stack feature map in
    # both twins, so the head's Dense sees the same flattened width and
    # the two builds share one parameter structure.
    cells = get_resnet_v1(
        depth=depth, num_classes=num_classes, pool_kernel=size // 4
    )
    twin = get_resnet_v1(
        depth=depth, num_classes=num_classes, pool_kernel=small // 4
    )
    rng = np.random.default_rng(seed)
    params = init_cells(
        twin, jax.random.PRNGKey(seed), jnp.zeros((1, small, small, 3))
    )
    cal = [
        jnp.asarray(rng.standard_normal((4, small, small, 3)), jnp.float32)
        for _ in range(max(1, int(calib_batches)))
    ]
    stats = collect_batch_stats(twin, params, cal)
    return tiled_engine(
        cells, params, stats, example_shape=(size, size, 3), tile=tile,
        **engine_kw,
    )
