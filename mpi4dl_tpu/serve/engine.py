"""Online serving engine: AOT warm-up + dynamic micro-batching request loop.

Design (the T3/FLUX lesson applied to single-chip inference — overlap data
movement with compute, and never let the hot loop pay a compile):

- **AOT warm-up.** At construction every configured batch bucket is lowered
  and compiled through :func:`mpi4dl_tpu.evaluate.aot_compile_predict`, then
  executed once on zeros. After warm-up the loop only ever *calls*
  ``jax.stages.Compiled`` executables, which structurally cannot trace or
  recompile — the no-surprise-JIT guarantee is an object-capability fact,
  not a convention, and :meth:`ServingEngine.assert_warm` checks every
  bucket has its executable before the loop starts.
- **Admission control.** The request queue is bounded per SLO class; a
  full class queue rejects at ``submit`` (:class:`QueueFullError`, with
  the class and a per-class retry hint) instead of building unbounded
  latency. Per-request deadlines are enforced three times: a deadline
  already expired at ``submit`` is rejected before it occupies a queue
  slot, requests expired at batch-formation time are rejected without
  being served, and a result that lands past its deadline is delivered
  as :class:`DeadlineExceededError`, never silently late.
- **Continuous batching + SLO-class EDF scheduling**
  (:mod:`mpi4dl_tpu.serve.scheduler`). The queue is partitioned by named
  SLO classes (``slo_classes=`` / ``submit(slo_class=)``), each class a
  latency :class:`~mpi4dl_tpu.telemetry.slo.Objective` over
  ``serve_class_latency_seconds{slo_class=}``; the batch former pops in
  earliest-deadline-first order across classes the moment the device can
  accept work — a new arrival joins the next dispatch instead of waiting
  out a window, and a tight-deadline request jumps bulk traffic by
  construction. The per-class ``slo_burn_rate`` gauges feed back into
  the scheduler: while a class burns its budget hot, classes burning
  slowest are deprioritized and shed early. ``scheduler="fifo"`` keeps
  the PR-2 windowed former (pop first, collect up to ``max_batch`` or
  ``max_wait_s``) as the measured A/B baseline. Either way the batch is
  right-padded into the smallest power-of-two bucket
  (:mod:`mpi4dl_tpu.serve.batching`).
- **Split/re-join.** A multi-image submission — ``(n, *example_shape)``,
  any ``n`` — is split into per-image requests at admission (atomically:
  all admitted or none) and re-joined in order into one ``(n, classes)``
  result, so a request larger than the largest compiled bucket is the
  engine's problem, not the caller's. Rows ride the same class queue
  with one shared deadline and trace id, and are bit-identical to the
  per-bucket forwards they split into.
- **Double-buffered staging.** The loop stages batch *k+1* host→device
  (``jax.device_put``) and dispatches its executable — both asynchronous —
  *before* blocking on batch *k*'s results, so the next batch's transfer
  and the host-side batch formation overlap the current batch's device
  compute. One batch is in flight at all times under load.

Thread model: clients call :meth:`submit` from any thread (it only touches
the bounded queue); a single batcher thread owns all JAX dispatch.

Telemetry (:mod:`mpi4dl_tpu.telemetry`, docs/OBSERVABILITY.md): every
request's lifecycle is traced as contiguous spans — ``queue_wait`` →
``batch_form`` → ``h2d_stage`` → ``device_compute`` — whose durations sum
exactly to its end-to-end latency; outcomes, queue depth, per-bucket
dispatch counts/occupancy, and the pad-waste ratio land in a metrics
registry. ``metrics_port=`` serves the registry as a Prometheus scrape
endpoint; ``telemetry_dir=`` (or ``MPI4DL_TPU_TELEMETRY_DIR``) appends
span events to a JSONL log. Both are opt-in; the registry itself is
always on (a few lock-guarded float adds per request — batched throughput
measured flat within ±1.5% noise across all telemetry arms; the overhead
table in docs/OBSERVABILITY.md).

Liveness + postmortem (docs/OBSERVABILITY.md "Flight recorder" / "Health
endpoints"): a :class:`telemetry.Watchdog` trips when requests are
outstanding but none completes within ``watchdog_factor`` × the rolling
p99 e2e latency (seeded with the AOT warm latency), flipping
:attr:`health` — served as ``/healthz`` 200→503 on the metrics port —
and dumping the always-on :class:`telemetry.FlightRecorder` ring (recent
span events + rate-limited metric snapshots) as schema-valid JSONL; the
batcher crashing or a SIGTERM (``serve.__main__``) dumps it too.
``/debugz`` serves the flight-recorder tail, watchdog state, and the
latest trace-attribution summary live.

Tail forensics (docs/OBSERVABILITY.md "Tail forensics"): the e2e and
span histograms record per-bucket exemplar trace ids (OpenMetrics
``# {trace_id=...}`` on ``/metrics``), and a
:class:`telemetry.TailWatcher` captures served requests slower than
``max(SLO latency threshold, tail_factor x rolling p99)`` as
rate-limited ``tail.sample`` events — full span phases plus the queue
depth at admission, bucket/batch/pad-waste, dispatch seq, watchdog
state, and latest attribution — joined per trace id by
``python -m mpi4dl_tpu.analyze tail``.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Any, Sequence

import numpy as np

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.profiling import annotate_step, percentiles
from mpi4dl_tpu.telemetry import coldstart
from mpi4dl_tpu.serve.batching import bucket_for, pad_batch, power_of_two_buckets
from mpi4dl_tpu.serve.scheduler import (
    ClassFeedback,
    ClassScheduler,
    SchedulerFull,
    normalize_classes,
)
from mpi4dl_tpu.tenancy.model import (
    QuotaExceededError,
    TenantAdmission,
    normalize_tenants,
)


class QueueFullError(RuntimeError):
    """Admission control: the bounded request queue is full.

    retry_after_s: advisory backoff hint derived from the live batch
        cadence (one batch drains up to ``max_batch`` queue slots per
        period, so a slot frees within roughly one period), scaled by
        the rejected class's own backlog — a client that waits this
        long before retrying lands when room plausibly exists instead
        of hammering a full queue. None when the engine has no cadence
        estimate yet (nothing served).
    slo_class: the class whose queue rejected the admission (None from
        publishers without classes, e.g. the pre-class router bound).
    shed: True when the rejection was an early burn-rate-feedback shed
        (the class was deprioritized), not a physically full queue."""

    def __init__(self, msg: str, retry_after_s: "float | None" = None,
                 slo_class: "str | None" = None, shed: bool = False):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.slo_class = slo_class
        self.shed = shed


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before a result could be delivered."""


class DrainedError(RuntimeError):
    """The request was flushed by a deliberate stop/drain — an
    operator- or router-initiated lifecycle event, not a serving
    failure. Counted as ``outcome="drained"`` (excluded from the
    availability SLO) so a fleet scale-down does not burn error budget;
    a router catching this requeues the request on a survivor."""


@dataclasses.dataclass
class _Request:
    x: np.ndarray
    submit_t: float
    deadline: float
    future: Future
    trace_id: str = ""
    slo_class: str = "default"
    # The admitted tenant (tenancy subsystem) — "default" when tenancy
    # is off, so every label/series below stays single-valued.
    tenant: str = "default"
    # Span boundaries (time.monotonic), filled in as the request moves:
    # picked by the batch former / batch complete / staged+dispatched.
    form_t: float = 0.0
    formed_t: float = 0.0
    staged_t: float = 0.0
    # Tail-forensics context: the queue depth this request saw at
    # admission and the dispatch sequence of the batch that served it —
    # a tail.sample must say what the system looked like around the
    # slow request, not just how slow it was.
    queue_depth_at_submit: int = 0
    dispatch_seq: int = -1
    # Split/re-join: the shared join a multi-image submission's rows
    # resolve into, and this row's index in it.
    join: "_Join | None" = None
    row: int = 0
    # Tiled-forward facts of the dispatch that served this request
    # (tile count, stitch/stream seconds — serve/tiled.py), riding the
    # serve.request span event so `analyze tail`/trace-export see them.
    tiled: "dict | None" = None
    # Numerics-sentinel probe (telemetry/canary.py): rides the real
    # queue/batch/dispatch path but is excluded from availability/SLO/
    # tenant accounting (outcome "canary", like "drained") and its
    # completion is verified against the warm-up reference digest.
    canary: bool = False


class _Join:
    """Re-join of one split multi-image submission: collects per-row
    logits in submission order and resolves the caller's single Future
    once every row lands — or fails it with the FIRST row failure
    (deadline/crash), after which late rows are no-ops."""

    def __init__(self, n: int, future: Future, trace_id: str,
                 submit_t: float):
        self.future = future
        self.trace_id = trace_id
        self.submit_t = submit_t
        self._rows: "list" = [None] * n
        self._remaining = n
        self._failed = False
        self._lock = threading.Lock()

    def row_done(self, row: int, logits, now: float) -> None:
        with self._lock:
            if self._failed:
                return
            self._rows[row] = logits
            self._remaining -= 1
            done = self._remaining == 0
        if done:
            self.future.trace_id = self.trace_id
            self.future.e2e_latency_s = now - self.submit_t
            self.future.set_result(np.stack(self._rows))

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._failed:
                return
            self._failed = True
        self.future.trace_id = self.trace_id
        self.future.set_exception(exc)


class SingleChipPredictor:
    """The engine's compile/stage/run backend for the default one-chip
    replica: every bucket compiles to a one-device executable with zero
    collectives, and the hlolint gate it derives
    (``Expectations(single_chip=True)``) enforces exactly that.

    This is the seam the multi-chip path plugs into
    (:class:`mpi4dl_tpu.serve.sharded.ShardedPredictor`): the engine's
    batcher/scheduler/telemetry stack talks only to this interface —
    ``compile_bucket`` / ``stage`` / ``run`` / ``expectations`` — so
    sharding the forward never touches the host-side hot loop."""

    program = "serve_predict"
    mesh_shape = (1, 1)

    def __init__(self, cells, params, batch_stats, example_shape, dtype):
        import jax

        self.cells = tuple(cells)
        self.example_shape = tuple(int(d) for d in example_shape)
        self.dtype = dtype
        self.device = jax.devices()[0]
        # Params/stats live on the device once; per-request traffic is
        # the input batch only.
        self.params = jax.device_put(params, self.device)
        self.stats = jax.device_put(batch_stats, self.device)
        # Per-bucket cold-start facts (trace_s/compile_s/fingerprint)
        # from the last compile_bucket — the engine merges them into the
        # footprint ledger entry it records for the same executable.
        self.compile_timings: "dict[int, dict]" = {}

    @property
    def num_devices(self) -> int:
        return 1

    def halo_shifts(self) -> int:
        """Forward halo-shift permutes in the serving forward (the
        partition-math input of the sharded lint window): a one-chip
        program exchanges nothing."""
        return 0

    def compile_bucket(self, bucket: int):
        from mpi4dl_tpu.evaluate import aot_compile_predict

        timings: dict = {}
        out = aot_compile_predict(
            self.cells, self.params, self.stats, self.example_shape,
            [bucket], dtype=self.dtype, timings=timings,
        )[bucket]
        self.compile_timings[bucket] = timings.get(bucket, {})
        return out

    def stage(self, batch):
        """Async host→device transfer of one padded batch."""
        import jax

        return jax.device_put(batch, self.device)

    def run(self, compiled, staged):
        """Dispatch one pre-compiled bucket executable (async). Accepts
        an un-staged host batch too (the synchronous predict_one path)."""
        if isinstance(staged, np.ndarray):
            staged = self.stage(staged)
        return compiled(self.params, self.stats, staged)

    def expectations(self):
        """Algebra-derived hlolint expectations: the single-chip
        zero-collective delta composes to a gate where ANY collective in
        the compiled forward is a resharding regression."""
        from mpi4dl_tpu.analysis.expectations import compose

        return compose(self.collective_deltas())

    def collective_deltas(self):
        """One chip → one zero-collective layer delta
        (:mod:`mpi4dl_tpu.analysis.expectations`)."""
        from mpi4dl_tpu.analysis.expectations import single_chip_delta

        return (single_chip_delta(),)

    def platform(self) -> str:
        return self.device.platform

    def limit_device(self):
        """The device whose memory limit bounds one bucket's footprint
        (per-chip share: the guard compares against ONE device even on a
        multi-chip mesh)."""
        return self.device

    def param_tree(self):
        """``(params, batch_stats)`` live trees, for the numerics
        sentinel's integrity checksum (telemetry/canary.py)."""
        return self.params, self.stats

    def reload_params(self, params) -> None:
        """Replace the live parameter tree. ``run`` passes
        ``self.params`` on every call, so the swap takes effect on the
        next dispatch — the corrupt-drill hook rides this."""
        import jax

        self.params = jax.device_put(params, self.device)


class ServingEngine:
    """Serves single-example requests through pre-compiled bucketed
    frozen-stats forwards of a calibrated model.

    cells/params/batch_stats: the :mod:`mpi4dl_tpu.evaluate` triple (plain
        cell list, its params, calibrated BN stats).
    example_shape: per-request input shape, e.g. ``(H, W, 3)``.
    max_batch: largest micro-batch; buckets default to
        ``(1, 2, ..., max_batch)`` powers of two.
    max_wait_s: batch-formation window after the first queued request.
    max_queue: admission-control bound on waiting requests.
    default_deadline_s: per-request deadline when ``submit`` gives none.
    registry: a shared :class:`telemetry.MetricsRegistry`; None creates a
        private one (exposed as :attr:`registry`).
    metrics_port: serve the registry as a Prometheus ``/metrics`` endpoint
        on this port (0 = ephemeral; bound port on :attr:`metrics_port`),
        plus ``/healthz`` (200/503 from :attr:`health`) and ``/debugz``
        (flight tail + watchdog state + latest attribution).
        None (default) starts no server.
    telemetry_dir: JSONL span-event log directory; None falls back to
        ``MPI4DL_TPU_TELEMETRY_DIR``, unset disables.
    watchdog_factor: trip the stalled-loop watchdog when no request
        completes within ``factor`` × rolling p99 e2e latency (floored at
        ``watchdog_min_timeout_s``) while work is outstanding; None or 0
        disables the watchdog.
    flight_capacity: flight-recorder ring size in events (0 disables).
    flight_dir: where watchdog/crash dumps land; defaults to the
        telemetry dir, then ``MPI4DL_TPU_TELEMETRY_DIR``, then the
        system temp dir.
    slo: a :class:`telemetry.SLOConfig` — declarative availability /
        latency objectives. When set (with at least one objective), a
        daemon :class:`telemetry.SLOEvaluator` snapshots the registry
        every ``interval_s``, computes multi-window burn rates, runs the
        ``pending → firing → resolved`` alert machines (transitions land
        in the JSONL log and the flight ring; latency transitions carry
        a phase-attribution payload naming the span phase whose share
        grew), drives the advisory autoscaler, and serves it all on
        ``/alertz`` (:attr:`slo`). None (default) runs no evaluator.
    attribution_every: sampled continuous attribution — every N
        dispatches, run ONE batch synchronously under a private XProf
        capture and publish the parsed device-time attribution as the
        live ``trace_*`` gauges (``program="serve_sampled"``), turning
        the one-shot ``--trace-dir`` report into a continuously
        refreshed signal (T3's track-and-trigger, applied to serving).
        The sampled batch loses its double-buffering overlap; everything
        between samples runs the normal async path. None/0 disables.
    attribution_min_interval_s: floor between samples. A capture costs
        ~100 ms of profiler start/stop + parse regardless of batch size
        (measured on this container's CPU backend), so the dispatch
        cadence alone would let a high-rps workload burn arbitrary time
        in sampling; the time floor caps the amortized overhead at
        roughly ``capture_cost / interval`` (~0.3% at the 30 s default)
        no matter the request rate. The profiler backend's one-time
        ~3 s init is paid at construction (with a throwaway capture),
        never by a live request.
    memory_monitor: sample ``jax.Device.memory_stats()`` into the
        ``device_hbm_*`` gauges at the SLO-evaluator cadence
        (:class:`telemetry.MemoryMonitor`; docs/OBSERVABILITY.md
        "Memory"). Backends without stats (CPU) publish nothing and the
        sampler retires itself — absent-not-wrong.
    memory_guard: opt-in admission guard: a bucket whose footprint-
        ledger predicted peak exceeds the device limit — or whose
        compile dies on RESOURCE_EXHAUSTED — is refused at warm-up
        (recorded in :attr:`refused_buckets` / ``stats()["memory"]``)
        instead of crashing the engine; serving degrades to the buckets
        that fit.
    memory_limit_bytes: explicit device-capacity override for the guard
        and ``stats()["memory"]``; None reads the device's
        ``memory_stats()`` limit (absent on CPU → the guard's peak
        check is skipped, compile-OOM refusal still applies).
    tail_factor / tail_min_interval_s / tail_capacity: the slow-request
        watcher (:class:`telemetry.TailWatcher`; docs/OBSERVABILITY.md
        "Tail forensics"): a served request whose e2e latency exceeds
        ``max(SLO latency threshold, tail_factor x rolling p99)`` is
        captured — at most one per ``tail_min_interval_s`` — as a
        ``tail.sample`` event (full span phases, queue depth at
        admission, bucket/batch/pad-waste, dispatch seq, watchdog
        state, latest attribution) into the JSONL log, the flight
        ring, and a ``tail_capacity``-bounded ring on ``/debugz``.
        ``tail_capacity=0`` disables capture (the A/B-overhead arm).
    slo_classes: named SLO classes partitioning the admission queue
        (:mod:`mpi4dl_tpu.serve.scheduler`): a spec string
        (``"tight=50ms:99.9@200ms,bulk=2s"``), a sequence of
        :class:`~mpi4dl_tpu.serve.SLOClass`, or None for the implicit
        single ``default`` class. Each class with a threshold becomes a
        latency objective over ``serve_class_latency_seconds`` — the
        SLO evaluator runs whenever any class declares one, even
        without ``slo=`` — and its published burn rate steers the
        scheduler's deprioritize/shed feedback. Unclassed submissions
        land in the class named ``default`` when present, else the
        LAST configured class.
    predictor: the compile/stage/run backend for the serving forward.
        None (default) builds a :class:`SingleChipPredictor` from
        cells/params/batch_stats; a
        :class:`~mpi4dl_tpu.serve.sharded.ShardedPredictor` runs every
        bucket as a spatially-partitioned ``shard_map`` forward over a
        ``tile_h×tile_w`` mesh instead (docs/SERVING.md "Multi-chip
        sharded serving"). With a predictor, cells/params/batch_stats
        are ignored — use :meth:`from_predictor`. The hlolint gate,
        footprint ledger, and memory guard all derive from the
        predictor (mesh-derived expectations, per-chip share).
    scheduler: ``"edf"`` (default) — the continuous scheduler:
        deadline-ordered dispatch across class queues, in-flight
        re-admission (no formation window), burn-rate feedback.
        ``"fifo"`` — the PR-2 max-wait/max-size windowed former,
        retained as the measured A/B baseline (bench.py ``sched_ab``).
    shed_ratio: fraction of a class's queue bound at which a
        DEPRIORITIZED class starts shedding admissions early.
    canary_interval_s: numerics-sentinel cadence
        (:mod:`mpi4dl_tpu.telemetry.canary`; docs/OBSERVABILITY.md
        "Numerics"): every interval a daemon injects the deterministic
        golden probe through the REAL dispatch path (outcome
        ``canary`` — excluded from availability/SLO/tenant accounting
        like ``drained``) and verifies the answer against the per-
        bucket reference digest recorded at warm-up, then re-audits
        the :func:`~mpi4dl_tpu.telemetry.canary.params_checksum`
        against its load-time value. A divergence emits the
        ``canary.failure`` event and fires :attr:`canary` callbacks
        (the fleet worker fences itself). None (default) still records
        references + the load checksum — :meth:`inject_canary` and
        :meth:`params_checksum` work on demand — but runs no daemon.
    canary_seed: probe-derivation seed. Model-level: every replica of
        one model must share it, or federation cannot compare their
        canary digests.
    """

    def __init__(
        self,
        cells: Sequence[Any],
        params: Sequence[Any],
        batch_stats,
        example_shape: Sequence[int],
        dtype=None,
        max_batch: int = 8,
        buckets: Sequence[int] | None = None,
        max_wait_s: float = 0.002,
        max_queue: int = 64,
        default_deadline_s: float = 1.0,
        registry=None,
        metrics_port: "int | None" = None,
        telemetry_dir: "str | None" = None,
        watchdog_factor: "float | None" = 20.0,
        watchdog_min_timeout_s: float = 2.0,
        flight_capacity: int = 512,
        flight_dir: "str | None" = None,
        slo=None,
        attribution_every: "int | None" = None,
        attribution_min_interval_s: float = 30.0,
        memory_monitor: bool = True,
        memory_guard: bool = False,
        memory_limit_bytes: "int | None" = None,
        tail_factor: float = 4.0,
        tail_min_interval_s: float = 1.0,
        tail_capacity: int = 64,
        slo_classes=None,
        scheduler: str = "edf",
        shed_ratio: float = 0.5,
        tenants=None,
        predictor=None,
        canary_interval_s: "float | None" = None,
        canary_seed: int = 0,
    ):
        import jax.numpy as jnp

        from mpi4dl_tpu.telemetry import memory as memobs

        dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
        self._np_dtype = np.dtype(dtype.name)
        self.example_shape = tuple(int(d) for d in example_shape)
        self._buckets = (
            tuple(sorted({int(b) for b in buckets}))
            if buckets is not None
            else power_of_two_buckets(max_batch)
        )
        self._max_wait_s = float(max_wait_s)
        self._default_deadline_s = float(default_deadline_s)
        self._classes = normalize_classes(slo_classes)
        # Tenancy (mpi4dl_tpu/tenancy): None = OFF (everything runs as
        # the implicit "default" tenant — identical label values and
        # behavior to the pre-tenancy engine). ON = token-bucket quota
        # admission in submit(), deficit-weighted-round-robin fill in
        # the scheduler, and a `tenant` label on every per-class series.
        self._tenants = normalize_tenants(tenants)
        # Per-class latency objectives, per tenant allowed on the class
        # when tenancy is ON (windows match label sets exactly, so each
        # (class, tenant) series needs its own fully-selected objective;
        # burn protection is then scoped to the burning tenant alone).
        _obj_tenants = (
            [t for t in self._tenants] if self._tenants is not None
            else [None]
        )
        self._class_objectives = []
        for c in self._classes:
            for t in _obj_tenants:
                if t is not None and t.classes and c.name not in t.classes:
                    continue
                o = c.objective(tenant=t.name if t is not None else "default")
                if o is not None:
                    self._class_objectives.append(o)
        # The compile/stage/run backend: single-chip by default, or an
        # injected mesh-aware predictor (serve/sharded.py) — the batcher,
        # scheduler, and telemetry above never see the difference.
        if predictor is None:
            predictor = SingleChipPredictor(
                cells, params, batch_stats, self.example_shape, dtype
            )
        self._predictor = predictor

        # The registry (and the memory machinery reading/writing it)
        # exists BEFORE warm-up: the footprint ledger records each
        # bucket's predicted peak at compile time, and the admission
        # guard consults it before anything executes.
        self.registry = (
            registry if registry is not None else telemetry.MetricsRegistry()
        )
        self._events = telemetry.JsonlWriter(telemetry_dir)
        self.memory_ledger = memobs.FootprintLedger(registry=self.registry)
        self.memory_monitor: "memobs.MemoryMonitor | None" = (
            memobs.MemoryMonitor(
                self.registry,
                interval_s=(
                    slo.interval_s
                    if slo is not None and getattr(slo, "interval_s", None)
                    else 1.0
                ),
            )
            if memory_monitor
            else None
        )
        self._memory_limit = (
            int(memory_limit_bytes)
            if memory_limit_bytes is not None
            else memobs.device_memory_limit(self._predictor.limit_device())
        )
        self.refused_buckets: "dict[int, dict]" = {}
        telemetry.declare(self.registry, "oom_reports_total")
        # Predictor observability seam: a predictor that wants the
        # engine's ledger/registry/event log (the tiled predictor records
        # its tile + head executables and publishes tiled_* series) binds
        # them here, BEFORE warm-up compiles anything.
        bind = getattr(self._predictor, "bind_telemetry", None)
        if bind is not None:
            bind(
                registry=self.registry, ledger=self.memory_ledger,
                events=self._events,
            )

        # Numerics sentinel (telemetry/canary.py): state exists BEFORE
        # warm-up so the zeros loop below can record each bucket's
        # golden-probe reference digest right after its first execute.
        # The probe input derives from MODEL facts only (shape, dtype,
        # seed) — every replica of one model computes the same canary.
        self._canary_interval_s = (
            float(canary_interval_s)
            if canary_interval_s is not None and float(canary_interval_s) > 0
            else None
        )
        self.canary = telemetry.CanaryState(
            registry=self.registry,
            events=self._events,
            atol=telemetry.CANARY_ATOL,
            device=str(self._predictor.limit_device()),
            program=self._predictor.program,
        )
        self._canary_x = telemetry.canary_example(
            self.example_shape, self._np_dtype, seed=canary_seed
        )

        # AOT warm-up: compile every bucket now, then run each once so the
        # first real request pays neither a compile nor a first-exec setup.
        # With the opt-in admission guard, a bucket whose predicted peak
        # (footprint ledger, known at compile time) exceeds the device
        # limit — or whose compile itself dies on RESOURCE_EXHAUSTED —
        # is REFUSED instead of crashing the engine: graceful degradation
        # to the buckets that fit.
        self._compiled = {}
        self.warm_latency_s: dict[int, float] = {}
        _warmup_t0 = time.perf_counter()
        for b in self._buckets:
            try:
                compiled = self._predictor.compile_bucket(b)
            except Exception as e:  # noqa: BLE001 — compile-time OOM is a
                # memory fact about the bucket, not an engine defect
                if memory_guard and memobs.is_oom_error(e):
                    self._refuse_bucket(b, "compile_oom", error=e)
                    continue
                memobs.emit_oom_report(
                    e, program=self._predictor.program, bucket=b,
                    registry=self.registry, events=self._events,
                )
                raise
            # Cold-start facts measured inside compile_bucket (trace/
            # compile split + the lowered program's fingerprint) ride the
            # same ledger entry as the executable's predicted peak.
            cold = getattr(self._predictor, "compile_timings", {}).get(b, {})
            entry = self.memory_ledger.record_compiled(
                self._predictor.program, compiled, bucket=b, **cold
            )
            peak = entry.get("peak_bytes")
            if (
                memory_guard
                and self._memory_limit is not None
                and peak is not None
                and peak > self._memory_limit
            ):
                self._refuse_bucket(
                    b, "predicted_peak_exceeds_limit",
                    peak_bytes=peak, limit_bytes=self._memory_limit,
                )
                continue
            self._compiled[b] = compiled
        if not self._compiled:
            raise RuntimeError(
                f"no serving bucket fits: every configured bucket "
                f"{list(self._buckets)} was refused "
                f"({ {b: r['reason'] for b, r in self.refused_buckets.items()} })"
            )
        self._buckets = tuple(sorted(self._compiled))
        self._max_batch = max(self._buckets)
        # Predictors that publish per-run stats (tiled) must not count
        # the warm-up zeros runs as served traffic.
        if hasattr(self._predictor, "warming"):
            self._predictor.warming = True
        for b in self._buckets:
            z = np.zeros((b, *self.example_shape), self._np_dtype)
            t0 = time.perf_counter()
            np.asarray(self._predictor.run(self._compiled[b], z))
            self.warm_latency_s[b] = time.perf_counter() - t0
            # First-execute setup is the third cold-start phase: merge it
            # into the bucket's ledger entry next to trace_s/compile_s.
            self.memory_ledger.annotate(
                self._predictor.program, bucket=b,
                warm_s=round(self.warm_latency_s[b], 6),
            )
            # Golden-probe reference: the canary padded into this bucket,
            # row 0 of the answer is the ground truth every later sentinel
            # probe is verified against. Recorded inside the warming
            # region (predictor per-run stats must not count it) and
            # annotated into the SAME ledger entry as the executable
            # fingerprint, so the exact-vs-quantized digest semantics
            # stay attributable to the binary that produced them.
            ref_row = np.asarray(
                self._predictor.run(
                    self._compiled[b],
                    pad_batch([self._canary_x], b, self._np_dtype),
                )
            )[0]
            _entry = self.memory_ledger.get(
                self._predictor.program, bucket=b
            ) or {}
            rec = self.canary.record_reference(
                b, ref_row, fingerprint=_entry.get("fingerprint")
            )
            self.memory_ledger.annotate(
                self._predictor.program, bucket=b,
                canary_digest=rec["digest"],
                canary_qdigest=rec["qdigest"],
            )
        if hasattr(self._predictor, "warming"):
            self._predictor.warming = False
        self.warmup_wall_s = time.perf_counter() - _warmup_t0
        self.assert_warm()
        # Load-time parameter-integrity baseline: every later checksum
        # audit (sentinel cadence, /healthz, federation skew comparison)
        # is judged against this value.
        self.canary.record_checksum(self.params_checksum(), load=True)

        # The continuous scheduler (or the fifo baseline): per-class
        # bounded EDF queues + the batch former. Burn-rate feedback only
        # exists when there is more than one class AND at least one
        # class declares an objective — otherwise there is nothing to
        # protect and nothing to read.
        feedback = (
            ClassFeedback(self.registry, self._classes)
            if len(self._classes) > 1 and self._class_objectives
            else None
        )
        # Quota admission (tenancy ON): token buckets refilled at each
        # tenant's configured rate, consulted in submit() BEFORE any
        # queue slot is occupied — an over-quota flood is shed with a
        # refill-derived retry hint instead of crowding other tenants
        # out of the bounded queues. None when tenancy is off.
        self._admission = (
            TenantAdmission(self._tenants, registry=self.registry)
            if self._tenants is not None
            else None
        )
        self._sched = ClassScheduler(
            self._classes, max_queue=max_queue, registry=self.registry,
            mode=scheduler, feedback=feedback, shed_ratio=shed_ratio,
            tenants=self._tenants,
        )
        self._poll_s = 0.02
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._counts = {
            "submitted": 0,
            "rejected_queue_full": 0,
            "rejected_quota": 0,
            "rejected_deadline": 0,
            "served": 0,
            "served_late": 0,
            "drained": 0,
            "canary": 0,
            "batches": 0,
            "batched_examples": 0,
        }
        # Batch-completion cadence (EMA of the gap between completed
        # batches) — the QueueFullError.retry_after_s hint's source.
        self._batch_period_ema: "float | None" = None
        self._last_complete_t: "float | None" = None
        self._latencies: list[float] = []
        self._bucket_dispatches: dict[int, int] = {b: 0 for b in self._buckets}
        self._padded_rows = 0
        self._total_rows = 0
        self._batch_seq = 0

        # -- telemetry surface (docs/OBSERVABILITY.md) ----------------------
        # (registry + event writer already exist — created before warm-up
        # so the memory machinery could use them.)
        decl = lambda name: telemetry.declare(self.registry, name)  # noqa: E731
        self._m_submitted = decl("serve_submitted_total")
        self._m_requests = decl("serve_requests_total")
        self._m_batches = decl("serve_batches_total")
        self._m_occupancy = decl("serve_batch_occupancy")
        self._m_pad_waste = decl("serve_pad_waste_ratio")
        self._m_latency = decl("serve_request_latency_seconds")
        # Per-class e2e latency: the series the per-class latency
        # objectives (and the scheduler's burn feedback) read. The
        # queue-depth gauges (total + per-class) are owned by the
        # scheduler, which already declared them above.
        self._m_class_latency = decl("serve_class_latency_seconds")
        # The tenancy series exist with or without configured tenants
        # (the catalog pin: one engine exposes exactly the catalog);
        # with tenancy off they simply never move off their zeros.
        decl("tenant_quota_tokens")
        decl("tenant_quota_sheds_total")
        decl("tenant_admitted_total")
        self._m_spans = decl("serve_span_seconds")
        self._m_phase_share = decl("serve_phase_share")
        self._phase_totals: dict[str, float] = {}
        self._attr_every = int(attribution_every or 0)
        self._attr_min_interval_s = float(attribution_min_interval_s)
        self._attr_last_t = float("-inf")
        warm = decl("serve_warm_latency_seconds")
        for b, t in self.warm_latency_s.items():
            warm.set(t, bucket=b)
        # Cold-start surface: total warm-up wall (compile loop + zeros
        # runs — what a cold respawn pays before its ready handshake) and
        # the compilation-cache honesty gauge. compile_seconds{program,
        # phase} is accumulated by the footprint ledger itself.
        decl("warmup_wall_seconds").set(self.warmup_wall_s)
        self.cache_status = coldstart.publish_cache_status(self.registry)
        # Mesh facts of the serving forward: device count (1 = the
        # single-chip replica; tile_h*tile_w for a sharded one) and the
        # forward halo-shift permute count the sharded lint window is
        # derived from (0 on a single chip — nothing to exchange).
        decl("serve_mesh_devices").set(self._predictor.num_devices)
        decl("serve_halo_shifts").set(self._predictor.halo_shifts())

        # -- liveness + postmortem ------------------------------------------
        self.health = telemetry.HealthState(registry=self.registry)
        self.flight = telemetry.FlightRecorder(
            capacity=flight_capacity,
            registry=self.registry,
            directory=flight_dir or telemetry_dir,
        )
        # canary.failure forensics join the postmortem ring alongside the
        # JSONL log (the ring did not exist when CanaryState was built).
        self.canary.flight = self.flight
        # The sentinel daemon: one tick = params-checksum audit + one
        # golden probe through the REAL dispatch path. Created disabled
        # (None) without an interval; start()/stop() manage its life.
        self.sentinel: "telemetry.CanarySentinel | None" = (
            telemetry.CanarySentinel(
                self._canary_tick, interval_s=self._canary_interval_s
            )
            if self._canary_interval_s is not None
            else None
        )
        self.last_attribution: "dict | None" = None
        self.watchdog: "telemetry.Watchdog | None" = None
        if watchdog_factor:
            self.watchdog = telemetry.Watchdog(
                factor=watchdog_factor,
                min_timeout_s=watchdog_min_timeout_s,
                registry=self.registry,
                health=self.health,
                on_trip=(self._on_watchdog_trip,),
            )
            # Prime the rolling-p99 history so the adaptive timeout is
            # meaningful before the first served request.
            self.watchdog.seed(max(self.warm_latency_s.values()))

        # -- slow-request capture (telemetry/tail.py) -----------------------
        # Seeded with the AOT warm latency (like the watchdog) and
        # floored at the TIGHTEST declared latency threshold (the slo=
        # config's or any SLO class's): under an objective, "slow" never
        # means less than the strictest objective.
        _thresholds = [
            c.latency_threshold_s for c in self._classes
            if c.latency_threshold_s is not None
        ]
        if slo is not None and getattr(slo, "latency_threshold_s", None):
            _thresholds.append(slo.latency_threshold_s)
        self.tail = telemetry.TailWatcher(
            registry=self.registry,
            slo_threshold_s=min(_thresholds) if _thresholds else None,
            factor=tail_factor,
            seed_s=max(self.warm_latency_s.values()),
            min_interval_s=tail_min_interval_s,
            capacity=tail_capacity,
            events=self._events,
            flight=self.flight,
        )

        if self._attr_every > 0:
            # Pay the profiler backend's one-time init (~3 s measured)
            # here, on a throwaway smallest-bucket capture, so the FIRST
            # live sample costs the same ~100 ms as every later one
            # instead of stalling a real batch past its deadline.
            b = min(self._buckets)
            self._dispatch_sampled(
                np.zeros((b, *self.example_shape), self._np_dtype), b, -1,
                publish=False,
            )
            self._attr_last_t = time.monotonic()

        # -- SLO evaluation (telemetry/slo.py, alerts.py, autoscale.py) -----
        # Per-class latency objectives are appended to the configured
        # ones, and the evaluator runs whenever ANY objective exists —
        # including classes declared without an slo= config, because the
        # scheduler's burn-rate feedback reads the evaluator's gauges.
        self.slo: "telemetry.SLOEvaluator | None" = None
        slo_cfg = slo
        if slo_cfg is None and self._class_objectives:
            slo_cfg = telemetry.SLOConfig()
        if slo_cfg is not None:
            objectives = slo_cfg.objectives() + self._class_objectives
            # The evaluator also runs for a headroom-only config (no
            # availability/latency objective): the memory_headroom_low
            # alert rides the same tick.
            if objectives or getattr(slo_cfg, "headroom_alert_ratio", None) is not None:
                autoscaler = telemetry.Autoscaler(
                    registry=self.registry,
                    config=slo_cfg.autoscale,
                    queue_capacity=max_queue,
                )
                self.slo = telemetry.SLOEvaluator(
                    registry=self.registry,
                    objectives=objectives,
                    config=slo_cfg,
                    autoscaler=autoscaler,
                    events=self._events,
                    flight=self.flight,
                )

        self._server = (
            telemetry.MetricsServer(
                self.registry, port=metrics_port,
                health=self.health.snapshot, debug=self._debugz,
                alerts=self.slo.state if self.slo is not None else None,
                numerics=self.canary.view,
            )
            if metrics_port is not None
            else None
        )
        self.metrics_port = self._server.port if self._server else None

    # -- construction helpers ------------------------------------------------

    @classmethod
    def from_predictor(cls, predictor, **kw) -> "ServingEngine":
        """Engine over an already-built predictor (the multi-chip entry:
        ``serve.sharded`` constructs a :class:`ShardedPredictor` and
        hands it here — batcher/scheduler/telemetry stack unchanged)."""
        return cls(
            None, None, None,
            example_shape=predictor.example_shape,
            dtype=predictor.dtype,
            predictor=predictor,
            **kw,
        )

    @classmethod
    def from_checkpoint(cls, path_or_dir: str, **kw) -> "ServingEngine":
        """Engine from a self-describing checkpoint path alone: metadata →
        rebuilt cells, restored params, calibrated ``batch_stats`` (which
        must have been saved — serving without calibration would silently
        use garbage BN statistics)."""
        from mpi4dl_tpu.checkpoint import rebuild_from_checkpoint

        cells, state, stats, meta = rebuild_from_checkpoint(path_or_dir)
        if stats is None:
            raise ValueError(
                "checkpoint has no batch_stats.msgpack — calibrate with "
                "evaluate.collect_batch_stats and save_checkpoint(..., "
                "batch_stats=...) before serving"
            )
        spec = meta["model"]
        shape = (
            spec["image_size"], spec["image_size"], spec.get("channels", 3)
        )
        kw.setdefault("dtype", spec.get("dtype", "float32"))
        return cls(cells, state.params, stats, example_shape=shape, **kw)

    # -- public surface ------------------------------------------------------

    @property
    def buckets(self) -> tuple[int, ...]:
        return self._buckets

    @property
    def slo_classes(self):
        """The normalized :class:`~mpi4dl_tpu.serve.SLOClass` tuple."""
        return self._classes

    @property
    def mesh_shape(self) -> tuple[int, int]:
        """``(tile_h, tile_w)`` of the serving forward's mesh — ``(1, 1)``
        for the single-chip replica. Fleet workers surface it on
        ``/healthz`` so shard-for-model-size (mesh) and
        replicate-for-traffic (fleet) read as two orthogonal axes."""
        return tuple(self._predictor.mesh_shape)

    def queue_depth(self) -> int:
        """Total requests waiting across every class queue (the
        enriched-/healthz payload the fleet router scrapes)."""
        return self._sched.qsize()

    @property
    def events(self) -> "telemetry.JsonlWriter":
        """The engine's JSONL event writer — co-located publishers (the
        in-process load generator's client-side span segments) write
        through THIS handle rather than opening the same file twice."""
        return self._events

    def _refuse_bucket(self, bucket: int, reason: str, error=None, **facts):
        """Admission-guard refusal: record why the bucket will not be
        warmed (stats()/debugz surface it) instead of letting the first
        execution crash the process. A compile-time OOM additionally
        emits the structured ``oom.report``."""
        from mpi4dl_tpu.telemetry import memory as memobs

        entry = {"reason": reason, **facts}
        if error is not None:
            ev = memobs.emit_oom_report(
                error, program=self._predictor.program, bucket=bucket,
                registry=self.registry, events=self._events,
            )
            entry["oom"] = ev["attrs"]["parsed"]
        self.refused_buckets[int(bucket)] = entry

    def assert_warm(self) -> None:
        """Every configured bucket must have its pre-built executable —
        the no-compile-after-warm-up contract."""
        missing = [b for b in self._buckets if b not in self._compiled]
        if missing:
            raise AssertionError(
                f"buckets {missing} have no pre-compiled executable; the "
                "serving loop would have to JIT on a live request"
            )

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._record_marker("serve.start")
        if self.memory_monitor is not None:
            self.memory_monitor.start()
        if self.slo is not None:
            self.slo.start()
        self._thread = threading.Thread(
            target=self._loop, name="mpi4dl-serve-batcher", daemon=True
        )
        self._thread.start()
        if self.sentinel is not None:
            self.sentinel.start()

    def stop(self, drain: bool = True) -> None:
        """Stop the batcher. ``drain=True`` serves what is already queued
        first; ``drain=False`` fails queued requests immediately with
        :class:`DrainedError` (counted ``outcome="drained"`` — a
        lifecycle event, not an availability-SLO failure)."""
        # The sentinel stops FIRST: a probe injected into a stopping
        # engine would only land in the drain/flush path as noise.
        if self.sentinel is not None:
            self.sentinel.stop()
        if not drain:
            self._flush_queue("engine stopped before this request was served")
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._flush_queue("engine stopped before this request was served")
        self._record_marker("serve.stop")
        # The exporters die with the engine; the registry itself stays
        # readable (stats(), snapshots) after stop, and the flight ring
        # stays dumpable.
        if self.watchdog is not None:
            self.watchdog.close()
        if self.memory_monitor is not None:
            self.memory_monitor.close()
        if self.slo is not None:
            # Final evaluation so the last requests' outcomes reach the
            # gauges/verdict before the evaluator thread stops.
            self.slo.close()
            try:
                self.slo.evaluate_once()
            except Exception:  # noqa: BLE001 — the verdict is advisory
                pass
        if self._server is not None:
            self._server.close()
            self._server = None
        self._events.close()

    def submit(
        self,
        x,
        deadline_s: float | None = None,
        trace_id: "str | None" = None,
        slo_class: "str | None" = None,
        tenant: "str | None" = None,
    ) -> Future:
        """Enqueue one example — or a multi-image batch of shape
        ``(n, *example_shape)``, which is split into per-image requests
        at admission and re-joined in order into one ``(n, classes)``
        result. Returns a ``Future`` resolving to the logits. Raises
        :class:`QueueFullError` when admission control rejects (the
        class queue is full, or the burn-rate feedback shed it); the
        future raises :class:`DeadlineExceededError` when the deadline
        passes before delivery — including a deadline already expired
        at submit, which is rejected before occupying any queue slot.

        slo_class: the named SLO class this request belongs to
        (``slo_classes=`` at construction). None lands in the default
        class. The class decides EDF queueing, the default deadline,
        and which per-class latency objective the request's outcome
        burns.

        tenant: the submitting tenant (``tenants=`` at construction).
        None lands in the ``default`` tenant. With tenancy configured,
        the tenant's token bucket is debited per row BEFORE any queue
        slot is taken — over quota raises
        :class:`~mpi4dl_tpu.tenancy.QuotaExceededError` whose
        ``retry_after_s`` is the bucket's refill time; an unknown
        tenant or a class outside the tenant's allowlist raises
        ``ValueError``. With tenancy off the name is carried through
        to labels/spans but nothing is enforced.

        trace_id: distributed-trace propagation — a caller in ANOTHER
        process (load generator, fleet router) passes the id it minted so
        this engine's span segment joins the caller's under one trace
        (``telemetry.group_spans_by_trace`` / ``analyze trace-export``).
        None mints a fresh globally-unique id. On delivery the future
        additionally carries ``trace_id`` and ``e2e_latency_s``
        attributes, so the caller can compute its own hop overhead
        (``serve_client_overhead_seconds``)."""
        x = np.asarray(x, self._np_dtype)
        multi = (
            x.ndim == len(self.example_shape) + 1
            and x.shape[0] >= 1
            and tuple(x.shape[1:]) == self.example_shape
        )
        if not multi and x.shape != self.example_shape:
            raise ValueError(
                f"example shape {x.shape} != configured {self.example_shape}"
                f" (or (n, *{self.example_shape}) for a multi-image request)"
            )
        cls = self._sched.resolve(slo_class)
        if self._stop_evt.is_set() and self._thread is None:
            raise RuntimeError("engine is stopped; call start() first")
        # Quota admission BEFORE the deadline check or any queue work:
        # an over-quota flood must be shed before it occupies anything.
        # Raises QuotaExceededError (retry_after_s = the bucket's refill
        # time for the debited rows) or ValueError for an unknown tenant
        # / class-allowlist violation — both typed, both pre-queue.
        n_rows = (
            x.shape[0]
            if x.ndim == len(self.example_shape) + 1 else 1
        )
        if self._admission is not None:
            try:
                ten = self._admission.admit(
                    tenant, n=n_rows, slo_class=cls.name,
                )
            except QuotaExceededError:
                with self._lock:
                    self._counts["rejected_quota"] += n_rows
                raise
            tenant_name = ten.name
        else:
            tenant_name = tenant or "default"
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = (
                cls.deadline_s if cls.deadline_s is not None
                else self._default_deadline_s
            )
        ddl = now + deadline_s
        tid = str(trace_id) if trace_id else telemetry.new_trace_id("serve")
        rows = list(x) if multi else [x]
        n = len(rows)
        future: Future = Future()
        if ddl <= now:
            # Admission-time deadline check: an already-expired deadline
            # is rejected with the existing typed error before it ever
            # occupies a queue slot (per-row counted, like formation-
            # time rejection).
            with self._lock:
                self._counts["rejected_deadline"] += n
            self._m_requests.inc(n, outcome="rejected_deadline")
            future.trace_id = tid
            future.set_exception(DeadlineExceededError(
                "deadline already expired at submit — rejected at admission"
            ))
            return future
        join = _Join(n, future, tid, submit_t=now) if multi else None
        reqs = [
            _Request(
                x=row, submit_t=now, deadline=ddl,
                future=future if join is None else Future(),
                trace_id=tid, slo_class=cls.name, tenant=tenant_name,
                join=join, row=i,
            )
            for i, row in enumerate(rows)
        ]
        with self._lock:
            self._counts["submitted"] += n
        self._m_submitted.inc(n)
        # Arm the watchdog BEFORE the enqueue: if the loop has already
        # stalled, the very request that exposes it must be counted as
        # outstanding. A queue-full reject cancels (not "done" — an
        # admission bounce is not loop progress and must not reset the
        # stall clock).
        if self.watchdog is not None:
            for _ in reqs:
                self.watchdog.begin()
        try:
            # Atomic: a multi-image split admits all rows or none.
            depth = self._sched.put_many(reqs)
        except SchedulerFull as e:
            if self.watchdog is not None:
                for _ in reqs:
                    self.watchdog.cancel()
            with self._lock:
                self._counts["rejected_queue_full"] += n
            self._m_requests.inc(n, outcome="rejected_queue_full")
            raise QueueFullError(
                str(e),
                retry_after_s=self.retry_after_hint(e.slo_class),
                slo_class=e.slo_class, shed=e.shed,
            ) from None
        for r in reqs:
            r.queue_depth_at_submit = depth
        return future

    def retry_after_hint(self, slo_class: "str | None" = None) -> float:
        """How long a queue-full-rejected client should wait before
        retrying: one batch-completion period (EMA), floored at the
        batch-formation window. Before the first completed batch the
        warm latency stands in — the engine's only cadence fact. With a
        class name, the hint scales by that class's own backlog (its
        queued requests drain at most ``max_batch`` per batch, so a
        deep class queue frees a slot proportionally later)."""
        with self._lock:
            ema = self._batch_period_ema
        if ema is None:
            ema = max(self.warm_latency_s.values())
        hint = max(self._max_wait_s, ema)
        if slo_class is not None:
            depth = self._sched.qsize_by_class().get(slo_class, 0)
            hint *= max(1.0, min(10.0, depth / self._max_batch))
        return hint

    def predict_one(self, x) -> np.ndarray:
        """Synchronous batch-size-1 forward through the bucket-1
        executable, bypassing the queue — the serial baseline the load
        generator compares dynamic batching against."""
        x = np.asarray(x, self._np_dtype)
        b = bucket_for(1, self._buckets)
        batch = pad_batch([x], b, self._np_dtype)
        out = self._predictor.run(self._compiled[b], batch)
        return np.asarray(out)[0]

    # -- numerics sentinel (telemetry/canary.py) ----------------------------

    def params_checksum(self) -> str:
        """Order-independent content checksum over the predictor's live
        parameter tree + BN statistics (``pc`` + 16 hex). Deterministic
        across replicas loading the same checkpoint — the federation's
        cross-replica integrity comparison and the ``/healthz`` payload
        both read this."""
        params, stats = self._predictor.param_tree()
        return telemetry.params_checksum(params, stats)

    def inject_canary(self) -> "Future | None":
        """Inject the golden probe through the REAL dispatch path: the
        same scheduler queue, batch former, executable, and completion
        loop as client traffic — a corruption anywhere on that path is
        caught, not just one in the raw forward. The probe is counted
        ``outcome="canary"`` and excluded from submitted/SLO/tenant/
        latency accounting. Returns the probe's future, or None when the
        queue is full (the sentinel records a ``skipped`` verdict and
        tries again next interval — probe traffic never displaces client
        work)."""
        now = time.monotonic()
        r = _Request(
            x=self._canary_x,
            submit_t=now,
            # Generous deadline: a canary expiring in a deep queue is a
            # capacity fact, not a numerics fact — skip, don't diverge.
            deadline=now + max(30.0, self._default_deadline_s),
            future=Future(),
            trace_id=telemetry.new_trace_id("canary"),
            slo_class=self._sched.resolve(None).name,
            canary=True,
        )
        if self.watchdog is not None:
            self.watchdog.begin()
        try:
            self._sched.put_many([r])
        except SchedulerFull:
            if self.watchdog is not None:
                self.watchdog.cancel()
            self.canary.skip("queue full")
            return None
        return r.future

    def _canary_tick(self) -> None:
        """One sentinel interval: re-audit the params checksum against
        its load-time baseline, then send one golden probe (verified
        against its bucket reference in :meth:`_complete`)."""
        self.canary.record_checksum(self.params_checksum())
        self.inject_canary()

    def corrupt_params(self, bits: int = 3, seed: int = 0) -> dict:
        """Chaos hook (``corrupt:`` drill): flip ``bits`` mantissa-region
        bits in the predictor's largest parameter leaf WITHOUT updating
        the canary references or checksum baseline — the sentinel must
        *discover* the damage. Returns bit-flip forensics."""
        return telemetry.corrupt_params(self._predictor, bits=bits, seed=seed)

    def stats(self) -> dict:
        """Counter snapshot + served-latency percentiles (seconds), plus
        the live queue depth and per-bucket dispatch counts the autoscaling
        signal consumes (mirrored in the metrics registry)."""
        with self._lock:
            out = dict(self._counts)
            lat = list(self._latencies)
            out["bucket_dispatches"] = dict(self._bucket_dispatches)
            padded, total = self._padded_rows, self._total_rows
        out["latency_s"] = percentiles(lat)
        if out["batches"]:
            out["mean_batch_size"] = out["batched_examples"] / out["batches"]
        out["queue_depth"] = self._sched.qsize()
        out["queue_depth_by_class"] = self._sched.qsize_by_class()
        out["scheduler"] = self._sched.state()
        if self._admission is not None:
            out["tenancy"] = self._admission.state()
        out["pad_waste_ratio"] = padded / total if total else 0.0
        out["buckets"] = list(self._buckets)
        out["mesh"] = list(self.mesh_shape)
        out["warm_latency_s"] = dict(self.warm_latency_s)
        out["warmup"] = self.warmup_stats()
        out["healthy"] = self.health.healthy
        out["memory"] = self.memory_view()
        out["numerics"] = self.canary.view()
        run_stats = getattr(self._predictor, "run_stats", None)
        if run_stats is not None:
            # Tiled predictor: geometry + per-request tile/stitch facts
            # (the loadgen report's `tiled` block reads this).
            out["tiled"] = run_stats()
        return out

    def warmup_stats(self) -> dict:
        """Cold-start decomposition of this engine's warm-up
        (stats()/``/debugz``/the worker ready handshake): per-bucket
        trace/compile/first-execute seconds + executable fingerprints
        from the footprint ledger, phase totals, the warm-up wall, and
        the compilation-cache status."""
        buckets = {}
        totals = {"trace_s": 0.0, "compile_s": 0.0, "warm_s": 0.0}
        for b in sorted(self.warm_latency_s):
            e = self.memory_ledger.get(self._predictor.program, bucket=b) or {}
            rec = {
                k: e.get(k)
                for k in ("trace_s", "compile_s", "warm_s", "fingerprint")
            }
            buckets[str(b)] = rec
            for k in totals:
                if isinstance(rec.get(k), (int, float)):
                    totals[k] += rec[k]
        return {
            "wall_s": round(self.warmup_wall_s, 6),
            "buckets": buckets,
            "totals": {k: round(v, 6) for k, v in totals.items()},
            "cache": getattr(self, "cache_status", None),
        }

    def memory_view(self) -> dict:
        """The memory observability surface (stats()/debugz): per-bucket
        predicted peaks from the footprint ledger, refused buckets, the
        configured/device limit, and the latest live device sample."""
        buckets = {}
        for b in self._buckets:
            e = self.memory_ledger.get(self._predictor.program, bucket=b)
            if e is not None:
                buckets[str(b)] = e.get("peak_bytes")
        return {
            "bucket_peak_hbm_bytes": buckets,
            "refused_buckets": {
                str(b): dict(v) for b, v in self.refused_buckets.items()
            },
            "limit_bytes": self._memory_limit,
            "devices": (
                self.memory_monitor.state()
                if self.memory_monitor is not None else None
            ),
            "programs": self.memory_ledger.summary()["entries"],
        }

    # -- liveness + postmortem -----------------------------------------------

    def _record_marker(self, name: str, **attrs) -> None:
        if self.flight.enabled:
            self.flight.record({
                "ts": time.time(), "kind": "event", "name": name,
                "attrs": attrs,
            })

    def _on_watchdog_trip(self, reason: str) -> None:
        """Watchdog callback: mark + dump the flight ring. The health
        flip and trip counter already happened inside the watchdog."""
        self._record_marker("serve.watchdog_trip", reason=reason)
        self.flight.dump(reason="watchdog")

    def set_attribution(self, summary: dict) -> None:
        """Attach the latest trace-attribution summary
        (:mod:`mpi4dl_tpu.analysis.trace`) so ``/debugz`` serves it."""
        self.last_attribution = summary

    def _debugz(self) -> dict:
        return {
            "stats": self.stats(),
            "health": self.health.snapshot(),
            "watchdog": self.watchdog.state() if self.watchdog else None,
            "slo": self.slo.state() if self.slo is not None else None,
            "phase_attribution": (
                self.slo.last_phase_attribution
                if self.slo is not None else None
            ),
            "tail": self.tail.state(),
            "flight_tail": self.flight.tail(50),
            "attribution": self.last_attribution,
        }

    def _publish_phase_shares(self) -> None:
        """Refresh ``serve_phase_share{phase=}`` from the cumulative
        served-latency phase mix (once per completed batch, four gauge
        sets)."""
        with self._lock:
            totals = dict(self._phase_totals)
        total = sum(totals.values())
        if total <= 0:
            return
        for phase, v in totals.items():
            self._m_phase_share.set(v / total, phase=phase)

    def dump_flight(self, path: "str | None" = None, reason: str = "manual"):
        """Dump the flight-recorder ring now; returns the JSONL path."""
        return self.flight.dump(path=path, reason=reason)

    def lint_report(self, bucket: int | None = None):
        """hlolint gate over a serving executable's HLO, with expectations
        DERIVED FROM THE MESH rather than hardcoded: a single-chip engine
        keeps the zero-collectives gate (rule ``single-chip-collectives``
        — any collective is resharding that regressed off the one
        device), while a sharded engine flips to the partition-math
        halo-permute window (tile grid + counted forward halo shifts,
        rule ``halo-permute-count`` — the same gate the train step rides)
        plus the standing stray-resharding rules."""
        from mpi4dl_tpu.analysis import analyze_compiled

        from mpi4dl_tpu.analysis.metrics import publish_report

        b = bucket if bucket is not None else max(self._buckets)
        rep = analyze_compiled(
            self._compiled[b],
            expected=self._predictor.expectations(),
            platform=self._predictor.platform(),
            config={"program": self._predictor.program, "bucket": b,
                    "example_shape": list(self.example_shape),
                    "mesh_shape": list(self.mesh_shape)},
        )
        publish_report(rep, self.registry)  # verdict scrapes with the rest
        return rep

    # -- batcher loop --------------------------------------------------------

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — the batcher dying is
            # the flight recorder's reason to exist: dump the last N
            # requests, flip health, fail what's queued, then surface.
            self.health.set_unhealthy(f"batcher crashed: {e!r}")
            self._record_marker("serve.crash", error=repr(e))
            from mpi4dl_tpu.telemetry import memory as memobs

            if memobs.is_oom_error(e):
                # Structured forensics BEFORE the crash dump, so the
                # oom.report sits in the ring the dump writes out.
                memobs.emit_oom_report(
                    e, program=self._predictor.program,
                    registry=self.registry, events=self._events,
                    flight=self.flight,
                )
            try:
                self.flight.dump(reason="crash")
            except Exception:  # noqa: BLE001 — postmortem best-effort
                pass
            self._flush_queue(f"batcher crashed: {e!r}", outcome=None)
            raise

    def _loop_inner(self) -> None:
        inflight = None
        while True:
            reqs = self._form_batch(busy=inflight is not None)
            staged = None
            if reqs:
                try:
                    staged = (reqs, self._dispatch(reqs))
                except Exception as e:  # noqa: BLE001 — a bad batch must
                    # fail its own requests, not kill the batcher thread
                    # (hanging every future ever submitted after it).
                    self._record_marker(
                        "serve.batch_error", error=repr(e), batch=len(reqs)
                    )
                    from mpi4dl_tpu.telemetry import memory as memobs

                    if memobs.is_oom_error(e):
                        # Runtime OOM on a live batch: structured report
                        # into the event log + flight ring, and dump the
                        # ring — the postmortem names the program, the
                        # bucket, and the largest buffers.
                        memobs.emit_oom_report(
                            e, program=self._predictor.program,
                            bucket=bucket_for(len(reqs), self._buckets),
                            registry=self.registry, events=self._events,
                            flight=self.flight, dump=True,
                        )
                    for r in reqs:
                        self._fail_request(r, e)
                        if self.watchdog is not None:
                            self.watchdog.done()
            if inflight is not None:
                self._complete(*inflight)
            inflight = staged
            if (
                inflight is None
                and self._stop_evt.is_set()
                and self._sched.empty()
            ):
                return

    def _form_batch(self, busy: bool = False) -> "list[_Request] | None":
        """One scheduler take. The continuous (edf) former never makes
        an IDLE device wait out a window — with nothing in flight, the
        first arrival dispatches with whatever else is already queued.
        But while a batch IS in flight (``busy``), the device cannot
        accept work anyway, so the former keeps the ``max_wait_s``
        collection window open to fill the next batch — arrivals during
        the in-flight compute join the next dispatch, and occupancy
        matches the windowed former under load. Fifo mode always holds
        the window (the PR-2 baseline). Requests whose deadline passed
        while queued come back in ``expired`` and are rejected without
        occupying a batch slot."""
        reqs, expired = self._sched.take(
            self._max_batch,
            first_timeout_s=self._poll_s,
            window_s=(
                self._max_wait_s
                if (self._sched.mode == "fifo" or busy) else 0.0
            ),
        )
        for r in expired:
            self._reject_deadline(r)
        if not reqs:
            return None
        formed = time.monotonic()
        for r in reqs:
            r.formed_t = formed
        return reqs

    def _dispatch(self, reqs: "list[_Request]"):
        bucket = bucket_for(len(reqs), self._buckets)
        # The executable must pre-exist — never compile on a live request.
        if bucket not in self._compiled:
            raise AssertionError(
                f"no pre-built executable for bucket {bucket}"
            )
        batch = pad_batch([r.x for r in reqs], bucket, self._np_dtype)
        seq = self._batch_seq
        self._batch_seq += 1
        out = None
        if (
            self._attr_every > 0
            and seq > 0
            and seq % self._attr_every == 0
            and time.monotonic() - self._attr_last_t
            >= self._attr_min_interval_s
        ):
            out = self._dispatch_sampled(batch, bucket, seq)
        if out is None:
            with annotate_step("mpi4dl_serve_batch", seq):
                staged = self._predictor.stage(batch)  # async H2D
                out = self._predictor.run(self._compiled[bucket], staged)
        staged_t = time.monotonic()
        # Tiled predictors record per-run facts (tile count, stitch/
        # stream seconds) — attach them so this batch's requests carry
        # them into their span events and tail samples.
        tiled_facts = getattr(self._predictor, "last_run", None)
        for r in reqs:
            r.staged_t = staged_t
            r.dispatch_seq = seq
            r.tiled = tiled_facts
        with self._lock:
            self._bucket_dispatches[bucket] = (
                self._bucket_dispatches.get(bucket, 0) + 1
            )
            self._padded_rows += bucket - len(reqs)
            self._total_rows += bucket
            waste = self._padded_rows / self._total_rows
        self._m_batches.inc(bucket=bucket)
        self._m_occupancy.observe(len(reqs) / bucket, bucket=bucket)
        self._m_pad_waste.set(waste)
        return out

    def _dispatch_sampled(self, batch, bucket: int, seq: int,
                          publish: bool = True):
        """Sampled continuous attribution: run this one batch blocked
        inside a private XProf capture, parse it, publish the live
        ``trace_*`` gauges (``program="serve_sampled"``) and refresh
        :attr:`last_attribution`. Returns the logits, or None to send
        the batch down the normal async path instead (capture refused —
        e.g. an outer ``--trace-dir`` profile already owns the
        profiler; only one trace can be active per process).
        ``publish=False`` is the constructor's profiler-warm-up mode."""
        import jax

        from mpi4dl_tpu.profiling import trace as profiler_trace

        self._attr_last_t = time.monotonic()
        tmp = tempfile.mkdtemp(prefix="mpi4dl-serve-sample-")
        out = None
        try:
            try:
                with profiler_trace(tmp):
                    with annotate_step("mpi4dl_serve_batch", seq):
                        staged = self._predictor.stage(batch)
                        out = self._predictor.run(
                            self._compiled[bucket], staged
                        )
                        jax.block_until_ready(out)
            except Exception as e:  # noqa: BLE001 — sampling must never
                # fail a live batch; the normal dispatch path takes over
                self._record_marker(
                    "serve.sample_skipped", error=repr(e), batch_seq=seq
                )
                return out  # None unless the forward itself completed
            if not publish:
                return out
            try:
                from mpi4dl_tpu.analysis.trace import (
                    analyze_trace_dir,
                    publish_attribution,
                )

                summary = analyze_trace_dir(
                    tmp, step_name="mpi4dl_serve_batch"
                )
                publish_attribution(
                    summary, self.registry, program="serve_sampled"
                )
                self.last_attribution = {
                    "program": "serve_sampled",
                    "batch_seq": seq,
                    "n_steps": summary["n_steps"],
                    "per_step_mean": summary["per_step_mean"],
                    "range": summary["range"],
                    "collective": summary["collective"],
                }
            except Exception as e:  # noqa: BLE001 — a broken trace drops
                # the sample, never the batch
                self._record_marker(
                    "serve.sample_error", error=repr(e), batch_seq=seq
                )
            return out
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def _complete(self, reqs: "list[_Request]", out) -> None:
        logits = np.asarray(out)  # blocks until the device batch finishes
        now = time.monotonic()
        bucket = bucket_for(len(reqs), self._buckets)
        with self._lock:
            self._counts["batches"] += 1
            self._counts["batched_examples"] += len(reqs)
            if self._last_complete_t is not None:
                period = now - self._last_complete_t
                self._batch_period_ema = (
                    period if self._batch_period_ema is None
                    else 0.8 * self._batch_period_ema + 0.2 * period
                )
            self._last_complete_t = now
        for i, r in enumerate(reqs):
            if self.watchdog is not None:
                self.watchdog.done(now - r.submit_t)
            if r.canary:
                # Sentinel probe: verify row i against the bucket's
                # warm-up reference (row outputs are independent of the
                # other rows in the batch — the row-bitwise identity the
                # padding contract already guarantees) and step off the
                # client accounting entirely: no latency histogram, no
                # SLO burn, no tenant charge, no span.
                with self._lock:
                    self._counts["canary"] += 1
                self._m_requests.inc(outcome="canary")
                _entry = self.memory_ledger.get(
                    self._predictor.program, bucket=bucket
                ) or {}
                self.canary.verify(
                    bucket, logits[i], fingerprint=_entry.get("fingerprint")
                )
                r.future.set_result(np.array(logits[i]))
                continue
            # Cross-process trace surface: the caller (loadgen today, the
            # fleet router tomorrow) reads these off the future to compute
            # its hop overhead and to join its own span segment. Join
            # rows set them on the OUTER future at re-join instead.
            if r.join is None:
                r.future.trace_id = r.trace_id
                r.future.e2e_latency_s = now - r.submit_t
            if now > r.deadline:
                with self._lock:
                    self._counts["served_late"] += 1
                self._m_requests.inc(outcome="served_late")
                self._emit_spans(r, now, "served_late", bucket, len(reqs))
                self._fail_request(r, DeadlineExceededError(
                    f"result ready {now - r.deadline:.3f}s past deadline — "
                    "dropped rather than silently served late"
                ))
                continue
            with self._lock:
                self._counts["served"] += 1
                self._latencies.append(now - r.submit_t)
            self._m_requests.inc(outcome="served")
            self._m_latency.observe(now - r.submit_t, exemplar=r.trace_id)
            self._m_class_latency.observe(
                now - r.submit_t, exemplar=r.trace_id,
                slo_class=r.slo_class, tenant=r.tenant,
            )
            self._emit_spans(r, now, "served", bucket, len(reqs))
            if r.join is not None:
                r.join.row_done(r.row, logits[i], now)
            else:
                r.future.set_result(logits[i])
        self._publish_phase_shares()

    def _emit_spans(
        self, r: _Request, end_t: float, outcome: str,
        bucket: int, batch_size: int,
    ) -> None:
        """Record one request's contiguous lifecycle spans: into the
        phase-labeled histogram always, into the JSONL log when enabled.
        Contiguity (each phase starts where the previous ended, the last
        ends at delivery) is what makes queue+form+stage+compute sum to
        the end-to-end latency — the tier-1 invariant."""
        spans = telemetry.spans_from_marks([
            ("submit", r.submit_t),
            ("queue_wait", r.form_t),
            ("batch_form", r.formed_t),
            ("h2d_stage", r.staged_t),
            ("device_compute", end_t),
        ])
        telemetry.record_spans(self._m_spans, spans, exemplar=r.trace_id)
        if outcome.startswith("served"):
            # Served-latency phase mix for the serve_phase_share gauges
            # (and the latency alerts' attribution baseline).
            with self._lock:
                for s in spans:
                    self._phase_totals[s["phase"]] = (
                        self._phase_totals.get(s["phase"], 0.0)
                        + s["duration_s"]
                    )
            # Slow-request capture: served AND served_late completions
            # are offered (the late ones are the pathological tail); the
            # watcher itself decides threshold + rate limit.
            with self._lock:
                padded, total = self._padded_rows, self._total_rows
            self.tail.observe(
                r.trace_id, end_t - r.submit_t, spans,
                outcome=outcome, bucket=bucket, batch_size=batch_size,
                slo_class=r.slo_class, tenant=r.tenant,
                queue_depth_at_submit=r.queue_depth_at_submit,
                dispatch_seq=r.dispatch_seq,
                pad_waste_ratio=padded / total if total else 0.0,
                watchdog=(
                    self.watchdog.state() if self.watchdog is not None
                    else None
                ),
                attribution=self.last_attribution,
            )
        if self.flight.enabled or self._events.enabled:
            attrs = {"outcome": outcome, "bucket": bucket,
                     "batch_size": batch_size,
                     "e2e_latency_s": end_t - r.submit_t,
                     "slo_class": r.slo_class, "tenant": r.tenant,
                     "pid": os.getpid(), "role": "engine"}
            if r.tiled is not None:
                attrs["tiled"] = dict(r.tiled)
            ev = telemetry.span_event(
                "serve.request", r.trace_id, spans, attrs=attrs,
            )
            self.flight.record(ev)
            if self._events.enabled:
                self._events.write(ev)

    def _reject_deadline(self, req: _Request) -> None:
        if req.canary:
            # A probe expiring in a deep queue is a capacity fact, not a
            # numerics verdict — record it skipped, off the client books.
            if self.watchdog is not None:
                self.watchdog.done()
            self.canary.skip("expired in queue")
            req.future.set_exception(DeadlineExceededError(
                "canary probe expired while queued"
            ))
            return
        with self._lock:
            self._counts["rejected_deadline"] += 1
        self._m_requests.inc(outcome="rejected_deadline")
        if self.watchdog is not None:
            # A formation-time rejection is loop progress: the batcher is
            # alive and draining.
            self.watchdog.done()
        if self.flight.enabled or self._events.enabled:
            spans = telemetry.spans_from_marks([
                ("submit", req.submit_t), ("queue_wait", req.form_t),
            ])
            ev = telemetry.span_event(
                "serve.request", req.trace_id, spans,
                attrs={"outcome": "rejected_deadline",
                       "slo_class": req.slo_class,
                       "pid": os.getpid(), "role": "engine"},
            )
            self.flight.record(ev)
            if self._events.enabled:
                self._events.write(ev)
        self._fail_request(req, DeadlineExceededError(
            "deadline expired while the request waited for batch formation"
        ))

    def _fail_request(self, req: _Request, exc: BaseException) -> None:
        """Deliver a failure: directly onto a single request's future,
        or into a multi-image request's join (first failure wins the
        whole join; later rows are no-ops)."""
        if req.join is not None:
            req.join.fail(exc)
        else:
            req.future.set_exception(exc)

    def _flush_queue(self, msg: str, outcome: "str | None" = "drained") -> None:
        """Fail every still-queued request. ``outcome="drained"``
        (deliberate stop/drain) delivers :class:`DrainedError` and
        counts the distinct ``drained`` label — excluded from the
        availability SLO, so a router-initiated drain never burns error
        budget. ``outcome=None`` (batcher crash) keeps the bare
        RuntimeError: those ARE failures and the crash already
        surfaced through health/flight."""
        for req in self._sched.drain():
            if self.watchdog is not None:
                self.watchdog.cancel()
            if req.canary:
                # Probes never count as drained client work.
                self.canary.skip("flushed at stop")
                req.future.set_exception(DrainedError(msg))
                continue
            if outcome == "drained":
                with self._lock:
                    self._counts["drained"] += 1
                self._m_requests.inc(outcome="drained")
                self._fail_request(req, DrainedError(msg))
            else:
                self._fail_request(req, RuntimeError(msg))
