"""``python -m mpi4dl_tpu.serve`` — start a serving engine and load-test it.

Restores a self-describing checkpoint (``--ckpt``) or builds a synthetic
calibrated ResNet (default — no artifacts needed), AOT-warms every bucket,
runs the requested load model, and prints ONE JSON report line to stdout
(bench.py's keep-the-last-line protocol). ``--lint`` additionally gates
the serving executable's HLO through hlolint (zero collectives on the
single-chip path) and fails the process on error-severity findings.

Examples::

    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.serve --requests 64
    python -m mpi4dl_tpu.serve --ckpt /ckpts/run1 --mode open \
        --rate 200 --duration 10 --deadline-ms 50 --lint
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.serve --requests 512 \
        --slo-availability 99.9 --slo-latency-ms 50 --metrics-port 0
    JAX_PLATFORMS=cpu python -m mpi4dl_tpu.serve --mesh 2x2 \
        --requests 64 --lint   # spatially-sharded forward, halo-window gate
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.serve",
        description="mpi4dl_tpu online serving engine + load generator",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--ckpt", default=None,
                   help="self-describing checkpoint dir/path "
                        "(default: synthetic calibrated ResNet)")
    p.add_argument("--depth", type=int, default=11,
                   help="synthetic ResNet-v2 depth (9n+2)")
    p.add_argument("--image-size", type=int, default=32,
                   help="synthetic model input size")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--calib-batches", type=int, default=2,
                   help="synthetic BN calibration batches")
    p.add_argument("--mesh", default=None, metavar="HxW",
                   help="spatially shard the serving forward over a "
                        "tile_h x tile_w device mesh (e.g. 2x2, 1x2): "
                        "each request's H/W partitions across chips with "
                        "halo exchanges, the hlolint gate flips to the "
                        "partition-math halo-permute window, and the "
                        "synthetic model becomes a spatial ResNet-v1 "
                        "front (default: single-chip engine)")
    p.add_argument("--conv-overlap", default=None,
                   choices=("monolithic", "decomposed"),
                   help="spatial conv/pool impl for the sharded forward "
                        "(overlap_decompose: interior hides the halo "
                        "permute; bit-identical outputs); default "
                        "inherits MPI4DL_TPU_CONV_OVERLAP")
    p.add_argument("--spatial-cells", type=int, default=None,
                   help="leading cells of the sharded model that run "
                        "spatially partitioned (--mesh only; default: "
                        "the checkpoint's stored spatial_cells builder "
                        "arg, or 3 for the synthetic model)")
    p.add_argument("--tiled", default=None, metavar="HxW",
                   help="gigapixel tiled inference (serve/tiled.py): "
                        "serve images of this size on ONE chip by "
                        "streaming halo-correct overlap-read tiles "
                        "through a fixed tile executable and stitching "
                        "exactly — the /predict_tiled surface, with its "
                        "own 'tiled' SLO class and per-request "
                        "tile/stitch report (mutually exclusive with "
                        "--mesh; with --ckpt, HxW must match the "
                        "checkpoint's image size)")
    p.add_argument("--tile", type=int, default=None,
                   help="tiled core extent in input px (a multiple of "
                        "the model's cumulative stride; default: a "
                        "quarter of the image). `analyze memory-plan "
                        "--bisect tile` computes the largest that fits "
                        "a chip")
    p.add_argument("--tile-batch", type=int, default=1,
                   help="largest power-of-two TILE bucket the tiled "
                        "forward batches windows into per dispatch "
                        "(1 = the exact, bit-identical default; larger "
                        "buckets trade last-bit determinism for "
                        "throughput at the documented f32 tolerance)")
    p.add_argument("--max-batch", type=int, default=8,
                   help="largest micro-batch bucket (power of two)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="batch formation window")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission-control queue bound (per SLO class)")
    p.add_argument("--deadline-ms", type=float, default=10000.0,
                   help="per-request deadline")
    p.add_argument("--scheduler", choices=("edf", "fifo"), default="edf",
                   help="batch former: edf = continuous scheduler "
                        "(deadline-ordered class queues, in-flight "
                        "re-admission, burn-rate feedback); fifo = the "
                        "windowed max-wait/max-size former (the A/B "
                        "baseline)")
    p.add_argument("--slo-classes", default=None, metavar="SPEC",
                   help="named SLO classes partitioning the queue, "
                        "NAME=THRESHOLD[:TARGET_PCT][@DEADLINE] comma-"
                        "separated (e.g. 'tight=50ms:99.9@200ms,"
                        "bulk=2s'); each threshold becomes a per-class "
                        "latency objective whose burn rate feeds the "
                        "scheduler")
    p.add_argument("--class-mix", default=None, metavar="MIX",
                   help="loadgen traffic mix over the declared classes, "
                        "NAME:WEIGHT[:DEADLINE] comma-separated (e.g. "
                        "'tight:1:10s,bulk:3:60s'); the report then "
                        "carries per-class latency under by_class")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant admission: NAME=RPS:BURST[:WEIGHT]"
                        "[@CLASSES] comma-separated (e.g. "
                        "'tight=200:50:4,bulk=50:200:1@bulk', "
                        "'bulk=none' = unlimited); each tenant gets a "
                        "token-bucket quota (over-quota floods shed with "
                        "retry_after_s BEFORE taking queue slots) and a "
                        "deficit-weighted-fair share of EDF batch fill; "
                        "an implicit unlimited 'default' tenant is "
                        "appended for unlabeled traffic")
    p.add_argument("--tenant-mix", default=None, metavar="MIX",
                   help="loadgen traffic mix over tenants, NAME:WEIGHT "
                        "comma-separated (e.g. 'bulk:10,tight:1'); the "
                        "report then carries per-tenant outcomes and "
                        "latency under by_tenant")
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--requests", type=int, default=64,
                   help="closed loop: total requests")
    p.add_argument("--concurrency", type=int, default=16,
                   help="closed loop: client count")
    p.add_argument("--rate", type=float, default=100.0,
                   help="open loop: offered requests/sec")
    p.add_argument("--duration", type=float, default=5.0,
                   help="open loop: seconds")
    p.add_argument("--queue-full-retries", type=int, default=0,
                   help="opt-in client retries per request on queue-full "
                        "admission bounces, backing off per the engine's "
                        "retry_after_s cadence hint (0 = shed instantly)")
    p.add_argument("--retry-backoff-ms", type=float, default=None,
                   help="explicit retry backoff base; default honors the "
                        "engine's QueueFullError.retry_after_s hint")
    p.add_argument("--serial", type=int, default=16,
                   help="batch-size-1 serial baseline requests (0 skips)")
    p.add_argument("--lint", action="store_true",
                   help="hlolint the serving executable; fail on errors")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve a Prometheus /metrics endpoint on this "
                        "port for the run (0 = ephemeral; the bound port "
                        "is in the report and on stderr)")
    p.add_argument("--telemetry-dir", default=None,
                   help="write JSONL span/metrics events here "
                        "(default: $MPI4DL_TPU_TELEMETRY_DIR, unset = off)")
    p.add_argument("--watchdog-factor", type=float, default=20.0,
                   help="trip the stalled-loop watchdog at this multiple "
                        "of the rolling p99 request latency (0 disables)")
    p.add_argument("--watchdog-min-timeout", type=float, default=2.0,
                   help="floor of the watchdog timeout, seconds")
    p.add_argument("--flight-capacity", type=int, default=512,
                   help="flight-recorder ring size in events (0 disables)")
    p.add_argument("--flight-dir", default=None,
                   help="where watchdog/crash/SIGTERM flight dumps land "
                        "(default: the telemetry dir, then the temp dir)")
    p.add_argument("--tail-factor", type=float, default=4.0,
                   help="slow-request capture: trip at this multiple of "
                        "the rolling p99 e2e latency (floored at the "
                        "latency SLO threshold when one is set)")
    p.add_argument("--tail-min-interval", type=float, default=1.0,
                   help="rate limit between captured tail.sample "
                        "events, seconds")
    p.add_argument("--tail-capacity", type=int, default=64,
                   help="tail-sample ring size on /debugz (0 disables "
                        "capture)")
    p.add_argument("--slo-availability", type=float, default=None,
                   metavar="PCT",
                   help="availability SLO target in percent (e.g. 99.9): "
                        "good outcomes / all outcomes of "
                        "serve_requests_total; enables the SLO evaluator, "
                        "burn-rate alerts, /alertz, and the advisory "
                        "autoscale gauge")
    p.add_argument("--slo-latency-ms", type=float, default=None,
                   metavar="MS",
                   help="latency SLO threshold: --slo-latency-target "
                        "percent of served requests must finish within "
                        "this many milliseconds (e2e)")
    p.add_argument("--slo-latency-target", type=float, default=99.0,
                   metavar="PCT",
                   help="latency SLO target in percent")
    p.add_argument("--slo-interval", type=float, default=1.0,
                   help="SLO evaluator tick, seconds")
    p.add_argument("--trace-dir", default=None,
                   help="capture an XProf trace of the load run here and "
                        "attribute device time per serve batch "
                        "(report key 'attribution', /debugz, trace_* "
                        "gauges)")
    p.add_argument("--attribution-every", type=int, default=0,
                   help="sampled continuous attribution: every N "
                        "dispatches, capture+attribute one batch and "
                        "publish live trace_* gauges under "
                        "program=serve_sampled (0 disables; mutually "
                        "exclusive with --trace-dir, whose profile owns "
                        "the profiler)")
    p.add_argument("--attribution-min-interval", type=float, default=30.0,
                   help="floor between attribution samples, seconds — "
                        "caps the amortized sampling overhead at "
                        "~capture cost / interval regardless of rps")
    p.add_argument("--memory-guard", action="store_true",
                   help="refuse to warm any bucket whose footprint-"
                        "ledger predicted peak exceeds the device limit "
                        "(or whose compile OOMs) instead of crashing — "
                        "serving degrades to the buckets that fit")
    p.add_argument("--memory-limit-bytes", type=int, default=None,
                   help="device-capacity override for the memory guard "
                        "(default: the device's memory_stats() limit)")
    p.add_argument("--no-memory-monitor", action="store_true",
                   help="disable the live device_hbm_* gauge sampler")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the report JSON here")
    return p


def _sharded_synthetic_engine(args, mesh_shape):
    """``--mesh HxW``: the sharded zero-artifact path — a spatial
    ResNet-v1 front over the tile mesh (serve/sharded.py), batcher and
    telemetry stack identical to the single-chip engine's."""
    from mpi4dl_tpu.serve.sharded import synthetic_sharded_engine

    return synthetic_sharded_engine(
        mesh_shape, image_size=args.image_size,
        depth=args.depth if args.depth != 11 else 8,  # v1 depths are 6n+2
        num_classes=args.classes,
        spatial_cells=(
            args.spatial_cells if args.spatial_cells is not None else 3
        ),
        calib_batches=args.calib_batches, conv_overlap=args.conv_overlap,
        max_batch=args.max_batch, max_wait_s=args.max_wait_ms / 1e3,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_ms / 1e3,
        metrics_port=args.metrics_port, telemetry_dir=args.telemetry_dir,
        **_liveness_kw(args),
    )


def _parse_tiled_size(spec: str) -> int:
    """``--tiled HxW`` → the (square) image extent; the synthetic tiled
    model's global-pool head needs H == W."""
    try:
        h, w = (int(p) for p in str(spec).lower().split("x"))
    except ValueError:
        raise SystemExit(
            f"--tiled must look like HxW (e.g. 8192x8192), got {spec!r}"
        ) from None
    if h != w:
        raise SystemExit(
            f"--tiled serves square images (the model head pools the "
            f"full feature map), got {h}x{w}"
        )
    return h


def _tiled_engine(args):
    """``--tiled HxW``: the gigapixel tile-streaming engine — synthetic
    by default, or the checkpoint's model served tiled (the size must
    match the checkpoint's, since the head is size-bound)."""
    from mpi4dl_tpu.serve.tiled import (
        synthetic_tiled_engine,
        tiled_engine_from_checkpoint,
    )

    size = _parse_tiled_size(args.tiled)
    kw = dict(
        tile=args.tile, tile_batch=args.tile_batch,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_ms / 1e3,
        metrics_port=args.metrics_port, telemetry_dir=args.telemetry_dir,
        **_liveness_kw(args),
    )
    if args.ckpt:
        eng = tiled_engine_from_checkpoint(args.ckpt, **kw)
        if eng.example_shape[0] != size:
            raise SystemExit(
                f"--tiled {size}x{size} does not match the checkpoint's "
                f"image size {eng.example_shape[0]} — the head is bound "
                "to the size the model was built for"
            )
        return eng
    return synthetic_tiled_engine(
        size, depth=args.depth if args.depth != 11 else 8,  # v1: 6n+2
        num_classes=args.classes, calib_batches=args.calib_batches,
        **kw,
    )


def _synthetic_engine(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine

    size = args.image_size
    cells = get_resnet_v2(
        depth=args.depth, num_classes=args.classes, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    x0 = jnp.zeros((1, size, size, 3), jnp.float32)
    params = init_cells(cells, jax.random.PRNGKey(0), x0)
    cal = [
        jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)
        for _ in range(args.calib_batches)
    ]
    stats = collect_batch_stats(cells, params, cal)
    return ServingEngine(
        cells, params, stats, example_shape=(size, size, 3),
        max_batch=args.max_batch, max_wait_s=args.max_wait_ms / 1e3,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_ms / 1e3,
        metrics_port=args.metrics_port, telemetry_dir=args.telemetry_dir,
        **_liveness_kw(args),
    )


def _liveness_kw(args) -> dict:
    return {
        "slo_classes": args.slo_classes,
        "tenants": args.tenants,
        "scheduler": args.scheduler,
        "watchdog_factor": args.watchdog_factor or None,
        "watchdog_min_timeout_s": args.watchdog_min_timeout,
        "flight_capacity": args.flight_capacity,
        "flight_dir": args.flight_dir,
        "slo": _slo_config(args),
        "attribution_every": args.attribution_every,
        "attribution_min_interval_s": args.attribution_min_interval,
        "memory_guard": args.memory_guard,
        "memory_limit_bytes": args.memory_limit_bytes,
        "memory_monitor": not args.no_memory_monitor,
        "tail_factor": args.tail_factor,
        "tail_min_interval_s": args.tail_min_interval,
        "tail_capacity": args.tail_capacity,
    }


def _slo_config(args):
    """``--slo-availability 99.9 --slo-latency-ms 50`` → SLOConfig (CLI
    speaks percent, the library speaks ratios); None when neither
    objective is requested."""
    if args.slo_availability is None and args.slo_latency_ms is None:
        return None
    from mpi4dl_tpu.telemetry import SLOConfig

    return SLOConfig(
        availability=(
            args.slo_availability / 100.0
            if args.slo_availability is not None else None
        ),
        latency_threshold_s=(
            args.slo_latency_ms / 1e3
            if args.slo_latency_ms is not None else None
        ),
        latency_target=args.slo_latency_target / 100.0,
        interval_s=args.slo_interval,
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import os

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    if args.tiled and args.mesh:
        raise SystemExit(
            "--tiled and --mesh are mutually exclusive: tiled streaming "
            "serves huge images on ONE chip; --mesh shards across chips"
        )

    mesh_shape = None
    if args.mesh:
        from mpi4dl_tpu.serve.sharded import parse_mesh

        mesh_shape = parse_mesh(args.mesh)
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # The tile mesh needs virtual devices before backend init
            # (the same simulation the test suite / analyze CLI use).
            from mpi4dl_tpu.compat import set_cpu_devices

            set_cpu_devices(max(8, mesh_shape[0] * mesh_shape[1]))

    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import (
        run_closed_loop,
        run_open_loop,
        serial_throughput,
    )

    if args.tiled:
        engine = _tiled_engine(args)
    elif args.ckpt and mesh_shape is not None:
        # Checkpoint → sharded serve: the spatial twin's builder args ride
        # in the checkpoint metadata (model_metadata(spatial_cells=...)),
        # so the path + mesh is all the config needed.
        from mpi4dl_tpu.serve.sharded import sharded_engine_from_checkpoint

        engine = sharded_engine_from_checkpoint(
            args.ckpt, mesh_shape, spatial_cells=args.spatial_cells,
            conv_overlap=args.conv_overlap,
            max_batch=args.max_batch, max_wait_s=args.max_wait_ms / 1e3,
            max_queue=args.max_queue,
            default_deadline_s=args.deadline_ms / 1e3,
            metrics_port=args.metrics_port,
            telemetry_dir=args.telemetry_dir,
            **_liveness_kw(args),
        )
    elif args.ckpt:
        engine = ServingEngine.from_checkpoint(
            args.ckpt, max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1e3, max_queue=args.max_queue,
            default_deadline_s=args.deadline_ms / 1e3,
            metrics_port=args.metrics_port, telemetry_dir=args.telemetry_dir,
            **_liveness_kw(args),
        )
    elif mesh_shape is not None:
        engine = _sharded_synthetic_engine(args, mesh_shape)
    else:
        engine = _synthetic_engine(args)

    # Postmortem on SIGTERM: dump the flight ring before the default
    # disposition terminates the process.
    engine.flight.install_signal_handlers()

    # Supervised replica (elastic.supervise / the fleet babysitter):
    # health-gated heartbeat — a wedged batcher trips the watchdog, the
    # beats stop, the supervisor kills and restarts this process.
    from mpi4dl_tpu import elastic

    heartbeat = None
    hb_path = elastic.heartbeat_path_from_env()
    if hb_path:
        heartbeat = elastic.HeartbeatReporter(
            hb_path, health=engine.health, watchdog=engine.watchdog,
        )
        heartbeat.start()

    if args.ckpt:
        model_name = "checkpoint:" + args.ckpt
    elif args.tiled:
        model_name = (
            f"synthetic_resnet_tiled{engine.example_shape[0]}px"
        )
    else:
        model_name = f"synthetic_resnet{args.depth}_{args.image_size}px"
    report = {
        "model": model_name,
        "buckets": list(engine.buckets),
        "mesh": list(engine.mesh_shape),
    }
    if engine.metrics_port is not None:
        report["metrics_port"] = engine.metrics_port
        # stderr, not stdout: the stdout protocol is "keep the last JSON
        # line", and the scrape URL must be visible while the run is live.
        endpoints = "/healthz, /debugz" + (
            ", /alertz" if engine.slo is not None else ""
        )
        print(
            f"# metrics: http://127.0.0.1:{engine.metrics_port}/metrics "
            f"(also {endpoints})",
            file=sys.stderr, flush=True,
        )
    if args.serial:
        report["serial"] = serial_throughput(engine, args.serial)

    from contextlib import nullcontext

    from mpi4dl_tpu.profiling import trace as profiler_trace

    engine.start()
    try:
        with profiler_trace(args.trace_dir) if args.trace_dir \
                else nullcontext():
            retry_kw = {
                "queue_full_retries": args.queue_full_retries,
                "retry_backoff_s": (
                    args.retry_backoff_ms / 1e3
                    if args.retry_backoff_ms is not None else None
                ),
            }
            if args.class_mix:
                from mpi4dl_tpu.serve.loadgen import ClassMix

                retry_kw["class_mix"] = ClassMix.parse(args.class_mix)
            if args.tenant_mix:
                from mpi4dl_tpu.serve.loadgen import TenantMix

                retry_kw["tenant_mix"] = TenantMix.parse(args.tenant_mix)
            if args.mode == "closed":
                report["loadgen"] = run_closed_loop(
                    engine, args.requests, concurrency=args.concurrency,
                    deadline_s=args.deadline_ms / 1e3,
                    events=engine.events, **retry_kw,
                )
            else:
                report["loadgen"] = run_open_loop(
                    engine, rate_rps=args.rate, duration_s=args.duration,
                    deadline_s=args.deadline_ms / 1e3,
                    events=engine.events, **retry_kw,
                )
    finally:
        engine.stop()
        if heartbeat is not None:
            heartbeat.close()

    if args.trace_dir:
        try:
            from mpi4dl_tpu.analysis.trace import (
                analyze_trace_dir,
                publish_attribution,
            )

            summary = analyze_trace_dir(
                args.trace_dir, step_name="mpi4dl_serve_batch"
            )
            publish_attribution(
                summary, engine.registry, program="serve_batch"
            )
            engine.set_attribution(summary)
            report["attribution"] = {
                k: summary[k]
                for k in ("n_steps", "per_step_mean", "range", "collective")
            }
        except Exception as e:  # noqa: BLE001 — attribution is advisory;
            # the load report must survive a broken trace
            report["attribution"] = {
                "error": f"{type(e).__name__}: {str(e)[:160]}"
            }

    if args.attribution_every and engine.last_attribution is not None:
        # The most recent sampled capture (the live gauges' source).
        report["attribution_sampled"] = engine.last_attribution

    if args.tiled:
        # Per-request tile counts + stitch/stream latency percentiles —
        # the loadgen numbers a gigapixel surface is judged by alongside
        # p50/p90/p99.
        report["tiled"] = engine.stats().get("tiled")

    if engine.slo is not None:
        report["slo"] = engine.slo.verdict()

    if args.serial and report["serial"]["throughput_rps"] > 0:
        report["speedup_vs_serial"] = (
            report["loadgen"]["throughput_rps"]
            / report["serial"]["throughput_rps"]
        )

    lint_failed = False
    if args.lint:
        rep = engine.lint_report()
        report["lint"] = {
            "ok": rep.ok,
            "summary": rep.summary_line(),
            "findings": rep.findings,
        }
        lint_failed = not rep.ok

    line = json.dumps(report)
    print(line, flush=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return 2 if lint_failed else 0


if __name__ == "__main__":
    sys.exit(main())
