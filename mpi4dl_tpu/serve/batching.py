"""Bucket policy + batch padding for the serving engine.

Dynamic micro-batching serves variable-sized request groups through a
FIXED set of pre-compiled executables: batch sizes are rounded up to
power-of-two buckets, the batch is right-padded with zeros into the
bucket, and pad rows are sliced off the logits afterwards. Power-of-two
buckets bound the compile count at ``log2(max_batch)+1`` executables while
wasting at most 2x compute on a worst-case batch — and a padded row is
provably inert: every op in the frozen-stats forward (conv, frozen BN,
pool, dense) is per-sample along the batch axis, so real rows are
bit-identical whatever rides in the padding (tested in
``tests/test_serve.py``).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def power_of_two_buckets(max_batch: int) -> tuple[int, ...]:
    """``(1, 2, 4, ..., max_batch)``; ``max_batch`` must itself be a power
    of two so the largest bucket is reachable."""
    if max_batch < 1 or (max_batch & (max_batch - 1)):
        raise ValueError(f"max_batch must be a power of two >= 1, got {max_batch}")
    out = []
    b = 1
    while b <= max_batch:
        out.append(b)
        b *= 2
    return tuple(out)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n`` requests. Raises when ``n`` exceeds
    every bucket — the batch former must never build an oversized batch."""
    if n < 1:
        raise ValueError(f"need at least one request, got {n}")
    for b in sorted(buckets):
        if b >= n:
            return b
    raise ValueError(f"no bucket fits {n} requests (buckets: {sorted(buckets)})")


def pad_batch(examples: Sequence[np.ndarray], bucket: int, dtype) -> np.ndarray:
    """Stack per-request examples and right-pad with zeros to ``bucket``
    rows. Examples must share one shape (the engine's configured
    ``example_shape``)."""
    n = len(examples)
    if n > bucket:
        raise ValueError(f"{n} examples exceed bucket {bucket}")
    first = np.asarray(examples[0])
    out = np.zeros((bucket, *first.shape), dtype)
    for i, ex in enumerate(examples):
        ex = np.asarray(ex)
        if ex.shape != first.shape:
            raise ValueError(
                f"examples must share one shape; got {first.shape} and {ex.shape}"
            )
        out[i] = ex
    return out
