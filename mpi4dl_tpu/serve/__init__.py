"""Online serving: the inference workload the north star demands.

The reference framework never evaluates, let alone serves;
:mod:`mpi4dl_tpu.evaluate` added offline batch eval, and this package adds
the online path: a :class:`ServingEngine` that restores a calibrated model
from a self-describing checkpoint, pre-compiles one executable per
power-of-two batch bucket at startup (no request ever pays a JIT), and
runs a dynamic micro-batching request loop — bounded-queue admission
control, per-request deadlines, max-wait/max-size batch formation,
right-padding into the nearest bucket, and double-buffered host→device
staging so the next batch's transfer overlaps the current batch's compute.

Entry points:

- :class:`ServingEngine` / :meth:`ServingEngine.from_checkpoint` — the
  library surface;
- :mod:`mpi4dl_tpu.serve.sharded` — multi-chip sharded serving: every
  bucket runs as the trainer's spatially-partitioned forward over a
  ``tile_h×tile_w`` mesh (``--mesh HxW``; docs/SERVING.md "Multi-chip
  sharded serving"), for models whose single-chip forward doesn't fit;
- ``python -m mpi4dl_tpu.serve`` — CLI: restore (or synthesize) a model,
  warm up, drive a closed/open-loop load test, print one JSON report;
- :mod:`mpi4dl_tpu.serve.loadgen` — the load-generation library behind
  ``benchmarks/serving/`` and the bench.py serving hook.

Fully instrumented through :mod:`mpi4dl_tpu.telemetry`: request-lifecycle
spans, outcome/queue-depth/bucket-occupancy metrics, an opt-in Prometheus
scrape endpoint (``metrics_port=`` / ``--metrics-port``) and JSONL span
log (``MPI4DL_TPU_TELEMETRY_DIR``) — and, with an
:class:`~mpi4dl_tpu.telemetry.SLOConfig` (``slo=`` /
``--slo-availability`` / ``--slo-latency-ms``), continuous SLO
evaluation: error-budget burn-rate alerting on ``/alertz`` and the
advisory ``autoscale_desired_replicas`` fleet signal.

See ``docs/SERVING.md`` for architecture, bucket policy, and deadline
semantics; ``docs/OBSERVABILITY.md`` for the metric catalog.
"""

from mpi4dl_tpu.serve.batching import (  # noqa: F401
    bucket_for,
    pad_batch,
    power_of_two_buckets,
)
from mpi4dl_tpu.serve.scheduler import (  # noqa: F401
    ClassFeedback,
    ClassScheduler,
    SLOClass,
    parse_slo_classes,
)
from mpi4dl_tpu.serve.engine import (  # noqa: F401
    DeadlineExceededError,
    DrainedError,
    QueueFullError,
    ServingEngine,
    SingleChipPredictor,
)
from mpi4dl_tpu.serve.sharded import (  # noqa: F401
    ShardedPredictor,
    parse_mesh,
    sharded_engine,
    synthetic_sharded_engine,
)
from mpi4dl_tpu.serve.tiled import (  # noqa: F401
    TiledPredictor,
    TileGeometry,
    synthetic_tiled_engine,
    tile_geometry,
    tiled_engine,
    tiled_engine_from_checkpoint,
)
