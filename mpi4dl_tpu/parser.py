"""CLI parser — flag-for-flag parity with reference ``src/torchgems/parser.py:21-143``.

Same flags, same defaults, same semantics where they transfer to TPU. Flags
that are launcher-specific in the reference (``--num-workers`` for DataLoader
workers) are kept for CLI compatibility and used where meaningful.
"""

import argparse


def get_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="SP-MP-DP Configuration Script (TPU-native)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )

    parser.add_argument(
        "-v",
        "--verbose",
        help="Prints performance numbers or logs",
        action="store_true",
    )
    parser.add_argument("--batch-size", type=int, default=32, help="input batch size")
    parser.add_argument(
        "--parts", type=int, default=1, help="Number of micro-batches per pipeline step"
    )
    parser.add_argument(
        "--split-size", type=int, default=2, help="Number of pipeline (LP) stages"
    )
    parser.add_argument(
        "--num-spatial-parts",
        type=str,
        default="4",
        help="Number of partitions in spatial parallelism (csv for multi-stage SP)",
    )
    parser.add_argument(
        "--spatial-size",
        type=int,
        default=1,
        help="Number of model stages that run spatially partitioned",
    )
    parser.add_argument(
        "--times",
        type=int,
        default=1,
        help="GEMS-MASTER replication factor (1: 2 replications, 2: 4 replications)",
    )
    parser.add_argument(
        "--image-size", type=int, default=32, help="Image size for synthetic benchmark"
    )
    parser.add_argument("--num-epochs", type=int, default=1, help="Number of epochs")
    parser.add_argument(
        "--num-layers", type=int, default=18, help="Number of layers in amoebanet"
    )
    parser.add_argument(
        "--num-filters", type=int, default=416, help="Number of filters in amoebanet"
    )
    parser.add_argument("--num-classes", type=int, default=10, help="Number of classes")
    parser.add_argument(
        "--balance",
        type=str,
        default=None,
        help="csv; length equals number of partitions, sum equals num layers",
    )
    parser.add_argument(
        "--halo-D2",
        dest="halo_d2",
        action="store_true",
        default=False,
        help="Enable design2 (one wide halo exchange amortized over fused convs)",
    )
    parser.add_argument(
        "--fused-layers",
        type=int,
        default=1,
        help="With --halo-D2, number of blocks sharing one halo exchange",
    )
    parser.add_argument(
        "--local-DP",
        type=int,
        default=1,
        help="LBANN-style local data parallelism inside the LP stages after SP",
    )
    parser.add_argument(
        "--slice-method",
        type=str,
        default="square",
        help="Slice method (square, vertical, and horizontal) in Spatial parallelism",
    )
    parser.add_argument(
        "--app",
        type=int,
        default=3,
        help="Application type (1.medical, 2.cifar, 3.synthetic)",
    )
    parser.add_argument(
        "--datapath",
        type=str,
        default="./train",
        help="local Dataset path",
    )
    parser.add_argument(
        "--enable-master-comm-opt",
        dest="enable_master_comm_opt",
        action="store_true",
        default=False,
        help="Enable communication optimization for MASTER in Spatial",
    )
    parser.add_argument(
        "--num-workers",
        type=int,
        default=0,
        help="Data loading workers (kept for CLI parity)",
    )
    parser.add_argument(
        "--max-steps",
        type=int,
        default=None,
        help="Stop after N steps per epoch (TPU-native addition for smoke runs)",
    )
    parser.add_argument(
        "--precision",
        type=str,
        default="bf16",
        choices=["bf16", "fp32"],
        help="Compute precision (TPU-native addition; MXU prefers bf16)",
    )
    parser.add_argument(
        "--checkpoint-dir",
        type=str,
        default=None,
        help="Save checkpoints here (TPU-native addition; the reference has "
        "no persistence at all)",
    )
    parser.add_argument(
        "--checkpoint-every",
        type=int,
        default=100,
        help="Steps between checkpoints (with --checkpoint-dir)",
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        default=False,
        help="Resume from the newest checkpoint in --checkpoint-dir",
    )
    parser.add_argument(
        "--trace-dir",
        type=str,
        default=None,
        help="Write a jax.profiler trace here (TPU-native addition)",
    )
    parser.add_argument(
        "--eval-batches",
        type=int,
        default=0,
        help="After training: calibrate BN on N batches and evaluate on N "
        "more (TPU-native addition; the reference has no eval path)",
    )
    parser.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="Supervise the run: on crash or hang, restart up to N times, "
        "resuming from the newest checkpoint (TPU-native addition; the "
        "reference hangs the MPI world on any rank failure)",
    )
    parser.add_argument(
        "--hang-timeout",
        type=float,
        default=None,
        help="With --max-restarts: seconds without a training-step "
        "heartbeat before the child is declared wedged and restarted "
        "(must exceed the first step's XLA compile time)",
    )
    return parser
