from mpi4dl_tpu.models.resnet import get_resnet_v1, get_resnet_v2  # noqa: F401
