"""AmoebaNet-D — capability parity with reference ``src/models/amoebanet.py``
(plain + spatial) as one builder with per-cell ``spatial`` flags.

Architecture parity (file:line are reference cites):
- ``Operation`` factories (``amoebanet.py:88-291``): ``none`` (identity /
  FactorizedReduce at stride 2), ``avg_pool_3x3`` (count_include_pad=False),
  ``max_pool_3x3``, ``max_pool_2x2``, ``conv_1x7_7x1`` (c→c/4 bottleneck with
  1×7 then 7×1), ``conv_1x1``, ``conv_3x3`` (c→c/4 bottleneck).
- genotype tables ``NORMAL_OPERATIONS``/``NORMAL_CONCAT`` (TF-implementation
  variant ``[0,3,4,6]``), ``REDUCTION_*`` (``amoebanet.py:295-351``) — the
  AmoebaNet-D genotype from Real et al. 2018 as fixed by the GPipe paper.
- ``Stem`` (relu→3×3 s2 conv→BN, ``amoebanet.py:417-446``), ``Cell``
  (two-state DAG returning ``(concat, skip)`` — the tuple-valued stage
  interface the pipeline's MULTIPLE_INPUT/OUTPUT machinery exists for,
  ``amoebanet.py:449-532``), ``Classify`` (global avg pool → linear,
  ``amoebanet.py:401-414``).
- builders ``amoebanetd`` / ``amoebanetd_spatial`` (``amoebanet.py:535-737``):
  stem1 + 2 reduction stems + [normal×r, reduction, normal×r, reduction,
  normal×r] + classify, ``r = num_layers//3``, channels = num_filters/4
  doubled at each reduction; spatial variant flips cells plain after the SP
  stage boundary.

Deliberate deviations (documented, not accidental):
- reference ``max_pool_3x3`` constructs an **Avg**Pool2d in both branches
  (``amoebanet.py:110-125``) — an apparent copy-paste slip; we implement a
  real max pool.
- reference ``FactorizedReduce`` feeds both 1×1 convs the same input (the
  pixel-shifted second path is commented out, ``amoebanet.py:74-76``); we
  reproduce the *active* behavior.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mpi4dl_tpu.ops.layers import Conv2d, Identity, Pool, TrainBatchNorm, TILE_AXES


def _bn_axes(spatial: bool, cross_tile_bn: bool) -> tuple[str, ...]:
    return TILE_AXES if (spatial and cross_tile_bn) else ()


class ReluConvBn(nn.Module):
    """relu → conv → BN (ref ``relu_conv_bn``, ``amoebanet.py:365-398``)."""

    features: int
    kernel_size: Any = 1
    strides: Any = 1
    padding: Any = 0
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = Conv2d(
            features=self.features,
            kernel_size=self.kernel_size,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
            name="conv",
        )(x)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class FactorizedReduce(nn.Module):
    """relu → concat(1×1 s2 conv, 1×1 s2 conv) → BN (ref ``amoebanet.py:56-78``;
    both convs see the same input — the shifted path is commented out there)."""

    features: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        common = dict(
            kernel_size=1,
            strides=2,
            padding=0,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
        )
        a = Conv2d(features=self.features // 2, name="conv1", **common)(x)
        b = Conv2d(features=self.features - self.features // 2, name="conv2", **common)(x)
        x = jnp.concatenate([a, b], axis=-1)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class ConvBranch(nn.Module):
    """Shared body for the conv_* operations: an optional c→c/4 bottleneck
    around a list of (kernel, stride, padding) convs (refs
    ``conv_1x7_7x1`` ``amoebanet.py:246-291``, ``conv_1x1`` ``:240-248``,
    ``conv_3x3`` ``:250-291``)."""

    channels: int
    convs: Sequence[tuple[Any, Any, Any]]  # (kernel, stride, padding) each
    bottleneck: bool = False
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        c = self.channels
        inner = c // 4 if self.bottleneck else c
        common = dict(
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
        )
        idx = 0
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=inner, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
            idx += 1
        for k, s, p in self.convs:
            x = nn.relu(x)
            x = Conv2d(features=inner, kernel_size=k, strides=s, padding=p, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
            idx += 1
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=c, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
        return x


# -- operation factories (ref amoebanet.py:81-291) ---------------------------


def op_none(channels, stride, spatial, bn_axes, dtype, name):
    if stride == 1:
        return Identity(name=name)
    return FactorizedReduce(
        features=channels, spatial=spatial, bn_reduce_axes=bn_axes, dtype=dtype, name=name
    )


def op_avg_pool_3x3(channels, stride, spatial, bn_axes, dtype, name):
    return Pool(
        kind="avg",
        kernel_size=3,
        strides=stride,
        padding=1,
        spatial=spatial,
        count_include_pad=False,
        name=name,
    )


def op_max_pool_3x3(channels, stride, spatial, bn_axes, dtype, name):
    # Reference builds AvgPool2d here in both branches (amoebanet.py:110-125)
    # — we implement the op its name (and the genotype) means.
    return Pool(
        kind="max", kernel_size=3, strides=stride, padding=1, spatial=spatial, name=name
    )


def op_max_pool_2x2(channels, stride, spatial, bn_axes, dtype, name):
    return Pool(
        kind="max", kernel_size=2, strides=stride, padding=0, spatial=spatial, name=name
    )


def op_conv_1x7_7x1(channels, stride, spatial, bn_axes, dtype, name):
    return ConvBranch(
        channels=channels,
        convs=[((1, 7), (1, stride), (0, 3)), ((7, 1), (stride, 1), (3, 0))],
        bottleneck=True,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


def op_conv_1x1(channels, stride, spatial, bn_axes, dtype, name):
    # Reference keeps conv_1x1 plain even under SP (no halo needed for 1x1,
    # amoebanet.py:240-248) — spatial flag is harmless but kept for stride-2.
    return ConvBranch(
        channels=channels,
        convs=[(1, stride, 0)],
        bottleneck=False,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


def op_conv_3x3(channels, stride, spatial, bn_axes, dtype, name):
    return ConvBranch(
        channels=channels,
        convs=[(3, stride, 1)],
        bottleneck=True,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


# AmoebaNet-D genotype (ref amoebanet.py:295-351; NORMAL_CONCAT follows the
# TF implementation, see the long comment there).
NORMAL_OPERATIONS = [
    (1, op_conv_1x1),
    (1, op_max_pool_3x3),
    (1, op_none),
    (0, op_conv_1x7_7x1),
    (0, op_conv_1x1),
    (0, op_conv_1x7_7x1),
    (2, op_max_pool_3x3),
    (2, op_none),
    (1, op_avg_pool_3x3),
    (5, op_conv_1x1),
]
NORMAL_CONCAT = [0, 3, 4, 6]

REDUCTION_OPERATIONS = [
    (0, op_max_pool_2x2),
    (0, op_max_pool_3x3),
    (2, op_none),
    (1, op_conv_3x3),
    (2, op_conv_1x7_7x1),
    (2, op_max_pool_3x3),
    (3, op_none),
    (1, op_max_pool_2x2),
    (2, op_avg_pool_3x3),
    (3, op_conv_1x1),
]
REDUCTION_CONCAT = [4, 5, 6]


class Stem(nn.Module):
    """relu → 3×3 stride-2 conv → BN (ref ``Stem``, ``amoebanet.py:417-446``)."""

    channels: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = Conv2d(
            features=self.channels,
            kernel_size=3,
            strides=2,
            padding=1,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
            name="conv",
        )(x)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class Classify(nn.Module):
    """Global avg pool → linear on the concat state (ref ``Classify``,
    ``amoebanet.py:401-414``)."""

    num_classes: int
    dtype: Any = None

    @nn.compact
    def __call__(self, states):
        x, _ = states
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


class AmoebaCell(nn.Module):
    """Two-state NAS cell (ref ``Cell``, ``amoebanet.py:449-532``).

    Input: a tensor (after the stem) or ``(s, skip)`` tuple. Output:
    ``(concat, skip)`` — the tuple stage interface that exercises the
    pipeline's pytree-valued wires.
    """

    channels_prev_prev: int
    channels_prev: int
    channels: int
    reduction: bool
    reduction_prev: bool
    spatial: bool = False
    cross_tile_bn: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, input_or_states):
        if isinstance(input_or_states, (tuple, list)):
            s1, s2 = input_or_states
        else:
            s1 = s2 = input_or_states
        skip = s1

        bn_axes = _bn_axes(self.spatial, self.cross_tile_bn)
        common = dict(
            spatial=self.spatial, bn_reduce_axes=bn_axes, dtype=self.dtype
        )
        s1 = ReluConvBn(features=self.channels, name="reduce1", **common)(s1)
        if self.reduction_prev:
            s2 = FactorizedReduce(features=self.channels, name="reduce2", **common)(s2)
        elif self.channels_prev_prev != self.channels:
            s2 = ReluConvBn(features=self.channels, name="reduce2", **common)(s2)

        if self.reduction:
            indices_ops, concat = REDUCTION_OPERATIONS, REDUCTION_CONCAT
        else:
            indices_ops, concat = NORMAL_OPERATIONS, NORMAL_CONCAT

        states = [s1, s2]
        for i in range(0, len(indices_ops), 2):
            i1, f1 = indices_ops[i]
            i2, f2 = indices_ops[i + 1]
            stride1 = 2 if (self.reduction and i1 < 2) else 1
            stride2 = 2 if (self.reduction and i2 < 2) else 1
            h1 = f1(self.channels, stride1, self.spatial, bn_axes, self.dtype, f"op{i}")(
                states[i1]
            )
            h2 = f2(self.channels, stride2, self.spatial, bn_axes, self.dtype, f"op{i+1}")(
                states[i2]
            )
            states.append(h1 + h2)

        return jnp.concatenate([states[i] for i in concat], axis=-1), skip


def amoebanetd(
    num_classes: int = 10,
    num_layers: int = 4,
    num_filters: int = 512,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """AmoebaNet-D as a flat cell list (refs ``amoebanetd``
    ``amoebanet.py:535-615`` and ``amoebanetd_spatial`` ``:618-737`` unified:
    the first ``spatial_cells`` cells are spatial, the rest plain — the
    reference's ``layers_processed >= end_layer`` flip).

    Cell sequence: stem1, 2 reduction stems, then r normal / reduction /
    r normal / reduction / r normal (r = num_layers // 3), classifier.
    """
    if num_layers % 3:
        raise ValueError("num_layers must be a multiple of 3")
    r = num_layers // 3
    channels = num_filters // 4
    cells: list[nn.Module] = []

    state = dict(
        channels_prev_prev=channels, channels_prev=channels, reduction_prev=False,
        channels=channels,
    )

    def sp():
        return len(cells) < spatial_cells

    def add_cell(reduction: bool, channels_scale: int):
        state["channels"] *= channels_scale
        spatial = sp()
        cell = AmoebaCell(
            channels_prev_prev=state["channels_prev_prev"],
            channels_prev=state["channels_prev"],
            channels=state["channels"],
            reduction=reduction,
            reduction_prev=state["reduction_prev"],
            spatial=spatial,
            cross_tile_bn=cross_tile_bn,
            dtype=dtype,
        )
        concat = REDUCTION_CONCAT if reduction else NORMAL_CONCAT
        state["channels_prev_prev"] = state["channels_prev"]
        state["channels_prev"] = state["channels"] * len(concat)
        state["reduction_prev"] = reduction
        cells.append(cell)

    cells.append(
        Stem(
            channels=channels,
            spatial=sp(),
            bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
            dtype=dtype,
        )
    )
    add_cell(reduction=True, channels_scale=2)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    cells.append(Classify(num_classes=num_classes, dtype=dtype))
    return cells
