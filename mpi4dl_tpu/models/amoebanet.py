"""AmoebaNet-D — capability parity with reference ``src/models/amoebanet.py``
(plain + spatial) as one builder with per-cell ``spatial`` flags.

Architecture parity (file:line are reference cites):
- ``Operation`` factories (``amoebanet.py:88-291``): ``none`` (identity /
  FactorizedReduce at stride 2), ``avg_pool_3x3`` (count_include_pad=False),
  ``max_pool_3x3``, ``max_pool_2x2``, ``conv_1x7_7x1`` (c→c/4 bottleneck with
  1×7 then 7×1), ``conv_1x1``, ``conv_3x3`` (c→c/4 bottleneck).
- genotype tables ``NORMAL_OPERATIONS``/``NORMAL_CONCAT`` (TF-implementation
  variant ``[0,3,4,6]``), ``REDUCTION_*`` (``amoebanet.py:295-351``) — the
  AmoebaNet-D genotype from Real et al. 2018 as fixed by the GPipe paper.
- ``Stem`` (relu→3×3 s2 conv→BN, ``amoebanet.py:417-446``), ``Cell``
  (two-state DAG returning ``(concat, skip)`` — the tuple-valued stage
  interface the pipeline's MULTIPLE_INPUT/OUTPUT machinery exists for,
  ``amoebanet.py:449-532``), ``Classify`` (global avg pool → linear,
  ``amoebanet.py:401-414``).
- builders ``amoebanetd`` / ``amoebanetd_spatial`` (``amoebanet.py:535-737``):
  stem1 + 2 reduction stems + [normal×r, reduction, normal×r, reduction,
  normal×r] + classify, ``r = num_layers//3``, channels = num_filters/4
  doubled at each reduction; spatial variant flips cells plain after the SP
  stage boundary.

Deliberate deviations (documented, not accidental):
- reference ``max_pool_3x3`` constructs an **Avg**Pool2d in both branches
  (``amoebanet.py:110-125``) — an apparent copy-paste slip; we implement a
  real max pool.
- reference ``FactorizedReduce`` feeds both 1×1 convs the same input (the
  pixel-shifted second path is commented out, ``amoebanet.py:74-76``); we
  reproduce the *active* behavior.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mpi4dl_tpu.ops.layers import (
    Conv2d,
    HaloExchange,
    Identity,
    Pool,
    TrainBatchNorm,
    TILE_AXES,
)


def _bn_axes(spatial: bool, cross_tile_bn: bool) -> tuple[str, ...]:
    return TILE_AXES if (spatial and cross_tile_bn) else ()


class ReluConvBn(nn.Module):
    """relu → conv → BN (ref ``relu_conv_bn``, ``amoebanet.py:365-398``)."""

    features: int
    kernel_size: Any = 1
    strides: Any = 1
    padding: Any = 0
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = Conv2d(
            features=self.features,
            kernel_size=self.kernel_size,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
            name="conv",
        )(x)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class FactorizedReduce(nn.Module):
    """relu → concat(1×1 s2 conv, 1×1 s2 conv) → BN (ref ``amoebanet.py:56-78``;
    both convs see the same input — the shifted path is commented out there)."""

    features: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        common = dict(
            kernel_size=1,
            strides=2,
            padding=0,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
        )
        a = Conv2d(features=self.features // 2, name="conv1", **common)(x)
        b = Conv2d(features=self.features - self.features // 2, name="conv2", **common)(x)
        x = jnp.concatenate([a, b], axis=-1)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class ConvBranch(nn.Module):
    """Shared body for the conv_* operations: an optional c→c/4 bottleneck
    around a list of (kernel, stride, padding) convs (refs
    ``conv_1x7_7x1`` ``amoebanet.py:246-291``, ``conv_1x1`` ``:240-248``,
    ``conv_3x3`` ``:250-291``)."""

    channels: int
    convs: Sequence[tuple[Any, Any, Any]]  # (kernel, stride, padding) each
    bottleneck: bool = False
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        c = self.channels
        inner = c // 4 if self.bottleneck else c
        common = dict(
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
        )
        idx = 0
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=inner, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
            idx += 1
        for k, s, p in self.convs:
            x = nn.relu(x)
            x = Conv2d(features=inner, kernel_size=k, strides=s, padding=p, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
            idx += 1
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=c, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name=f"bn{idx}")(x)
        return x


# -- operation factories (ref amoebanet.py:81-291) ---------------------------


def op_none(channels, stride, spatial, bn_axes, dtype, name):
    if stride == 1:
        return Identity(name=name)
    return FactorizedReduce(
        features=channels, spatial=spatial, bn_reduce_axes=bn_axes, dtype=dtype, name=name
    )


def op_avg_pool_3x3(channels, stride, spatial, bn_axes, dtype, name):
    return Pool(
        kind="avg",
        kernel_size=3,
        strides=stride,
        padding=1,
        spatial=spatial,
        count_include_pad=False,
        name=name,
    )


def op_max_pool_3x3(channels, stride, spatial, bn_axes, dtype, name):
    # Reference builds AvgPool2d here in both branches (amoebanet.py:110-125)
    # — we implement the op its name (and the genotype) means.
    return Pool(
        kind="max", kernel_size=3, strides=stride, padding=1, spatial=spatial, name=name
    )


def op_max_pool_2x2(channels, stride, spatial, bn_axes, dtype, name):
    return Pool(
        kind="max", kernel_size=2, strides=stride, padding=0, spatial=spatial, name=name
    )


def op_conv_1x7_7x1(channels, stride, spatial, bn_axes, dtype, name):
    return ConvBranch(
        channels=channels,
        convs=[((1, 7), (1, stride), (0, 3)), ((7, 1), (stride, 1), (3, 0))],
        bottleneck=True,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


def op_conv_1x1(channels, stride, spatial, bn_axes, dtype, name):
    # Reference keeps conv_1x1 plain even under SP (no halo needed for 1x1,
    # amoebanet.py:240-248) — spatial flag is harmless but kept for stride-2.
    return ConvBranch(
        channels=channels,
        convs=[(1, stride, 0)],
        bottleneck=False,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


def op_conv_3x3(channels, stride, spatial, bn_axes, dtype, name):
    return ConvBranch(
        channels=channels,
        convs=[(3, stride, 1)],
        bottleneck=True,
        spatial=spatial,
        bn_reduce_axes=bn_axes,
        dtype=dtype,
        name=name,
    )


# AmoebaNet-D genotype (ref amoebanet.py:295-351; NORMAL_CONCAT follows the
# TF implementation, see the long comment there).
NORMAL_OPERATIONS = [
    (1, op_conv_1x1),
    (1, op_max_pool_3x3),
    (1, op_none),
    (0, op_conv_1x7_7x1),
    (0, op_conv_1x1),
    (0, op_conv_1x7_7x1),
    (2, op_max_pool_3x3),
    (2, op_none),
    (1, op_avg_pool_3x3),
    (5, op_conv_1x1),
]
NORMAL_CONCAT = [0, 3, 4, 6]

REDUCTION_OPERATIONS = [
    (0, op_max_pool_2x2),
    (0, op_max_pool_3x3),
    (2, op_none),
    (1, op_conv_3x3),
    (2, op_conv_1x7_7x1),
    (2, op_max_pool_3x3),
    (3, op_none),
    (1, op_max_pool_2x2),
    (2, op_avg_pool_3x3),
    (3, op_conv_1x1),
]
REDUCTION_CONCAT = [4, 5, 6]


class Stem(nn.Module):
    """relu → 3×3 stride-2 conv → BN (ref ``Stem``, ``amoebanet.py:417-446``)."""

    channels: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = Conv2d(
            features=self.channels,
            kernel_size=3,
            strides=2,
            padding=1,
            use_bias=False,
            spatial=self.spatial,
            dtype=self.dtype,
            name="conv",
        )(x)
        return TrainBatchNorm(
            reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
        )(x)


class Classify(nn.Module):
    """Global avg pool → linear on the concat state (ref ``Classify``,
    ``amoebanet.py:401-414``)."""

    num_classes: int
    dtype: Any = None

    @nn.compact
    def __call__(self, states):
        x, _ = states
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


class AmoebaCell(nn.Module):
    """Two-state NAS cell (ref ``Cell``, ``amoebanet.py:449-532``).

    Input: a tensor (after the stem) or ``(s, skip)`` tuple. Output:
    ``(concat, skip)`` — the tuple stage interface that exercises the
    pipeline's pytree-valued wires.
    """

    channels_prev_prev: int
    channels_prev: int
    channels: int
    reduction: bool
    reduction_prev: bool
    spatial: bool = False
    cross_tile_bn: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, input_or_states):
        if isinstance(input_or_states, (tuple, list)):
            s1, s2 = input_or_states
        else:
            s1 = s2 = input_or_states
        skip = s1

        bn_axes = _bn_axes(self.spatial, self.cross_tile_bn)
        common = dict(
            spatial=self.spatial, bn_reduce_axes=bn_axes, dtype=self.dtype
        )
        s1 = ReluConvBn(features=self.channels, name="reduce1", **common)(s1)
        if self.reduction_prev:
            s2 = FactorizedReduce(features=self.channels, name="reduce2", **common)(s2)
        elif self.channels_prev_prev != self.channels:
            s2 = ReluConvBn(features=self.channels, name="reduce2", **common)(s2)

        if self.reduction:
            indices_ops, concat = REDUCTION_OPERATIONS, REDUCTION_CONCAT
        else:
            indices_ops, concat = NORMAL_OPERATIONS, NORMAL_CONCAT

        states = [s1, s2]
        for i in range(0, len(indices_ops), 2):
            i1, f1 = indices_ops[i]
            i2, f2 = indices_ops[i + 1]
            stride1 = 2 if (self.reduction and i1 < 2) else 1
            stride2 = 2 if (self.reduction and i2 < 2) else 1
            h1 = f1(self.channels, stride1, self.spatial, bn_axes, self.dtype, f"op{i}")(
                states[i1]
            )
            h2 = f2(self.channels, stride2, self.spatial, bn_axes, self.dtype, f"op{i+1}")(
                states[i2]
            )
            states.append(h1 + h2)

        return jnp.concatenate([states[i] for i in concat], axis=-1), skip


# -- D2 (fused-halo) design --------------------------------------------------
#
# Reference ``src/models/amoebanet_d2.py`` (``Cell_D2`` ``:569-678``,
# padding-free op variants ``:88-313``, genotype ``NORMAL_OPERATIONS_D2``
# ``:389-456``): instead of a halo exchange inside every windowed op of every
# normal cell, the cell pre-fetches wide halos with standalone exchanges
# (there: halo 3 + halo 2 states) and runs the ops VALID, cropping as the
# halo shrinks. Here the same amortization is *derived* rather than
# hand-tabled: ``_plan_state_halos`` walks the genotype backwards and
# computes, per cell state, the widest halo any consumer chain needs; the
# two input states are exchanged ONCE at that width and every op crops its
# source down to (its target's halo + its own window need). Boundary
# semantics stay bit-exact with the per-op (D1) form by re-filling the
# outside-image ring before every windowed op (``fill_boundary_halo``) and
# masking in-flight halo out of BN statistics — divergences the reference's
# D2 silently accepts.


class ConvBranchD2(nn.Module):
    """D2 twin of :class:`ConvBranch`: input carries ``halo_in`` rows/cols of
    neighbor data; each conv runs VALID and shrinks the halo by its D1
    padding. Parameter names match :class:`ConvBranch` exactly (``conv{i}`` /
    ``bn{i}``) so plain-model parameters drop in unchanged."""

    channels: int
    convs: Sequence[tuple[Any, Any, Any]]  # (kernel, stride, d1_padding)
    halo_in: int
    bottleneck: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        from mpi4dl_tpu.parallel.halo import fill_boundary_halo

        c = self.channels
        inner = c // 4 if self.bottleneck else c
        hh = hw = self.halo_in
        common = dict(use_bias=False, spatial=True, exchange=False, dtype=self.dtype)

        def bn(idx):
            return TrainBatchNorm(
                reduce_axes=self.bn_reduce_axes,
                interior=(hh, hw),
                dtype=self.dtype,
                name=f"bn{idx}",
            )

        idx = 0
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=inner, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = bn(idx)(x)
            idx += 1
        for k, s, p in self.convs:
            if _pair_(s) != (1, 1):
                raise ValueError("D2 conv branches are stride-1 only")
            ph, pw = _pair_(p)
            x = nn.relu(x)
            if (hh or hw) and (ph or pw):
                x = fill_boundary_halo(x, hh, hw, 0.0)
            x = Conv2d(features=inner, kernel_size=k, strides=1, padding=0, name=f"conv{idx}", **common)(x)
            hh -= ph
            hw -= pw
            if hh < 0 or hw < 0:
                raise ValueError("halo_in too small for this conv branch")
            x = bn(idx)(x)
            idx += 1
        if self.bottleneck:
            x = nn.relu(x)
            x = Conv2d(features=c, kernel_size=1, padding=0, name=f"conv{idx}", **common)(x)
            x = bn(idx)(x)
        return x


class PoolD2(nn.Module):
    """D2 twin of :class:`~mpi4dl_tpu.ops.layers.Pool` for 3×3 stride-1
    pad-1 pools: input carries ``halo_in``, output carries ``halo_in - 1``.
    Outside-image ring is re-filled with the pool's neutral element
    (``-inf`` max / excluded-from-count avg), keeping D1 bit-parity."""

    kind: str
    halo_in: int
    count_include_pad: bool = True

    @nn.compact
    def __call__(self, x):
        from jax import lax as jlax

        from mpi4dl_tpu.parallel.halo import fill_boundary_halo, zero_boundary_halo

        from mpi4dl_tpu.ops.layers import max_pool_s1_valid

        h = self.halo_in
        if h < 1:
            raise ValueError("PoolD2 needs halo_in >= 1 (3x3 pad-1 window)")
        if self.kind == "max":
            x = fill_boundary_halo(x, h, h, float("-inf"))
            return max_pool_s1_valid(x, 3, 3)
        if self.kind != "avg":
            raise ValueError(f"unknown pool kind {self.kind!r}")
        x = zero_boundary_halo(x, h, h)
        if self.count_include_pad:
            return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="VALID")
        ones = zero_boundary_halo(jnp.ones_like(x), h, h)
        num = jlax.reduce_window(x, 0.0, jlax.add, (1, 3, 3, 1), (1, 1, 1, 1), "valid")
        den = jlax.reduce_window(ones, 0.0, jlax.add, (1, 3, 3, 1), (1, 1, 1, 1), "valid")
        return num / den


def _pair_(v):
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _crop_halo(x, d: int):
    if d == 0:
        return x
    if d < 0:
        raise ValueError("cannot crop a negative halo margin")
    return x[:, d:-d, d:-d, :]


# D1 op factory -> (halo consumed by the op's windows, D2 factory).
# D2 factories: (channels, halo_in, bn_axes, dtype, name) -> module.
def _d2_conv_1x1(c, h, bn_axes, dtype, name):
    return ConvBranchD2(
        channels=c, convs=[(1, 1, 0)], halo_in=h, bottleneck=False,
        bn_reduce_axes=bn_axes, dtype=dtype, name=name,
    )


def _d2_conv_1x7_7x1(c, h, bn_axes, dtype, name):
    return ConvBranchD2(
        channels=c,
        convs=[((1, 7), (1, 1), (0, 3)), ((7, 1), (1, 1), (3, 0))],
        halo_in=h, bottleneck=True, bn_reduce_axes=bn_axes, dtype=dtype, name=name,
    )


def _d2_conv_3x3(c, h, bn_axes, dtype, name):
    return ConvBranchD2(
        channels=c, convs=[(3, 1, 1)], halo_in=h, bottleneck=True,
        bn_reduce_axes=bn_axes, dtype=dtype, name=name,
    )


def _d2_max_pool_3x3(c, h, bn_axes, dtype, name):
    return PoolD2(kind="max", halo_in=h, name=name)


def _d2_avg_pool_3x3(c, h, bn_axes, dtype, name):
    return PoolD2(kind="avg", halo_in=h, count_include_pad=False, name=name)


def _d2_none(c, h, bn_axes, dtype, name):
    return Identity(name=name)


D2_OPS = {
    op_conv_1x1: (0, _d2_conv_1x1),
    op_conv_1x7_7x1: (3, _d2_conv_1x7_7x1),
    op_conv_3x3: (1, _d2_conv_3x3),
    op_max_pool_3x3: (1, _d2_max_pool_3x3),
    op_avg_pool_3x3: (1, _d2_avg_pool_3x3),
    op_none: (0, _d2_none),
}


def _plan_state_halos(table) -> list[int]:
    """Per-state halo widths for one D2 cell: walk the genotype backwards so
    each state carries the widest halo any consumer chain needs. States 0/1
    are the cell inputs — their plan entry is the exchange width (the role of
    the reference's hand-chosen ``s3``/``s4`` halo sizes,
    ``amoebanet_d2.py:569-632``)."""
    halos = [0] * (2 + len(table) // 2)
    for i in reversed(range(0, len(table), 2)):
        tgt = 2 + i // 2
        for src, f in table[i : i + 2]:
            need, _ = D2_OPS[f]
            halos[src] = max(halos[src], halos[tgt] + need)
    return halos


class AmoebaCellD2(nn.Module):
    """Fused-halo normal cell (ref ``Cell_D2``, ``amoebanet_d2.py:569-678``):
    one wide :class:`~mpi4dl_tpu.ops.layers.HaloExchange` per input state
    (width from :func:`_plan_state_halos`), then the whole genotype runs
    VALID with per-op crops — 2 exchanges per cell instead of ~8.
    Parameter structure matches :class:`AmoebaCell` (reduction=False), so the
    plain model initializes it and D1/D2 are interchangeable mid-zoo."""

    channels_prev_prev: int
    channels_prev: int
    channels: int
    reduction_prev: bool
    cross_tile_bn: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, input_or_states):
        if isinstance(input_or_states, (tuple, list)):
            s1, s2 = input_or_states
        else:
            s1 = s2 = input_or_states
        skip = s1

        bn_axes = _bn_axes(True, self.cross_tile_bn)
        common = dict(spatial=True, bn_reduce_axes=bn_axes, dtype=self.dtype)
        s1 = ReluConvBn(features=self.channels, name="reduce1", **common)(s1)
        if self.reduction_prev:
            s2 = FactorizedReduce(features=self.channels, name="reduce2", **common)(s2)
        elif self.channels_prev_prev != self.channels:
            s2 = ReluConvBn(features=self.channels, name="reduce2", **common)(s2)

        table, concat = NORMAL_OPERATIONS, NORMAL_CONCAT
        halos = _plan_state_halos(table)
        states = [
            HaloExchange(halo_len=halos[0])(s1) if halos[0] else s1,
            HaloExchange(halo_len=halos[1])(s2) if halos[1] else s2,
        ]
        for i in range(0, len(table), 2):
            tgt_halo = halos[2 + i // 2]
            pair = []
            for j, (src, f) in enumerate(table[i : i + 2]):
                need, d2f = D2_OPS[f]
                xin = _crop_halo(states[src], halos[src] - (tgt_halo + need))
                pair.append(
                    d2f(self.channels, tgt_halo + need, bn_axes, self.dtype, f"op{i + j}")(xin)
                )
            states.append(pair[0] + pair[1])
        out = jnp.concatenate(
            [_crop_halo(states[i], halos[i]) for i in concat], axis=-1
        )
        return out, skip


def amoebanetd(
    num_classes: int = 10,
    num_layers: int = 4,
    num_filters: int = 512,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    halo_d2: bool = False,
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """AmoebaNet-D as a flat cell list (refs ``amoebanetd``
    ``amoebanet.py:535-615`` and ``amoebanetd_spatial`` ``:618-737`` unified:
    the first ``spatial_cells`` cells are spatial, the rest plain — the
    reference's ``layers_processed >= end_layer`` flip).

    Cell sequence: stem1, 2 reduction stems, then r normal / reduction /
    r normal / reduction / r normal (r = num_layers // 3), classifier.
    """
    if num_layers % 3:
        raise ValueError("num_layers must be a multiple of 3")
    r = num_layers // 3
    channels = num_filters // 4
    cells: list[nn.Module] = []

    state = dict(
        channels_prev_prev=channels, channels_prev=channels, reduction_prev=False,
        channels=channels,
    )

    def sp():
        return len(cells) < spatial_cells

    def add_cell(reduction: bool, channels_scale: int):
        state["channels"] *= channels_scale
        spatial = sp()
        if halo_d2 and spatial and not reduction:
            # D2 fused-halo form for spatial normal cells (ref picks Cell_D2
            # for exactly these, ``amoebanet_d2.py:896-914``); reduction
            # cells keep per-op (D1) exchanges — their stride-2 windows need
            # no halo under the power-of-two tile constraint.
            cell = AmoebaCellD2(
                channels_prev_prev=state["channels_prev_prev"],
                channels_prev=state["channels_prev"],
                channels=state["channels"],
                reduction_prev=state["reduction_prev"],
                cross_tile_bn=cross_tile_bn,
                dtype=dtype,
            )
        else:
            cell = AmoebaCell(
                channels_prev_prev=state["channels_prev_prev"],
                channels_prev=state["channels_prev"],
                channels=state["channels"],
                reduction=reduction,
                reduction_prev=state["reduction_prev"],
                spatial=spatial,
                cross_tile_bn=cross_tile_bn,
                dtype=dtype,
            )
        concat = REDUCTION_CONCAT if reduction else NORMAL_CONCAT
        state["channels_prev_prev"] = state["channels_prev"]
        state["channels_prev"] = state["channels"] * len(concat)
        state["reduction_prev"] = reduction
        cells.append(cell)

    cells.append(
        Stem(
            channels=channels,
            spatial=sp(),
            bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
            dtype=dtype,
        )
    )
    add_cell(reduction=True, channels_scale=2)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    add_cell(reduction=True, channels_scale=2)
    for _ in range(r):
        add_cell(reduction=False, channels_scale=1)
    cells.append(Classify(num_classes=num_classes, dtype=dtype))
    return cells
