"""ResNet v1/v2 (Keras-style) — capability parity with reference
``src/models/resnet.py`` (plain), ``resnet_spatial.py`` (spatial D1) and
``resnet_spatial_d2.py`` (fused-halo D2), unified into one builder.

The reference keeps three near-copies of the model, differing only in which
conv class is instantiated; here every cell takes a ``spatial`` flag and the
builder marks the first ``spatial_cells`` cells spatial (ref boundary logic:
``resnet_spatial.py:545-633`` emits spatial cells for layers before the SP
stage's end layer).

Architecture parity (ref ``resnet.py``):
- ``ResNetLayer`` == ``resnet_layer`` (``resnet.py:24-78``): conv→BN→ReLU or
  BN→ReLU→conv (``conv_first``), k=3 default, padding (k-1)/2.
- v1 cell == ``make_cell_v1`` (``resnet.py:81-114``): two 3×3 layers +
  1×1-conv shortcut on stack transitions; out = relu(x + y).
- v2 cell == ``make_cell_v2`` (``resnet.py:181-231``): pre-activation
  bottleneck (3×3, 3×3, 1×1 — the reference's variant) + 1×1 shortcut on
  each stack's first block.
- builders == ``get_resnet_v1``/``get_resnet_v2`` (``resnet.py:145-178,
  :270-323``): depth = 6n+2 / 9n+2, 3 stacks, stride-2 downsample at stack
  starts, avg-pool-8 + linear head.

Deliberate deviation: the reference head applies ``F.softmax`` and then
feeds the result to ``nn.CrossEntropyLoss`` (a double-softmax —
``resnet.py:140``). We output logits; the loss applies softmax once.

Models are returned as a **list of cells** (tensor→tensor modules) so the
stage partitioner can slice them, like the reference's flat
``nn.Sequential(OrderedDict)`` sliced by child index (``mp_pipeline.py:71-83``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from mpi4dl_tpu.ops.layers import (
    Conv2d,
    Dense,
    HaloExchange,
    Identity,
    Pool,
    TrainBatchNorm,
    TILE_AXES,
)


def _bn_axes(spatial: bool, cross_tile_bn: bool) -> tuple[str, ...]:
    return TILE_AXES if (spatial and cross_tile_bn) else ()


class ResNetLayer(nn.Module):
    """conv/BN/ReLU unit (ref ``resnet_layer``, ``resnet.py:24-78``).

    ``exchange=False`` + ``padding=0`` turns the conv into the D2 "shrink"
    form (VALID conv consuming pre-fetched halo, ref ``resnet_spatial_d2.py``),
    and ``bn_interior`` excludes the remaining halo rows/cols from BN stats.
    """

    features: int
    kernel_size: int = 3
    strides: int = 1
    activation: str | None = "relu"
    batch_normalization: bool = True
    conv_first: bool = True
    spatial: bool = False
    exchange: bool = True
    padding: Any = None
    bn_interior: tuple[int, int] = (0, 0)
    zero_halo: tuple[int, int] = (0, 0)  # re-zero outside-image halo pre-conv
    bn_reduce_axes: tuple[str, ...] = ()
    pack: tuple[int, int] = (1, 1)  # packed activation layout (ops/packed.py)
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        from mpi4dl_tpu.parallel.halo import zero_boundary_halo

        conv = Conv2d(
            features=self.features,
            kernel_size=self.kernel_size,
            strides=self.strides,
            padding=self.padding,
            spatial=self.spatial,
            exchange=self.exchange,
            pack=self.pack,
            dtype=self.dtype,
            name="conv",
        )
        if not self.batch_normalization:
            bn = None
        elif (self.pack[0] if self.conv_first is False else self.pack[1]) > 1:
            # BN sees the conv's input (pre-activation) or output
            # (conv_first) — packed either way under the packed layout.
            from mpi4dl_tpu.ops.packed import PackedTrainBatchNorm

            bn = PackedTrainBatchNorm(
                pack=self.pack[0] if not self.conv_first else self.pack[1],
                reduce_axes=self.bn_reduce_axes,
                dtype=self.dtype,
                name="bn",
            )
        else:
            bn = TrainBatchNorm(
                reduce_axes=self.bn_reduce_axes,
                interior=self.bn_interior,
                dtype=self.dtype,
                name="bn",
            )
        if self.conv_first:
            x = conv(x)
            if bn is not None:
                x = bn(x)
            if self.activation:
                x = nn.relu(x)
        else:
            if bn is not None:
                x = bn(x)
            if self.activation:
                x = nn.relu(x)
            if self.zero_halo != (0, 0):
                x = zero_boundary_halo(x, *self.zero_halo)
            x = conv(x)
        return x


class CellV1(nn.Module):
    """Basic residual cell (ref ``make_cell_v1``, ``resnet.py:81-114``)."""

    stack: int
    res_block: int
    strides: int
    features: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        common = dict(
            spatial=self.spatial, bn_reduce_axes=self.bn_reduce_axes, dtype=self.dtype
        )
        y = ResNetLayer(self.features, strides=self.strides, name="r1", **common)(x)
        y = ResNetLayer(self.features, activation=None, name="r2", **common)(y)
        if self.res_block == 0 and self.stack > 0:
            x = ResNetLayer(
                self.features,
                kernel_size=1,
                strides=self.strides,
                activation=None,
                batch_normalization=False,
                name="r3",
                **common,
            )(x)
        return nn.relu(x + y)


class CellV2(nn.Module):
    """Pre-activation bottleneck cell (ref ``make_cell_v2``, ``resnet.py:181-231``)."""

    res_block: int
    strides: int
    features1: int  # bottleneck width
    features2: int  # output width
    activation: str | None = "relu"
    batch_normalization: bool = True
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    pack: tuple[int, int] = (1, 1)  # (f_in, f_mid) packed layout factors
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        common = dict(
            spatial=self.spatial, bn_reduce_axes=self.bn_reduce_axes, dtype=self.dtype
        )
        f_in, f_mid = self.pack
        y = ResNetLayer(
            self.features1,
            strides=self.strides,
            activation=self.activation,
            batch_normalization=self.batch_normalization,
            conv_first=False,
            pack=(f_in, f_mid),
            name="r1",
            **common,
        )(x)
        y = ResNetLayer(
            self.features1, conv_first=False, pack=(f_mid, f_mid), name="r2",
            **common,
        )(y)
        y = ResNetLayer(
            self.features2, kernel_size=1, conv_first=False,
            pack=(f_mid, f_mid), name="r3", **common,
        )(y)
        if self.res_block == 0:
            x = ResNetLayer(
                self.features2,
                kernel_size=1,
                strides=self.strides,
                activation=None,
                batch_normalization=False,
                pack=(f_in, f_mid),
                name="r4",
                **common,
            )(x)
        return x + y


class CellV2D2(nn.Module):
    """D2 (fused-halo) pre-activation bottleneck (ref ``make_cell_v2_spatial``
    in ``resnet_spatial_d2.py:375-480``): the input tile already carries
    ``halo_in`` rows/cols of neighbor data (fetched by one wide
    ``HaloExchange`` shared across ``fused_layers`` cells); the two 3×3 convs
    run VALID and shrink the halo by 2, the skip path is trimmed ``[2:-2]``
    to match (ref ``:462-480``). BN statistics exclude the in-flight halo
    (``bn_interior``) so results are bit-identical to the D1/plain model —
    the reference accepts halo-skewed BN there.

    Same parameter structure/names as :class:`CellV2` (r1-r4), so D1 golden
    params drop in unchanged. Stride-2 cells are never fused (the builder
    emits them as plain spatial cells)."""

    res_block: int
    features1: int
    features2: int
    halo_in: int
    activation: str | None = "relu"
    batch_normalization: bool = True
    cross_tile_bn: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        h = self.halo_in
        axes = TILE_AXES if self.cross_tile_bn else ()
        common = dict(
            spatial=True,
            exchange=False,
            padding=0,
            bn_reduce_axes=axes,
            dtype=self.dtype,
            conv_first=False,
        )
        y = ResNetLayer(
            self.features1,
            activation=self.activation,
            batch_normalization=self.batch_normalization,
            bn_interior=(h, h),
            zero_halo=(h, h),
            name="r1",
            **common,
        )(x)
        y = ResNetLayer(
            self.features1,
            bn_interior=(h - 1, h - 1),
            zero_halo=(h - 1, h - 1),
            name="r2",
            **common,
        )(y)
        y = ResNetLayer(
            self.features2,
            kernel_size=1,
            bn_interior=(h - 2, h - 2),
            name="r3",
            **common,
        )(y)
        x = x[:, 2:-2, 2:-2, :]
        if self.res_block == 0:
            x = ResNetLayer(
                self.features2,
                kernel_size=1,
                activation=None,
                batch_normalization=False,
                name="r4",
                spatial=True,
                exchange=False,
                padding=0,
                dtype=self.dtype,
            )(x)
        return x + y


def _v2_specs(depth: int) -> list[dict]:
    """Per-cell specs of the v2 bottleneck stack (shared by the D1 and D2
    builders so the two models cannot drift apart): strides/widths/activation
    rules of ref ``get_resnet_v2`` (``resnet.py:270-323``)."""
    if (depth - 2) % 9 != 0:
        raise ValueError("depth should be 9n+2 (eg 56 or 110)")
    n_blocks = (depth - 2) // 9
    specs = []
    features_in = 16  # bottleneck width, constant within a stage
    for stage in range(3):
        for res_block in range(n_blocks):
            strides = 1
            activation: str | None = "relu"
            batch_normalization = True
            if stage == 0:
                features_out = features_in * 4
                if res_block == 0:
                    activation = None
                    batch_normalization = False
            else:
                features_out = features_in * 2
                if res_block == 0:
                    strides = 2
            specs.append(
                dict(
                    # Only res_block == 0 changes behavior (the r4 shortcut
                    # conv); clamping the index makes the later blocks of a
                    # stage compare EQUAL as module configs, which is what
                    # lets the "scan" remat policy stack them into one
                    # lax.scan (train._plan_scan_runs groups by equality).
                    res_block=min(res_block, 1),
                    strides=strides,
                    features1=features_in,
                    features2=features_out,
                    activation=activation,
                    batch_normalization=batch_normalization,
                )
            )
        features_in = features_out
    return specs


class HeadV1(nn.Module):
    """AvgPool(8) + Linear head (ref ``end_part_v1``, ``resnet.py:117-142``;
    logits instead of softmax — see module docstring)."""

    num_classes: int
    pool_kernel: int = 8
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = Pool(kind="avg", kernel_size=self.pool_kernel, name="pool")(x)
        return Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


class HeadV2(nn.Module):
    """BN + ReLU + AvgPool(8) + Linear head (ref ``end_part_v2``,
    ``resnet.py:234-267``)."""

    num_classes: int
    pool_kernel: int = 8
    bn_reduce_axes: tuple[str, ...] = ()
    pack: int = 1  # packed layout factor of the incoming activation
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        if self.pack > 1:
            from mpi4dl_tpu.ops.packed import PackedTrainBatchNorm, unpack

            x = PackedTrainBatchNorm(
                pack=self.pack, reduce_axes=self.bn_reduce_axes,
                dtype=self.dtype, name="bn",
            )(x)
            x = nn.relu(x)
            x = unpack(x, self.pack)
        else:
            x = TrainBatchNorm(
                reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn"
            )(x)
            x = nn.relu(x)
        x = Pool(kind="avg", kernel_size=self.pool_kernel, name="pool")(x)
        return Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


def get_resnet_v1(
    depth: int,
    num_classes: int = 10,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    pool_kernel: int = 8,
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """ResNet v1 as a flat cell list (ref ``get_resnet_v1``, ``resnet.py:145-178``).

    spatial_cells: the first N cells run spatially partitioned (0 = plain
    model). The head is never spatial (it runs after the tile merge, like the
    reference's join rank)."""
    if (depth - 2) % 6 != 0:
        raise ValueError("depth should be 6n+2 (eg 20, 32, 44)")
    n_blocks = (depth - 2) // 6
    cells: list[nn.Module] = []

    def sp():
        return len(cells) < spatial_cells

    cells.append(
        ResNetLayer(
            16, spatial=sp(), bn_reduce_axes=_bn_axes(sp(), cross_tile_bn), dtype=dtype
        )
    )
    features = 16
    for stack in range(3):
        for res_block in range(n_blocks):
            strides = 2 if (stack > 0 and res_block == 0) else 1
            cells.append(
                CellV1(
                    # Clamped indices: only (stack > 0, res_block == 0)
                    # changes behavior; equal configs let repeated blocks
                    # scan (see _v2_specs note).
                    stack=min(stack, 1),
                    res_block=min(res_block, 1),
                    strides=strides,
                    features=features,
                    spatial=sp(),
                    bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
                    dtype=dtype,
                )
            )
        features *= 2
    cells.append(HeadV1(num_classes=num_classes, pool_kernel=pool_kernel, dtype=dtype))
    return cells


def get_resnet_v2(
    depth: int,
    num_classes: int = 10,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    pool_kernel: int = 8,
    layout: str = "nhwc",
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """ResNet v2 as a flat cell list (ref ``get_resnet_v2``, ``resnet.py:270-323``).

    layout="packed" builds the same model on the persistently-packed
    activation layout (ops/packed.py): identical parameter tree and math
    (mod f32 accumulation order), up to ~8x less HBM traffic for the
    small-channel stages on TPU. Composes with ``spatial_cells`` — spatial
    packed convs halo-exchange whole packed columns (``conv2d_packed``
    spatial mode); the pack factor must divide each spatial stage's local
    tile width (power-of-two tiles make this automatic for the standard
    image sizes).
    """
    if layout not in ("nhwc", "packed"):
        raise ValueError(f"layout must be nhwc|packed, got {layout!r}")
    cells: list[nn.Module] = []

    def sp():
        return len(cells) < spatial_cells

    def f_of(c):
        from mpi4dl_tpu.ops.packed import pack_factor

        return pack_factor(c) if layout == "packed" else 1

    cells.append(
        ResNetLayer(
            16,
            conv_first=True,
            spatial=sp(),
            bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
            pack=(1, f_of(16)),
            dtype=dtype,
        )
    )
    # Pack factors chain through the net: a cell's f_in is the previous
    # cell's f_mid, and the packed stride s' = strides*f_mid/f_in must be a
    # positive integer — so a stride-2 cell halves f (never below 1), and f
    # never drops below what keeps the minormost dim >= 128 when the
    # channel width allows it.
    f_prev = f_of(16)
    for spec in _v2_specs(depth):
        if layout == "packed":
            f_mid = max(f_of(spec["features1"]), f_prev // spec["strides"])
        else:
            f_mid = 1
        cells.append(
            CellV2(
                spatial=sp(),
                bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
                pack=(f_prev, f_mid),
                dtype=dtype,
                **spec,
            )
        )
        f_prev = f_mid
    cells.append(
        HeadV2(
            num_classes=num_classes, pool_kernel=pool_kernel, pack=f_prev,
            dtype=dtype,
        )
    )
    return cells


def get_resnet_v2_d2(
    depth: int,
    num_classes: int = 10,
    spatial_cells: int = 0,
    fused_layers: int = 2,
    cross_tile_bn: bool = True,
    pool_kernel: int = 8,
    dtype: Any = jnp.float32,
) -> tuple[list[nn.Module], list[nn.Module], int]:
    """ResNet v2 "design 2" (ref ``resnet_spatial_d2.py:578-726``): in the
    spatial region, runs of up to ``fused_layers`` stride-1 bottleneck cells
    share ONE wide :class:`~mpi4dl_tpu.ops.layers.HaloExchange` (halo
    ``2*run``), then run halo-free shrink convs (:class:`CellV2D2`); stride-2
    cells and the stem conv stay per-cell exchanged (D1 form). The reference
    mutates ``balance[0]`` so its partitioner counts the inserted halo layers
    (``:667-697``); here the front/back split point is returned explicitly.

    spatial_cells counts **D1** cells (as produced by
    ``PipelineTrainer.spatial_cell_count`` on the D1 cell list).

    Returns ``(cells, plain_twin, n_spatial_d2)`` — ``plain_twin`` has
    identical parameter structure (``Identity`` at halo positions) and is the
    golden/init model; ``n_spatial_d2`` is the spatial prefix length in the
    returned (expanded) cell list.
    """
    bn_axes = (lambda sp: TILE_AXES if (sp and cross_tile_bn) else ())
    specs = _v2_specs(depth)  # shared with get_resnet_v2 — no drift

    cells: list[nn.Module] = []
    plain: list[nn.Module] = []
    n_spatial_d2: int | None = None if spatial_cells > 0 else 0

    sp0 = spatial_cells > 0
    cells.append(
        ResNetLayer(16, spatial=sp0, bn_reduce_axes=bn_axes(sp0), dtype=dtype)
    )
    plain.append(ResNetLayer(16, dtype=dtype))

    i = 0
    while i < len(specs):
        in_spatial = (1 + i) < spatial_cells
        if n_spatial_d2 is None and not in_spatial:
            n_spatial_d2 = len(cells)
        spec = specs[i]
        if in_spatial and spec["strides"] == 1 and fused_layers > 1:
            j = i
            while (
                j < len(specs)
                and (1 + j) < spatial_cells
                and specs[j]["strides"] == 1
                and (j - i) < fused_layers
            ):
                j += 1
            group = specs[i:j]
            halo = 2 * len(group)
            cells.append(HaloExchange(halo_len=halo))
            plain.append(Identity())
            for g_idx, gs in enumerate(group):
                cells.append(
                    CellV2D2(
                        res_block=gs["res_block"],
                        features1=gs["features1"],
                        features2=gs["features2"],
                        halo_in=halo - 2 * g_idx,
                        activation=gs["activation"],
                        batch_normalization=gs["batch_normalization"],
                        cross_tile_bn=cross_tile_bn,
                        dtype=dtype,
                    )
                )
                plain.append(
                    CellV2(
                        res_block=gs["res_block"],
                        strides=1,
                        features1=gs["features1"],
                        features2=gs["features2"],
                        activation=gs["activation"],
                        batch_normalization=gs["batch_normalization"],
                        dtype=dtype,
                    )
                )
            i = j
        else:
            cells.append(
                CellV2(
                    res_block=spec["res_block"],
                    strides=spec["strides"],
                    features1=spec["features1"],
                    features2=spec["features2"],
                    activation=spec["activation"],
                    batch_normalization=spec["batch_normalization"],
                    spatial=in_spatial,
                    bn_reduce_axes=bn_axes(in_spatial),
                    dtype=dtype,
                )
            )
            plain.append(
                CellV2(
                    res_block=spec["res_block"],
                    strides=spec["strides"],
                    features1=spec["features1"],
                    features2=spec["features2"],
                    activation=spec["activation"],
                    batch_normalization=spec["batch_normalization"],
                    dtype=dtype,
                )
            )
            i += 1

    if n_spatial_d2 is None:
        n_spatial_d2 = len(cells)
    cells.append(HeadV2(num_classes=num_classes, pool_kernel=pool_kernel, dtype=dtype))
    plain.append(HeadV2(num_classes=num_classes, pool_kernel=pool_kernel, dtype=dtype))
    return cells, plain, n_spatial_d2
