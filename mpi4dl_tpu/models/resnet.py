"""ResNet v1/v2 (Keras-style) — capability parity with reference
``src/models/resnet.py`` (plain), ``resnet_spatial.py`` (spatial D1) and
``resnet_spatial_d2.py`` (fused-halo D2), unified into one builder.

The reference keeps three near-copies of the model, differing only in which
conv class is instantiated; here every cell takes a ``spatial`` flag and the
builder marks the first ``spatial_cells`` cells spatial (ref boundary logic:
``resnet_spatial.py:545-633`` emits spatial cells for layers before the SP
stage's end layer).

Architecture parity (ref ``resnet.py``):
- ``ResNetLayer`` == ``resnet_layer`` (``resnet.py:24-78``): conv→BN→ReLU or
  BN→ReLU→conv (``conv_first``), k=3 default, padding (k-1)/2.
- v1 cell == ``make_cell_v1`` (``resnet.py:81-114``): two 3×3 layers +
  1×1-conv shortcut on stack transitions; out = relu(x + y).
- v2 cell == ``make_cell_v2`` (``resnet.py:181-231``): pre-activation
  bottleneck (3×3, 3×3, 1×1 — the reference's variant) + 1×1 shortcut on
  each stack's first block.
- builders == ``get_resnet_v1``/``get_resnet_v2`` (``resnet.py:145-178,
  :270-323``): depth = 6n+2 / 9n+2, 3 stacks, stride-2 downsample at stack
  starts, avg-pool-8 + linear head.

Deliberate deviation: the reference head applies ``F.softmax`` and then
feeds the result to ``nn.CrossEntropyLoss`` (a double-softmax —
``resnet.py:140``). We output logits; the loss applies softmax once.

Models are returned as a **list of cells** (tensor→tensor modules) so the
stage partitioner can slice them, like the reference's flat
``nn.Sequential(OrderedDict)`` sliced by child index (``mp_pipeline.py:71-83``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from mpi4dl_tpu.ops.layers import Conv2d, Dense, Pool, TrainBatchNorm, TILE_AXES


def _bn_axes(spatial: bool, cross_tile_bn: bool) -> tuple[str, ...]:
    return TILE_AXES if (spatial and cross_tile_bn) else ()


class ResNetLayer(nn.Module):
    """conv/BN/ReLU unit (ref ``resnet_layer``, ``resnet.py:24-78``)."""

    features: int
    kernel_size: int = 3
    strides: int = 1
    activation: str | None = "relu"
    batch_normalization: bool = True
    conv_first: bool = True
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        conv = Conv2d(
            features=self.features,
            kernel_size=self.kernel_size,
            strides=self.strides,
            spatial=self.spatial,
            dtype=self.dtype,
            name="conv",
        )
        bn = (
            TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn")
            if self.batch_normalization
            else None
        )
        if self.conv_first:
            x = conv(x)
            if bn is not None:
                x = bn(x)
            if self.activation:
                x = nn.relu(x)
        else:
            if bn is not None:
                x = bn(x)
            if self.activation:
                x = nn.relu(x)
            x = conv(x)
        return x


class CellV1(nn.Module):
    """Basic residual cell (ref ``make_cell_v1``, ``resnet.py:81-114``)."""

    stack: int
    res_block: int
    strides: int
    features: int
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        common = dict(
            spatial=self.spatial, bn_reduce_axes=self.bn_reduce_axes, dtype=self.dtype
        )
        y = ResNetLayer(self.features, strides=self.strides, name="r1", **common)(x)
        y = ResNetLayer(self.features, activation=None, name="r2", **common)(y)
        if self.res_block == 0 and self.stack > 0:
            x = ResNetLayer(
                self.features,
                kernel_size=1,
                strides=self.strides,
                activation=None,
                batch_normalization=False,
                name="r3",
                **common,
            )(x)
        return nn.relu(x + y)


class CellV2(nn.Module):
    """Pre-activation bottleneck cell (ref ``make_cell_v2``, ``resnet.py:181-231``)."""

    res_block: int
    strides: int
    features1: int  # bottleneck width
    features2: int  # output width
    activation: str | None = "relu"
    batch_normalization: bool = True
    spatial: bool = False
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        common = dict(
            spatial=self.spatial, bn_reduce_axes=self.bn_reduce_axes, dtype=self.dtype
        )
        y = ResNetLayer(
            self.features1,
            strides=self.strides,
            activation=self.activation,
            batch_normalization=self.batch_normalization,
            conv_first=False,
            name="r1",
            **common,
        )(x)
        y = ResNetLayer(self.features1, conv_first=False, name="r2", **common)(y)
        y = ResNetLayer(
            self.features2, kernel_size=1, conv_first=False, name="r3", **common
        )(y)
        if self.res_block == 0:
            x = ResNetLayer(
                self.features2,
                kernel_size=1,
                strides=self.strides,
                activation=None,
                batch_normalization=False,
                name="r4",
                **common,
            )(x)
        return x + y


class HeadV1(nn.Module):
    """AvgPool(8) + Linear head (ref ``end_part_v1``, ``resnet.py:117-142``;
    logits instead of softmax — see module docstring)."""

    num_classes: int
    pool_kernel: int = 8
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = Pool(kind="avg", kernel_size=self.pool_kernel, name="pool")(x)
        return Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


class HeadV2(nn.Module):
    """BN + ReLU + AvgPool(8) + Linear head (ref ``end_part_v2``,
    ``resnet.py:234-267``)."""

    num_classes: int
    pool_kernel: int = 8
    bn_reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = TrainBatchNorm(reduce_axes=self.bn_reduce_axes, dtype=self.dtype, name="bn")(x)
        x = nn.relu(x)
        x = Pool(kind="avg", kernel_size=self.pool_kernel, name="pool")(x)
        return Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


def get_resnet_v1(
    depth: int,
    num_classes: int = 10,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """ResNet v1 as a flat cell list (ref ``get_resnet_v1``, ``resnet.py:145-178``).

    spatial_cells: the first N cells run spatially partitioned (0 = plain
    model). The head is never spatial (it runs after the tile merge, like the
    reference's join rank)."""
    if (depth - 2) % 6 != 0:
        raise ValueError("depth should be 6n+2 (eg 20, 32, 44)")
    n_blocks = (depth - 2) // 6
    cells: list[nn.Module] = []

    def sp():
        return len(cells) < spatial_cells

    cells.append(
        ResNetLayer(
            16, spatial=sp(), bn_reduce_axes=_bn_axes(sp(), cross_tile_bn), dtype=dtype
        )
    )
    features = 16
    for stack in range(3):
        for res_block in range(n_blocks):
            strides = 2 if (stack > 0 and res_block == 0) else 1
            cells.append(
                CellV1(
                    stack=stack,
                    res_block=res_block,
                    strides=strides,
                    features=features,
                    spatial=sp(),
                    bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
                    dtype=dtype,
                )
            )
        features *= 2
    cells.append(HeadV1(num_classes=num_classes, dtype=dtype))
    return cells


def get_resnet_v2(
    depth: int,
    num_classes: int = 10,
    spatial_cells: int = 0,
    cross_tile_bn: bool = True,
    dtype: Any = jnp.float32,
) -> list[nn.Module]:
    """ResNet v2 as a flat cell list (ref ``get_resnet_v2``, ``resnet.py:270-323``)."""
    if (depth - 2) % 9 != 0:
        raise ValueError("depth should be 9n+2 (eg 56 or 110)")
    n_blocks = (depth - 2) // 9
    cells: list[nn.Module] = []

    def sp():
        return len(cells) < spatial_cells

    cells.append(
        ResNetLayer(
            16,
            conv_first=True,
            spatial=sp(),
            bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
            dtype=dtype,
        )
    )
    features_in = 16  # bottleneck width, constant within a stage
    for stage in range(3):
        for res_block in range(n_blocks):
            strides = 1
            activation: str | None = "relu"
            batch_normalization = True
            if stage == 0:
                features_out = features_in * 4
                if res_block == 0:
                    activation = None
                    batch_normalization = False
            else:
                features_out = features_in * 2
                if res_block == 0:
                    strides = 2
            cells.append(
                CellV2(
                    res_block=res_block,
                    strides=strides,
                    features1=features_in,
                    features2=features_out,
                    activation=activation,
                    batch_normalization=batch_normalization,
                    spatial=sp(),
                    bn_reduce_axes=_bn_axes(sp(), cross_tile_bn),
                    dtype=dtype,
                )
            )
        features_in = features_out
    cells.append(HeadV2(num_classes=num_classes, dtype=dtype))
    return cells
