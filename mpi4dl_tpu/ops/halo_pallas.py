"""Pallas TPU kernel for the halo exchange hot path.

**Status: EXPERIMENTAL, off by default — recorded kill (round 5).** The
kernel is correctness-tested (bit-identical to the XLA path on the
8-device interpreter mesh, ``tests/test_halo_pallas.py``) but has never
beaten the four-ppermute XLA path where it matters and cannot on this
runtime: (a) the benchmark machine exposes ONE real chip, so the
cross-chip ICI DMA race this kernel exists to win is unmeasurable here;
(b) the same runtime's Pallas DMA path tops out ~10x below XLA's own
copy kernels (measured, docs/PERF.md round 2 #2), so the local evidence
points the wrong way; (c) under the pipeline's vmapped front the kernel
deadlocks and auto-downgrades (below), excluding it from the schedules
that dominate the benchmarks. The framework's transport story rests on
XLA collectives plus the two Pallas kernels with measured end-to-end
wins (``wgrad_pallas``, ``pool_pallas``); this module stays for a
runtime where the ICI DMA path is competitive. Enable explicitly with
``MPI4DL_TPU_HALO_IMPL=pallas``.

The halo exchange is the innermost hot loop of spatial parallelism — the
reference posts up to 8 tagged MPI isend/irecv per conv per micro-batch
(``src/torchgems/spatial.py:336-413``) and even ships a (dead) compute-overlap
variant (``spatial.py:415-828``). The XLA path here
(:func:`mpi4dl_tpu.parallel.halo.halo_exchange`) lowers to four sequential
``collective-permute`` ops. This module replaces each opposing pair with ONE
Pallas kernel that posts both remote DMAs together, so the up/down (and
left/right) strips ride the ICI links in both directions concurrently —
the TPU equivalent of the reference's "post all isends, then wait" batch,
with the semaphore protocol in hardware instead of MPI tags.

Design notes:

- **Uniform SPMD**: every device sends both strips with wraparound ring
  topology — no divergent control flow around communication (conditional
  sends deadlock the collective matcher the same way mismatched MPI tags
  would). Wrapped-around strips arriving at global-boundary tiles are
  garbage; the caller overwrites them with the pad value via a
  ``jnp.where`` on the axis index, which XLA fuses into the surrounding
  concatenate.
- **The kernel is a pure permutation** (`ra_i = a_{(i+1) mod n}`,
  ``rb_i = b_{(i-1) mod n}``), so its transpose is itself with the operands
  swapped: ``(gb, ga) = swap(grb, gra)`` — registered as a ``custom_vjp`` so
  the backward pass reuses the same kernel (the reference hand-writes the
  reverse halo scatter; here it falls out of linearity).
- Strip slicing / concatenation stays in XLA: those are local copies XLA
  fuses well; only the inter-chip movement needs Pallas.

On CPU (tests, simulated meshes) the kernel runs under the Pallas TPU
interpreter (``pltpu.InterpretParams``), bit-identical to the XLA path.
Select the implementation with ``MPI4DL_TPU_HALO_IMPL=xla|pallas`` or the
``impl=`` argument of :func:`mpi4dl_tpu.parallel.halo.halo_exchange`.

Operational knobs:

- ``MPI4DL_TPU_HALO_COLLECTIVE_IDS=N`` cycles collective ids within
  ``[0, N)`` instead of allocating a unique id per exchange — set it if a
  backend bounds its collective-id space (same-id kernels are then
  serialized by the layer chain's data dependences). Ids reset at each
  train-step trace (:func:`reset_collective_ids`), so they are
  deterministic across SPMD hosts either way.
- The kernel is only safe un-batched; batched callers (the pipeline's
  vmapped front) force the XLA path via
  :func:`mpi4dl_tpu.parallel.halo.xla_halo_only`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi4dl_tpu.compat import axis_size


def interpret_available() -> bool:
    """Whether this jax can interpret TPU-distributed Pallas kernels on
    CPU (``InterpretParams``; ``TPUInterpretParams`` on 2024-era lines;
    absent entirely on 0.4.x — tests skip the pallas halo there)."""
    return any(
        hasattr(pltpu, n) for n in ("InterpretParams", "TPUInterpretParams")
    )


def _interpret():
    # Pallas TPU kernels run interpreted on CPU test meshes.
    if jax.default_backend() == "tpu":
        return False
    for name in ("InterpretParams", "TPUInterpretParams"):
        cls = getattr(pltpu, name, None)
        if cls is not None:
            return cls()
    raise NotImplementedError(
        "this jax has no TPU-Pallas CPU interpreter; the pallas halo "
        "impl needs a real TPU here (use MPI4DL_TPU_HALO_IMPL=xla)"
    )


def _swap_kernel(axis_name: str):
    """Kernel: send ``a`` to the ring-previous device, ``b`` to the
    ring-next device; receive ``ra`` (= next's ``a``) and ``rb``
    (= previous's ``b``). Both RDMAs are posted before either is waited,
    so the two directions overlap on the ICI links."""

    def kernel(a_ref, b_ref, ra_ref, rb_ref, send_sem, recv_sem):
        idx = lax.axis_index(axis_name)
        n = axis_size(axis_name)
        nxt = lax.rem(idx + 1, n)
        prv = lax.rem(idx - 1 + n, n)
        # MESH-typed device ids address "same coordinates except this axis",
        # which makes the kernel correct under any surrounding mesh (each
        # (data, pipe, other-tile-axis) coordinate runs its own ring).
        to_prev = pltpu.make_async_remote_copy(
            src_ref=a_ref,
            dst_ref=ra_ref,
            send_sem=send_sem.at[0],
            recv_sem=recv_sem.at[0],
            device_id={axis_name: prv},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        to_next = pltpu.make_async_remote_copy(
            src_ref=b_ref,
            dst_ref=rb_ref,
            send_sem=send_sem.at[1],
            recv_sem=recv_sem.at[1],
            device_id={axis_name: nxt},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        to_prev.start()
        to_next.start()
        to_prev.wait()
        to_next.wait()

    return kernel


# Distinct collective_ids for kernels that can be concurrently live in one
# program (e.g. the two independent input-state exchanges of a D2 AmoebaNet
# cell): Pallas kernels sharing an id share collective bookkeeping, so
# overlap with a duplicate id can mis-match sends and recvs on real
# hardware. Round 1 cycled through 8 ids in trace order — a D2 ResNet-110
# program traces hundreds of exchanges, so duplicate ids within one program
# were GUARANTEED and the "not concurrently live" safety argument was
# unvalidated (VERDICT weak #3). Ids are now unique per trace by default
# (trace order is deterministic across SPMD devices, so ids agree
# everywhere). If a backend bounds the id space, set
# ``MPI4DL_TPU_HALO_COLLECTIVE_IDS`` to cycle within that bound — safe only
# because same-id kernels are then serialized by the data dependences of
# the layer chain.
_collective_counter = [0]


def reset_collective_ids() -> None:
    """Reset the id counter. Trainers call this at the START of tracing
    each train step, so ids are a deterministic function of program-local
    trace position — identical across SPMD hosts regardless of what else
    each host traced before (a host-asymmetric probe compile would
    otherwise skew the counter and mis-pair same-id bookkeeping across
    devices), and stable for the persistent compilation cache."""
    _collective_counter[0] = 0


def _next_collective_id() -> int:
    cid = _collective_counter[0]
    bound = int(os.environ.get("MPI4DL_TPU_HALO_COLLECTIVE_IDS", "0"))
    _collective_counter[0] = (cid + 1) % bound if bound else cid + 1
    return cid


def _swap_call(a, b, axis_name: str):
    return pl.pallas_call(
        _swap_kernel(axis_name),
        out_shape=(
            jax.ShapeDtypeStruct(a.shape, a.dtype),
            jax.ShapeDtypeStruct(b.shape, b.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            collective_id=_next_collective_id(), has_side_effects=True
        ),
    )(a, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def strip_swap(a, b, axis_name: str):
    """Bidirectional ring strip swap along a mesh axis (inside shard_map).

    Returns ``(ra, rb)`` where ``ra`` is the ``a`` of the ring-next device
    and ``rb`` is the ``b`` of the ring-previous device (wraparound at the
    ends — callers mask global-boundary tiles).
    """
    return _swap_call(a, b, axis_name)


def _strip_swap_fwd(a, b, axis_name):
    return _swap_call(a, b, axis_name), None


def _strip_swap_bwd(axis_name, _, cts):
    gra, grb = cts
    # ra_i = a_{i+1}  =>  ga_i = gra_{i-1} = "b-slot" routing of gra;
    # rb_i = b_{i-1}  =>  gb_i = grb_{i+1} = "a-slot" routing of grb.
    gb, ga = _swap_call(grb, gra, axis_name)
    return ga, gb


strip_swap.defvjp(_strip_swap_fwd, _strip_swap_bwd)


def _axis_exchange(x, halo: int, axis_name: str, array_axis: int, fill_value):
    """One axis of the halo exchange: returns x extended with ``halo``
    rows/cols of neighbor data on both sides of ``array_axis``."""
    n = axis_size(axis_name)
    size = x.shape[array_axis]
    if halo > size:
        raise ValueError(f"halo={halo} exceeds local tile extent {size}")
    lo = lax.slice_in_dim(x, 0, halo, axis=array_axis)  # my leading strip
    hi = lax.slice_in_dim(x, size - halo, size, axis=array_axis)
    # Send leading strip to prev (their trailing halo), trailing to next.
    from_below, from_above = strip_swap(lo, hi, axis_name)
    idx = lax.axis_index(axis_name)
    fill = jnp.full_like(lo, fill_value)
    from_above = jnp.where(idx == 0, fill, from_above)
    from_below = jnp.where(idx == n - 1, fill, from_below)
    return jnp.concatenate([from_above, x, from_below], axis=array_axis)


def halo_exchange_pallas(
    x,
    halo_h: int,
    halo_w: int,
    axis_h: str = "tile_h",
    axis_w: str = "tile_w",
    fill_value: float = 0.0,
):
    """Drop-in Pallas implementation of
    :func:`mpi4dl_tpu.parallel.halo.halo_exchange` (same contract, same
    two-phase corner composition: W-phase strips of the H-extended tile carry
    the corner halos)."""
    if halo_h > 0 and axis_size(axis_h) >= 1:
        x = _axis_exchange(x, halo_h, axis_h, 1, fill_value)
    if halo_w > 0 and axis_size(axis_w) >= 1:
        x = _axis_exchange(x, halo_w, axis_w, 2, fill_value)
    return x


def default_impl() -> str:
    """Halo implementation selection: ``MPI4DL_TPU_HALO_IMPL`` env var
    (``xla`` | ``pallas``), default ``xla`` (the Pallas path is opt-in until
    profiled on a real multi-chip slice)."""
    return os.environ.get("MPI4DL_TPU_HALO_IMPL", "xla").lower()


def annotate_id_space_error(e: BaseException) -> None:
    """Attach an operator hint to a compile error that looks like
    collective-id-space exhaustion (ADVICE r2): with the Pallas halo impl,
    ids are unique per trace by default, so a large spatial program
    allocates hundreds of distinct ids — on a backend that bounds the id
    space the first symptom is an opaque Mosaic compile failure. Trainers
    call this before re-raising compile-time errors."""
    if default_impl() != "pallas":
        return
    msg = str(e).lower()
    if "collective" not in msg:
        return
    note = (
        "hint: the Pallas halo kernel allocates one collective id per "
        "exchange (unique per trace). If this backend bounds the "
        "collective-id space, set MPI4DL_TPU_HALO_COLLECTIVE_IDS=<bound> "
        "to cycle ids within it (safe: same-id exchanges are serialized "
        "by layer dataflow), or MPI4DL_TPU_HALO_IMPL=xla to avoid Pallas."
    )
    if hasattr(e, "add_note"):  # py3.11+
        e.add_note(note)
