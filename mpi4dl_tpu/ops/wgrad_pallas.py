"""Pallas TPU kernel for the 3x3 conv weight gradient (stride-1, NHWC).

Why: profiling (docs/PERF.md) showed the backward-filter convolution is the
train step's single largest cost class on the bench device. XLA lowers it as
a conv contracting over the *batch* dimension (2 examples), which forces
T(2,128) operand tilings — each wgrad ran HBM-bound at 30-75 GB/s AND paid
two full-tensor layout copies to feed it.

This kernel streams x and dy through VMEM exactly once in their natural
NHWC layouts (no relayout copies) and accumulates the [kw, O, kh*C] tap
gradients in a VMEM f32 scratch across a (batch x row-chunk) grid:

    dw[u, v, c, o] = sum_{b,h,w} xp[b, h+u, w+v, c] * dy[b, h, w, o]

Per grid step it reads one aligned [TH, Wp, C] slab of the padded input
(plus a separate (kh-1)-row "tail" block of the same array — Pallas block
index maps can't express overlapping windows, so the overlap rows come in
through a second BlockSpec) and the matching [TH, Wo, O] slab of dy.

Contraction layout (the round-2 fix + speedup, measured on device):

- Taps are grouped BY W-OFFSET ``v``: the ``kh`` taps of one group differ
  only in their H offset, which is an untiled major dimension of the
  [H, W, C] slab — so their lane/sublane layouts match and the group
  concatenates legally. (Round 1 concatenated all kh*kw taps along the
  minor dim; taps with different ``v`` carry different sublane offsets and
  Mosaic rejects the concat — ``tpu.concatenate ... offset mismatch`` —
  which broke the headline bench, VERDICT weak #1.)
- Each group contracts as ``dy^T @ patches``: [K, O] x [K, kh*C] over the
  flattened pixel dim K = TH*Wo, f32 accumulation. Putting ``kh*C`` (not
  O) in the matmul N position fills the MXU lanes: the reference models
  carry O = 16..64 output channels, and the MXU's effective rate scales
  with N (docs/PERF.md). Measured vs the N=O orientation at C=16@1024px:
  2.7 ms vs 8.3 ms; vs XLA's backward-filter conv: 11.1 ms.

1x1 wgrads don't need this kernel — they are a plain ``x^T @ dy`` dot
(:func:`mpi4dl_tpu.ops.fastconv._conv2d_s1_bwd` handles that inline).

Exactness: same products as the stock wgrad, f32 accumulation, summation
regrouped per (batch, row-chunk) — ``tests/test_wgrad_pallas.py`` checks
math in interpreter mode. Dispatch is guarded by a cached on-device compile
probe (:func:`usable`): Mosaic layout failures only surface at compile time
on real hardware, so the probe falls back to XLA's backward-filter conv
instead of crashing the step (round-1 VERDICT weak #1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-chunk height. Must divide Ho and be a multiple of (kh - 1).
_TH = 8


def _wgrad_kernel(x_ref, xtail_ref, dy_ref, out_ref, acc_ref, *, kh, kw, th):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # [th + kh - 1, Wp, C] slab: aligned block + overlap tail rows.
    x = jnp.concatenate([x_ref[0], xtail_ref[0]], axis=0)
    dy = dy_ref[0]  # [th, Wo, O]
    wo = dy.shape[1]
    dyf = dy.reshape(th * wo, dy.shape[2])
    c = x.shape[2]
    for v in range(kw):
        # Same-v taps differ only in the untiled H dim — legal lane concat.
        xv = lax.slice(x, (0, v, 0), (x.shape[0], v + wo, c))
        taps = [lax.slice(xv, (u, 0, 0), (u + th, wo, c)) for u in range(kh)]
        patches = jnp.concatenate(taps, axis=-1).reshape(th * wo, kh * c)
        # dy^T @ patches: [O, kh*C] — N = kh*C fills the MXU lanes.
        acc_ref[v] += lax.dot_general(
            dyf,
            patches,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == n - 1)
    def _flush():
        out_ref[...] = acc_ref[...]


def supported(xp_shape, dy_shape, kh: int, kw: int,
              x_itemsize: int = 2, dy_itemsize: int = 2) -> bool:
    """Shape gate: stride-1 3x3-class kernels, power-of-two-ish extents."""
    b, hp, wp, c = xp_shape
    _, ho, wo, o = dy_shape
    if kh < 2:  # 1x1 wgrad is a plain dot; handled by the caller
        return False
    if hp != ho + kh - 1 or wp < wo + kw - 1:
        return False
    if ho % _TH or _TH % (kh - 1):
        return False
    x_bytes = (_TH + kh - 1) * wp * c * x_itemsize
    dy_bytes = _TH * wo * o * dy_itemsize
    acc_bytes = kw * o * kh * c * 4
    pat_bytes = _TH * wo * kh * c * x_itemsize
    return x_bytes + dy_bytes + 2 * acc_bytes + pat_bytes < 12 * 1024 * 1024


@functools.lru_cache(maxsize=None)
def _compiles(xp_shape, dy_shape, x_dtype, dy_dtype, kh: int, kw: int) -> bool:
    """One-time compile probe, cached per (shapes, dtypes, taps).

    Mosaic layout failures surface only at compile time on the real TPU —
    interpreter-mode tests cannot catch them (this is exactly how round 1's
    bench broke: ADVICE.md high finding, `tpu.concatenate` offset mismatch).
    Probing the actual lowering before dispatching makes the training step
    un-breakable by kernel compile regressions: on any failure we fall back
    to XLA's backward-filter conv.
    """
    import warnings

    import jax

    try:
        jax.jit(functools.partial(wgrad, kh=kh, kw=kw)).lower(
            jax.ShapeDtypeStruct(xp_shape, x_dtype),
            jax.ShapeDtypeStruct(dy_shape, dy_dtype),
        ).compile()
        return True
    except Exception as e:  # fall back to XLA's wgrad — but say so
        warnings.warn(
            "Pallas wgrad kernel failed to compile for "
            f"xp={xp_shape} dy={dy_shape} k=({kh},{kw}); using the XLA "
            f"backward-filter conv instead. Error: {str(e)[:400]}"
        )
        return False


def usable(xp, dy, kh: int, kw: int) -> bool:
    """supported() + the cached on-device compile probe."""
    if not supported(xp.shape, dy.shape, kh, kw,
                     xp.dtype.itemsize, dy.dtype.itemsize):
        return False
    return _compiles(tuple(xp.shape), tuple(dy.shape),
                     jnp.dtype(xp.dtype).name, jnp.dtype(dy.dtype).name,
                     kh, kw)


@functools.partial(jax.jit, static_argnames=("kh", "kw", "interpret"))
def wgrad(xp, dy, kh: int, kw: int, interpret: bool = False):
    """dw[kh, kw, C, O] (f32) for a stride-1 conv.

    xp: [B, Ho + kh - 1, Wp, C] pre-padded input (Wp >= Wo + kw - 1).
    dy: [B, Ho, Wo, O] output cotangent.
    """
    b, hp, wp, c = xp.shape
    _, ho, wo, o = dy.shape
    assert supported(xp.shape, dy.shape, kh, kw), (xp.shape, dy.shape, kh, kw)
    th = _TH
    rows = ho // th
    tail = kh - 1
    grid = (b * rows,)

    out = pl.pallas_call(
        functools.partial(_wgrad_kernel, kh=kh, kw=kw, th=th),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, th, wp, c), lambda i: (i // rows, i % rows, 0, 0)
            ),
            # Overlap rows [chunk_end, chunk_end + kh - 1) as an aligned
            # block of height (kh - 1): element row (i%rows + 1) * th.
            pl.BlockSpec(
                (1, tail, wp, c),
                lambda i: (i // rows, (i % rows + 1) * (th // tail), 0, 0),
            ),
            pl.BlockSpec(
                (1, th, wo, o), lambda i: (i // rows, i % rows, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((kw, o, kh * c), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((kw, o, kh * c), jnp.float32),
        scratch_shapes=[pltpu.VMEM((kw, o, kh * c), jnp.float32)],
        interpret=interpret,
    )(xp, xp, dy)
    # out[v, o, u*C + c] -> dw[u, v, c, o]
    return out.reshape(kw, o, kh, c).transpose(2, 0, 3, 1)
