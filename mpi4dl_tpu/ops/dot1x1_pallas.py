"""Pallas TPU kernel: fused one-pass 1x1-conv backward (dx + dw).

Why: the round-3/4 AmoebaNet@1024 profiles put ~25-32% of the train step
in ``dot_general`` — dominated by the cells' input-reduce 1x1 conv
backwards, measured HBM-bound (OI 67-205 under the ~240 ridge,
docs/PERF.md round 3). Stock AD emits TWO dots per 1x1 conv backward —
``dx = dy . w^T`` and ``dw = x^T . dy`` (``fastconv._conv2d_s1_bwd``) —
and XLA cannot multi-output-fuse them, so ``dy`` streams from HBM twice.
This kernel computes both in ONE pass over ``dy``: per (batch, row
chunk) grid step it loads the ``x`` and ``dy`` blocks once, issues both
MXU contractions in VMEM, writes the ``dx`` block, and accumulates
``dw`` in a resident f32 block across the sequential TPU grid. HBM
traffic drops from ``2*dy + x + dx`` to ``dy + x + dx`` — the op's
roofline. The reference leaves the equivalent to cuDNN/cuBLAS
(``conv2d`` backward, ``models/amoebanet.py:365-398``); on TPU the
schedule is ours.

**Status: EXPERIMENTAL, off by default — recorded negative (round 5).**
Measured end-to-end @1024 (AmoebaNet bs2, scan_save): 6.957 vs 7.241
img/s baseline (−3.9%) with per-result caps at 32 MB; at 100 MB caps
the full program kills the remote-compile helper (the VMEM-stack
result wall, docs/PERF.md round 4). The one-pass traffic win is real at
the op level but the custom-call boundaries un-fuse the surrounding
program — see ``dot1x1_mode`` for the ledger. Kept for a runtime whose
allocator handles custom-call results in HBM.

Dispatch discipline (the ``pool_pallas``/``wgrad_pallas`` playbook):
``dispatchable()`` = shape/VMEM plan gate + cached on-device compile
probe; batched traces and trainer-armed ``disable()`` contexts
(>=2048px programs) fall back to the stock two-dot path, so a kernel
regression cannot break the step. ``MPI4DL_TPU_DOT1X1=auto`` enables,
``=on`` additionally neutralizes the trainer ``disable()``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_VMEM_BUDGET = 8 * 1024 * 1024


def dot1x1_mode() -> str:
    """Default OFF (recorded negative, round 5): with per-result caps at
    32 MB the @1024 program compiles, but the fused kernel measured
    6.957 vs 7.241 img/s end-to-end (−3.9%) — the relayout/fusion
    boundaries Pallas custom calls impose on the surrounding program
    cost more than the saved dy re-read, the same end-to-end shape the
    pool kernel only escaped via the 4-D carry interaction (docs/PERF.md
    rounds 4–5). At 100 MB caps the full program kills the compile
    helper outright (VMEM-stack-allocated results). Enable for A/B with
    ``MPI4DL_TPU_DOT1X1=auto`` (gates) or ``=on`` (also neutralizes
    trainer ``disable()``)."""
    mode = os.environ.get("MPI4DL_TPU_DOT1X1", "off")
    if mode not in ("auto", "off", "on"):
        raise ValueError(f"MPI4DL_TPU_DOT1X1 must be auto|off|on, got {mode!r}")
    return mode


_DISABLED = [False]


class disable:
    """Trace-time off-switch (same pattern as ``pool_pallas.disable``):
    ``Trainer.train_step`` arms it for >=2048px traces. ``=on`` makes it
    a no-op for A/B revalidation."""

    def __enter__(self):
        self._prev = _DISABLED[0]
        if dot1x1_mode() != "on":
            _DISABLED[0] = True

    def __exit__(self, *exc):
        _DISABLED[0] = self._prev
        return False


def _kernel(x_ref, dy_ref, w_ref, dx_ref, dw_ref):
    step = pl.program_id(0)
    dy = dy_ref[0]  # [hb, W, O]
    hb, wdim, o = dy.shape
    c = w_ref.shape[0]
    dyf = dy.reshape(hb * wdim, o)
    # dx block: [hb*W, O] x [C, O]^T on the MXU, f32 accumulate.
    dx = lax.dot_general(
        dyf, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dx_ref[0] = dx.reshape(hb, wdim, c).astype(dx_ref.dtype)
    # dw partial: [C, hb*W] x [hb*W, O]; resident f32 accumulator (the
    # TPU grid is sequential, so += across steps is well-defined).
    xf = x_ref[0].reshape(hb * wdim, c)
    dwp = lax.dot_general(
        xf, dyf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(step == 0)
    def _init():
        dw_ref[...] = dwp

    @pl.when(step != 0)
    def _acc():
        dw_ref[...] += dwp


def _plan(b, h, w, c, o, itemsize):
    """Row-chunk height hb (divisor of h) fitting the VMEM budget."""
    for hb in (32, 16, 8, 4, 2, 1):
        if h % hb:
            continue
        block = hb * w * (c + o) * itemsize  # x + dy blocks
        block += hb * w * c * (itemsize + 4)  # dx out + f32 dx temp
        block += c * o * (itemsize + 4)  # w + dw accumulator
        if block < _VMEM_BUDGET:
            return hb
    return None


def supported(x_shape, o, itemsize=2) -> bool:
    b, h, w, c = x_shape
    # Lane-dim blocks carry whole C/O (no chunking): Mosaic accepts whole
    # dims of any width; tiny widths just waste lanes — require the
    # benchmark models' >=104-channel regime.
    if c < 104 or o < 104:
        return False
    # VMEM-stack-allocated result guard (docs/PERF.md round 4): this
    # runtime stack-allocates custom-call results, and the budget
    # interacts with co-resident calls unmodelably — a 100 MB cap let
    # per-shape probes pass while the FULL @1024 program (many engaged
    # 27-54 MB dx results across the scanned cells) killed the compile
    # helper (round 5). Cap per-result size hard.
    cap_mb = float(os.environ.get("MPI4DL_TPU_DOT1X1_CAP_MB", "32"))
    if b * h * w * c * itemsize > cap_mb * 1024 * 1024:
        return False
    return _plan(b, h, w, c, o, itemsize) is not None


def _bwd_impl(x, dy, w2, interpret=False):
    """(dx, dw_f32) from x [B,H,W,C], dy [B,H,W,O], w2 [C,O]."""
    b, h, wdim, c = x.shape
    o = dy.shape[-1]
    hb = _plan(b, h, wdim, c, o, x.dtype.itemsize)
    assert hb is not None, (x.shape, o)
    nh = h // hb
    grid = (b * nh,)

    def blk(i):
        return (i // nh, i % nh, 0, 0)

    dx, dw = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, hb, wdim, c), blk),
            pl.BlockSpec((1, hb, wdim, o), blk),
            pl.BlockSpec((c, o), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, wdim, c), blk),
            pl.BlockSpec((c, o), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, wdim, c), x.dtype),
            jax.ShapeDtypeStruct((c, o), jnp.float32),
        ],
        interpret=interpret,
    )(x, dy, w2)
    return dx, dw


@functools.lru_cache(maxsize=None)
def _compiles(x_shape, dtype, o, w_dtype) -> bool:
    """Cached on-device compile probe (Mosaic/VMEM-stack failures only
    surface on real hardware). The weight dtype is part of the key AND the
    probed signature: mixed-precision params (f32 weights under bf16
    activations) compile a DIFFERENT Mosaic program than the homogeneous
    one, and a probe that passed for x's dtype must not green-light an
    unprobed path (ADVICE r5)."""
    import warnings

    try:
        b, h, w, c = x_shape
        jax.jit(_bwd_impl).lower(
            jax.ShapeDtypeStruct((b, h, w, c), dtype),
            jax.ShapeDtypeStruct((b, h, w, o), dtype),
            jax.ShapeDtypeStruct((c, o), w_dtype),
        ).compile()
        return True
    except Exception as e:  # noqa: BLE001 — fall back to the two-dot path
        warnings.warn(
            "fused 1x1 backward kernel failed to compile for "
            f"x={x_shape} O={o} w_dtype={w_dtype}; using the XLA two-dot "
            f"backward. Error: {str(e)[:400]}"
        )
        return False


def dispatchable(x, dy, w=None) -> bool:
    """``w``: the conv weight (any shape; only its dtype matters here).
    ``None`` keeps the legacy assumption w.dtype == x.dtype."""
    from mpi4dl_tpu.parallel.halo import _is_batch_tracer, _xla_only_active

    if dot1x1_mode() == "off":
        return False
    if _DISABLED[0] or _xla_only_active():
        return False
    if jax.default_backend() != "tpu":
        return False
    if _is_batch_tracer(x) or _is_batch_tracer(dy):
        return False
    if x.ndim != 4 or dy.ndim != 4:
        return False
    if not supported(tuple(x.shape), dy.shape[-1], x.dtype.itemsize):
        return False
    w_dtype = jnp.dtype(w.dtype if w is not None else x.dtype).name
    return _compiles(
        tuple(x.shape), jnp.dtype(x.dtype).name, dy.shape[-1], w_dtype
    )


def bwd_1x1(x, dy, w2, interpret=False):
    """Fused (dx, dw) — callers gate with :func:`dispatchable`."""
    return _bwd_impl(x, dy, w2, interpret=interpret)
