"""Persistently-packed activation layout: the 128-lane pad-tax killer.

The problem (measured, docs/PERF.md round 2): TPU HBM stores a tensor's
minormost (channel) dim padded to the 128-lane tile, so the reference
models' small-channel/high-resolution trunks ([B, 1024, 1024, 16] and
friends) occupy up to 8x their logical bytes, and EVERY op touching them —
convs, BN, relu, residual adds — moves 8x the traffic. A 512px profile
showed the train step spending ~2/3 of its time in exactly those ops.

The fix is a layout change, not new math: activations live as

    [B, H, W/f, f*C]   with  f = 128 // C   (the "packed" layout)

which is bit-identical memory to NHWC *when C is minormost and dense* —
``pack``/``unpack`` are free reshapes of the logical data — but as the
tensor's actual shape it makes the minormost dim 128 wide, so HBM stores it
dense. BN, relu, and residual adds run on packed tensors unchanged (8x
less traffic); convolutions run directly on the packed form via a
*scattered kernel*: a stride-``s`` logical conv becomes a stride-``s'``
packed conv whose kernel gathers the right (tap, subpixel) pairs:

    y[b, h, f_out*jo + p, o] = sum_{u,v,c} x[b, h+u-ph, s*(f_out*jo+p)+v-pw, c]
                                          * K[u, v, c, o]

  packs to   yp[b, h, jo, p*O + o] = sum_{u, tt, q, c}
                 xp[b, h+u-ph, s'*jo + tt - pl', q*C + c] * Kp[u, tt, qC+c, pO+o]

  with  s' = s*f_out/f_in,  Kp[u, tt, q*C+c, p*O+o] = K[u, v, c, o]  where
  v = f_in*(tt - pl') + q - s*p + pw   (zero when v is out of kernel range).

Zero taps contribute exact zeros to the f32 accumulator and logical edge
padding coincides with whole packed-column padding (W % f == 0), so the
result is the same sum of the same products as the logical conv (mod f32
accumulation order). FLOPs inflate (kw'*f_in / kw useful fraction) but the
matmul's N dim becomes f_out*O = 128 — the MXU rate law (docs/PERF.md)
makes that a measured net win for every small-channel shape:

    fwd conv, one chip (ms):      packed    stock-NHWC
    3x3 16ch  @1024px              3.06       6.24
    3x3 32ch  @512px               2.74       5.06
    3x3 64ch  @256px               2.69       2.91

This is the pure-XLA successor to two earlier attempts: output-only
W-packing (ops/fastconv.py — input stays padded) and a Pallas compact-conv
kernel (round 2 — dead on arrival: Pallas block DMA on the bench runtime
tops out at ~45 GB/s vs XLA's ~350+ GB/s, see docs/PERF.md).

Parameter trees match the stock modules exactly (kernel [kh,kw,C,O], bias
[O], BN scale/bias [C]) so checkpoints and golden tests are interchangeable.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name


def pack_factor(c: int, w: int | None = None) -> int:
    """Subpixels per packed column for a C-channel tensor (1 = unpacked).
    ``w`` (logical width) caps the factor so W % f == 0."""
    f = max(128 // c, 1)
    if w is not None:
        while f > 1 and w % f:
            f //= 2
    return f


def pack(x, f: int):
    """[B, H, W, C] -> [B, H, W/f, f*C]; logical bytes unchanged."""
    if f == 1:
        return x
    b, h, w, c = x.shape
    return x.reshape(b, h, w // f, f * c)


def unpack(x, f: int):
    """[B, H, W/f, f*C] -> [B, H, W, C]."""
    if f == 1:
        return x
    b, h, wf, fc = x.shape
    return x.reshape(b, h, wf * f, fc // f)


def _plan(kw: int, s: int, pw: int, f_in: int, f_out: int):
    """Static W-axis plan: (stride', pad', vidx[kw', f_in, f_out], mask)."""
    assert (s * f_out) % f_in == 0, (s, f_in, f_out)
    s_p = s * f_out // f_in
    ts = [s * p + v - pw for p in range(f_out) for v in range(kw)]
    t_lo = min(ts) // f_in if min(ts) >= 0 else -((-min(ts) + f_in - 1) // f_in)
    t_hi = max(ts) // f_in
    kw_p = t_hi - t_lo + 1
    pl_p = -t_lo
    vidx = np.zeros((kw_p, f_in, f_out), np.int32)
    mask = np.zeros((kw_p, f_in, f_out), bool)
    for tt in range(kw_p):
        for q in range(f_in):
            for p in range(f_out):
                v = f_in * (tt - pl_p) + q - s * p + pw
                if 0 <= v < kw:
                    vidx[tt, q, p] = v
                    mask[tt, q, p] = True
    return s_p, pl_p, vidx, mask


def packed_kernel(w, f_in: int, f_out: int, s: int, pw: int):
    """[kh, kw, C, O] -> scattered [kh, kw', f_in*C, f_out*O] (+ plan)."""
    kh, kw, c, o = w.shape
    s_p, pl_p, vidx, mask = _plan(kw, s, pw, f_in, f_out)
    g = w[:, jnp.asarray(vidx.reshape(-1))]  # [kh, kw'*f_in*f_out, C, O]
    g = g.reshape(kh, vidx.shape[0], f_in, f_out, c, o)
    g = jnp.where(jnp.asarray(mask)[None, :, :, :, None, None], g, 0)
    kp = g.transpose(0, 1, 2, 4, 3, 5).reshape(
        kh, vidx.shape[0], f_in * c, f_out * o
    )
    return kp, s_p, pl_p


def _taps_profitable_packed(x) -> bool:
    """Use the per-tap wgrad for the packed core conv when the operand is
    large AND the contraction batch is tiny (B <= 2): XLA's backward-
    filter form space-to-depth-copies x AND dy (~4.5 GB of copies at
    3072px bs=1 — docs/PERF.md round 4) because the contraction batch
    underfills the feature dim; at larger batches the pathology is gone
    and taps would just pay kh*kw' re-reads. Taps on the packed layout
    are MXU-friendly (128-lane operands). Shares fastconv's off switch
    (MPI4DL_TPU_WGRAD_TAPS) and its single threshold (taps_min_mb: the
    3072 MB default, the Trainer's big-image context, or the env
    override — one value for both gates)."""
    import os

    from mpi4dl_tpu.ops.fastconv import taps_min_mb

    if os.environ.get("MPI4DL_TPU_WGRAD_TAPS", "auto") == "off":
        return False
    min_mb = taps_min_mb()
    b, c = x.shape[0], x.shape[-1]
    # Gate on the PADDED copy estimate, not raw bytes: the backward-filter
    # form pads the operand ~256/(B*C)-fold (an un-packed 3-channel stem
    # input at 4096px is 96 MB raw but an 8 GB padded copy — docs/PERF.md
    # round 4); fully-packed 128-lane operands still pay ~2x plus the
    # space-to-depth copies.
    expansion = 256.0 / (b * min(c, 128))
    return (
        b <= 2
        and float(np.prod(x.shape)) * x.dtype.itemsize * max(expansion, 2.0)
        >= min_mb * 1e6
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _packed_core(x, kp, strides, padding):
    """The packed conv's core ``conv_general_dilated`` with a backward
    that dodges the wgrad space-to-depth copies at large sizes."""
    return lax.conv_general_dilated(
        x, kp, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def _packed_core_fwd(x, kp, strides, padding):
    return _packed_core(x, kp, strides, padding), (x, kp)


def _packed_core_bwd(strides, padding, res, dy):
    from mpi4dl_tpu.ops.fastconv import conv_bwd_with_taps

    x, kp = res
    return conv_bwd_with_taps(
        lambda xx, kk: lax.conv_general_dilated(
            xx, kk, strides, padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ),
        _taps_profitable_packed,
        x, kp, dy, strides, padding,
    )


_packed_core.defvjp(_packed_core_fwd, _packed_core_bwd)


def _core(x, kp, strides, padding):
    """Dispatch: the custom-VJP core only when the taps gate is armed for
    this shape — wrapping every conv in a custom_vjp was measured ~10%
    slower end-to-end at @1024 (the wrapper pins residuals and walls off
    fwd/bwd fusion XLA otherwise does); stock AD handles the small-size
    regime exactly as before."""
    if _taps_profitable_packed(x):
        return _packed_core(x, kp, strides, padding)
    return lax.conv_general_dilated(
        x, kp, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def conv2d_packed(
    xp,
    w,
    f_in: int,
    f_out: int,
    strides,
    padding,
    spatial: bool = False,
):
    """Logical conv on packed operands. xp [B, H, W/f_in, f_in*C];
    w [kh, kw, C, O] (logical params); strides (sh, sw) with sh == sw;
    padding ((ph, ph), (pw, pw)) logical. Returns [B, H', W'/f_out, f_out*O].

    ``spatial=True`` (inside ``shard_map`` over the tile mesh axes) replaces
    the zero padding with a halo exchange — ref ``conv_spatial``
    (``spatial.py:25-1029``) on the packed layout. The exchange moves WHOLE
    packed columns: a packed column is bit-identical memory to ``f_in``
    logical columns, so the neighbor's edge column block carries exactly the
    logical halo (plus up to ``f_in - pw`` extra columns that the scattered
    kernel's zero taps ignore), and ``ppermute``'s zero fill at the mesh
    boundary reproduces ``ZeroPad2d`` semantics — the packed conv's masked
    taps never read past the logical pad width.
    """
    sh, sw = strides
    (ph0, ph1), (pw0, pw1) = padding
    assert pw0 == pw1, "packed conv needs symmetric W padding"
    kh, kw = w.shape[0], w.shape[1]
    kp, s_p, pl_p = packed_kernel(w, f_in, f_out, sw, pw0)
    win_p = xp.shape[2]

    if spatial:
        from mpi4dl_tpu.parallel.halo import halo_exchange

        assert ph0 == ph1, "packed spatial conv needs symmetric H padding"
        if (win_p * f_in) % (sw * f_out):
            raise ValueError(
                f"packed spatial conv: local width {win_p * f_in} must "
                f"divide by stride*f_out={sw * f_out}"
            )
        wout_p = win_p * f_in // (sw * f_out)  # this tile's output columns
        # Column halo wide enough for both the plan's left pad and the
        # rightmost window; off realigns the VALID output grid when the
        # exchange is wider than the plan's left pad.
        pr_p = s_p * (wout_p - 1) + kp.shape[1] - pl_p - win_p
        hw_p = max(pl_p, pr_p, 0)
        off, rem = divmod(hw_p - pl_p, s_p)
        if rem:
            raise ValueError(
                "packed spatial conv: halo width misaligned with the packed "
                f"stride (pl'={pl_p}, pr'={pr_p}, s'={s_p})"
            )
        h_loc = xp.shape[1]
        xe = halo_exchange(xp, ph0, hw_p)
        y = _core(xe, kp, (sh, s_p), ((0, 0), (0, 0)))
        return y[:, : h_loc // sh, off : off + wout_p, :]

    w_logical = win_p * f_in
    w_out = (w_logical + 2 * pw0 - kw) // sw + 1
    if w_out % f_out:
        raise ValueError(
            f"packed conv output width {w_out} must divide by f_out={f_out} "
            "(columns would be silently dropped); use a pack factor that "
            "divides the width"
        )
    wout_p = w_out // f_out
    # Right padding sized so the packed conv emits exactly wout_p columns
    # (the scattered kernel's tap range is asymmetric in general).
    pr_p = s_p * (wout_p - 1) + kp.shape[1] - pl_p - win_p
    return _core(xp, kp, (sh, s_p), ((ph0, ph1), (pl_p, pr_p)))


class PackedConv(nn.Module):
    """Conv on persistently-packed activations. Parameter tree ("kernel"
    [kh, kw, C, O], "bias" [O]) matches ``FastConv``/``nn.Conv`` exactly."""

    features: int
    kernel_size: tuple[int, int]
    pack_in: int
    pack_out: int
    strides: tuple[int, int] = (1, 1)
    padding: tuple[tuple[int, int], tuple[int, int]] = ((0, 0), (0, 0))
    use_bias: bool = True
    spatial: bool = False  # halo-exchange instead of zero pad (shard_map)
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        import os

        if os.environ.get("MPI4DL_TPU_COUNTING_FLOPS"):
            raise ValueError(
                "MFU FLOPs must be counted on the logical (stock-layout) "
                "model: PackedConv executes inflated scattered-kernel FLOPs "
                "by design (see mpi4dl_tpu/flops.py)"
            )
        kh, kw = self.kernel_size
        c_in = x.shape[-1] // self.pack_in
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, c_in, self.features),
            jnp.float32,
        )
        bias = (
            self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
            )
            if self.use_bias
            else None
        )
        x, kernel, bias = nn.dtypes.promote_dtype(x, kernel, bias, dtype=self.dtype)
        y = conv2d_packed(
            x, kernel, self.pack_in, self.pack_out, self.strides, self.padding,
            spatial=self.spatial,
        )
        if bias is not None:
            y = y + jnp.tile(bias, self.pack_out)
        # scan_save remat tag (see fastconv.save_compact_enabled): packed
        # tensors are already dense-lane, no compact reshape needed.
        from mpi4dl_tpu.ops.fastconv import save_compact_enabled

        if save_compact_enabled():
            y = checkpoint_name(y, "conv_out")
        return y


class PackedTrainBatchNorm(nn.Module):
    """TrainBatchNorm on packed activations: statistics fold the subpixel
    axis into the batch axes, parameters stay logical [C] — numerics and
    parameter tree identical to ``TrainBatchNorm`` on the unpacked tensor
    (sums regrouped; f32 accumulation as there)."""

    pack: int
    eps: float = 1e-5
    reduce_axes: tuple[str, ...] = ()
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        from mpi4dl_tpu.ops.layers import _accumulate_bn_stats, current_bn_mode

        fc = x.shape[-1]
        c = fc // self.pack
        scale = self.param("scale", nn.initializers.ones_init(), (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (c,), jnp.float32)
        if current_bn_mode() == "running":
            # Frozen calibration stats (mpi4dl_tpu/evaluate.py) — logical
            # [C], tiled over the subpixel axis like w/b below.
            mean = self.variable(
                "batch_stats", "mean", jnp.zeros, (c,), jnp.float32
            ).value
            var = self.variable(
                "batch_stats", "var", jnp.ones, (c,), jnp.float32
            ).value
            w = (lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
            b = (bias - mean * lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
            return x * jnp.tile(w, self.pack) + jnp.tile(b, self.pack)
        # Moments over the leading axes per PACKED channel (convert-free
        # backward — layers.bn_moments), then averaged over the pack groups
        # (equal group sizes: mean of group means == pooled mean).
        from mpi4dl_tpu.ops.layers import bn_moments

        m_pc, msq_pc = bn_moments(x)
        mean = m_pc.reshape(self.pack, c).mean(0)
        mean_sq = msq_pc.reshape(self.pack, c).mean(0)
        if self.reduce_axes:
            mean = lax.pmean(mean, self.reduce_axes)
            mean_sq = lax.pmean(mean_sq, self.reduce_axes)
        if current_bn_mode() == "collect":
            _accumulate_bn_stats(self, mean, mean_sq)
        var = mean_sq - jnp.square(mean)
        w = (lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
        b = (bias - mean * lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
        return x * jnp.tile(w, self.pack) + jnp.tile(b, self.pack)
