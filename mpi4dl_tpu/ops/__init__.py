from mpi4dl_tpu.ops.layers import (  # noqa: F401
    Conv2d,
    Dense,
    Pool,
    TrainBatchNorm,
    HaloExchange,
    Sequential,
)
