"""Core layer library (plain + spatially-partitioned variants).

One set of modules covers what the reference implements three times over
(``src/torchgems/spatial.py`` ``conv_spatial``/``halo_exchange_layer``/``Pool``
plus the plain torch layers): every module takes a ``spatial`` flag, and when
set, runs on a local image tile inside ``shard_map`` using
:func:`mpi4dl_tpu.parallel.halo.halo_exchange` for boundary data.

Layout is NHWC throughout (TPU-native; the reference is NCHW).

Semantics parity notes:

- ``Conv2d(spatial=True)`` == ref ``conv_spatial`` (``spatial.py:25-1029``):
  zero-pad via neighbor halos then VALID conv; stride-2 requires
  power-of-two tiles, matching ref's asserts (``train_spatial.py:25-58``).
- ``TrainBatchNorm`` normalizes with current-batch statistics (training
  mode). With ``reduce_axes=()`` statistics are tile-local — exactly the
  reference's per-tile BN behavior under SP. With mesh axis names, stats are
  ``pmean``-ed across tiles (cross-tile BN) which restores bit-parity with a
  single-device golden model; this is what the spatial model builders use by
  default. Eval-time stats come from a *calibration pass* rather than EMA
  buffers mutated inside the train step (which stays pure/donated): see
  :func:`bn_stats_mode` and :mod:`mpi4dl_tpu.evaluate`. (The reference has
  no eval path at all — its BN buffers are written but never read.)
- ``Pool(spatial=True)`` == ref ``Pool`` (``spatial.py:1416-1509``): halo
  exchange of ``padding`` rows/cols, then VALID pooling.
- ``HaloExchange`` == ref ``halo_exchange_layer`` (``spatial.py:1032-1413``),
  the building block of the D2 fused-halo design.
"""

from __future__ import annotations

import contextlib
import functools
import math
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.config import AXIS_TILE_H, AXIS_TILE_W
from mpi4dl_tpu.ops.fastconv import FastConv
from mpi4dl_tpu.parallel.halo import halo_exchange, zero_boundary_halo

TILE_AXES = (AXIS_TILE_H, AXIS_TILE_W)


def _pair(v) -> tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_overlap_impl() -> str:
    """Spatial windowed-op decomposition selector: ``"monolithic"``
    (default — one VALID op over the whole halo-extended tile) or
    ``"decomposed"`` (interior op with NO data dependency on the halo
    ppermutes + thin boundary-strip ops consuming the exchanged halo,
    stitched into the identical output — see :func:`overlap_decompose`).
    ``MPI4DL_TPU_CONV_OVERLAP`` sets the process default; the ``overlap=``
    field on :class:`Conv2d` / :class:`Pool` overrides per layer."""
    import os

    impl = os.environ.get("MPI4DL_TPU_CONV_OVERLAP", "monolithic")
    impl = {
        "0": "monolithic", "off": "monolithic",
        "1": "decomposed", "on": "decomposed",
    }.get(impl, impl)
    if impl not in ("monolithic", "decomposed"):
        raise ValueError(
            "MPI4DL_TPU_CONV_OVERLAP must be monolithic|decomposed "
            f"(or 0/1/off/on), got {impl!r}"
        )
    return impl


# Trace-time recorders of PLAIN (non-spatial) windowed-op geometry — the
# count_halo_shifts pattern applied to receptive-field math instead of
# permute counting: tracing a model section under record_windowed_ops()
# (e.g. with jax.eval_shape — no device work) yields every conv/pool's
# kernel/stride/padding and input extent in call order, which is exactly
# the partition-math input the tiled-inference margin derives from
# (serve/tiled.py: margin = cumulative receptive-field growth, the
# single-device analogue of the spatial halo the exchange ops carry).
_WINDOWED_OP_RECORDERS: "list[list]" = []


@contextlib.contextmanager
def record_windowed_ops():
    """Record plain windowed-op geometry issued while tracing the
    enclosed region. Yields a list of dicts (kind/kernel/strides/
    padding/input_hw, in call order); packed-layout ops record
    ``kind="packed"`` so consumers that cannot reason about the packed
    column layout can refuse loudly instead of mis-stitching."""
    box: list = []
    _WINDOWED_OP_RECORDERS.append(box)
    try:
        yield box
    finally:
        _WINDOWED_OP_RECORDERS.remove(box)


def _record_windowed_op(kind, x, kh, kw, sh, sw, ph, pw, **extra) -> None:
    if not _WINDOWED_OP_RECORDERS:
        return
    rec = {
        "kind": kind,
        "kernel": (int(kh), int(kw)),
        "strides": (int(sh), int(sw)),
        "padding": (int(ph), int(pw)),
        "input_hw": (int(x.shape[1]), int(x.shape[2])),
        **extra,
    }
    for box in _WINDOWED_OP_RECORDERS:
        box.append(rec)


def _strip_bounds(n: int, k: int, s: int, p: int) -> tuple[int, int, int]:
    """Per-dim split of a spatial op's output rows into halo-dependent
    boundary strips and a halo-free interior.

    A VALID windowed op over the halo-extended tile produces ``n // s``
    output rows (post-trim); output row ``i`` consumes input rows
    ``[i*s - p, i*s - p + k - 1]`` of the LOCAL tile. Rows whose window
    stays inside ``[0, n)`` need no neighbor data. Returns
    ``(t_lo, t_hi, n_out)``: the count of output rows needing the
    low-side / high-side halo, and the trimmed output extent."""
    n_out = n // s
    t_lo = min(n_out, -(-p // s))  # first interior row: ceil(p/s)
    hi_int = (n - k + p) // s      # last row with i*s + k-1 - p <= n-1
    t_hi = min(n_out, max(0, n_out - 1 - hi_int))
    return t_lo, t_hi, n_out


def overlap_decompose(x, xe, op, kh, kw, sh, sw, ph, pw):
    """Compute ``op(xe)[:, :H//sh, :W//sw]`` as an interior application on
    the un-exchanged tile plus thin boundary-strip applications on the
    halo-extended tile — exact output stitching, different dataflow.

    ``op`` is any position-independent VALID windowed op with strides
    ``(sh, sw)`` and window ``(kh, kw)`` (a conv, a pool). The interior
    call reads ``x`` alone, so it has NO data dependency on the
    ``lax.ppermute`` chain that produced ``xe`` and XLA's scheduler is
    free to run it concurrently with the exchange (the T3/FLUX
    interior/boundary overlap decomposition, arXiv:2401.16677 /
    2406.06858). The boundary strips — at most ``ceil(p/s)`` output
    rows/cols per side — consume the halo once it arrives. Every output
    window sees exactly the bytes the monolithic op saw (boundary fill
    included, since the strips slice ``xe`` itself), so the stitched
    result is window-for-window identical.

    Returns the stitched ``[B, H//sh, W//sw, C']`` array, or ``None``
    when the tile is too small to have a non-empty interior in both dims
    (caller falls back to the monolithic path)."""
    b, h, w, c = x.shape
    tt, tb, ho = _strip_bounds(h, kh, sh, ph)
    tl, tr, wo = _strip_bounds(w, kw, sw, pw)
    if tt + tb >= ho or tl + tr >= wo or (tt + tb + tl + tr) == 0:
        return None
    n_ih, n_iw = ho - tt - tb, wo - tl - tr
    r0, c0 = tt * sh - ph, tl * sw - pw
    y_int = op(x[
        :,
        r0 : r0 + (n_ih - 1) * sh + kh,
        c0 : c0 + (n_iw - 1) * sw + kw,
        :,
    ])
    # Middle band: [left strip | interior | right strip] over the interior
    # rows; the side strips read xe rows aligned with the interior ones.
    mid = y_int
    if tl:
        y_l = op(xe[
            :, tt * sh : (ho - tb - 1) * sh + kh, : (tl - 1) * sw + kw, :
        ])
        mid = jnp.concatenate([y_l[:, :n_ih, :tl, :], mid], axis=2)
    if tr:
        y_r = op(xe[
            :, tt * sh : (ho - tb - 1) * sh + kh, (wo - tr) * sw :, :
        ])
        mid = jnp.concatenate([mid, y_r[:, :n_ih, :tr, :]], axis=2)
    parts = []
    if tt:
        y_top = op(xe[:, : (tt - 1) * sh + kh, :, :])
        parts.append(y_top[:, :tt, :wo, :])
    parts.append(mid)
    if tb:
        y_bot = op(xe[:, (ho - tb) * sh :, :, :])
        parts.append(y_bot[:, :tb, :wo, :])
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _check_window_coverage(kh, kw, sh, sw, ph, pw):
    """A spatially-partitioned windowed op is only exact when the halo
    (== padding) covers the window overlap beyond the stride: windows that
    straddle a tile boundary need ``k - s`` rows/cols of neighbor data and the
    exchange provides ``2*p``. The reference enforces the pool flavor of this
    with asserts (``spatial.py:1445-1464``); without the check the stitched
    output silently drops cross-boundary windows."""
    if kh - sh > 2 * ph or kw - sw > 2 * pw:
        raise ValueError(
            f"spatial window op needs padding >= (kernel - stride)/2 per dim "
            f"to cover tile-boundary windows; got kernel=({kh},{kw}) "
            f"strides=({sh},{sw}) padding=({ph},{pw})"
        )


# --- BN statistics mode -----------------------------------------------------
# Trace-time switch read by TrainBatchNorm/PackedTrainBatchNorm. "batch"
# (the default) declares NO extra variables, so the train step's params-only
# plumbing is untouched. "collect" accumulates exact pooled statistics into
# a mutable "batch_stats" collection (a calibration pass — cf. BN
# re-estimation practice); "running" normalizes with frozen {mean, var} from
# that collection (inference). A plain global rather than a module field so
# no model builder, cell class, or trainer needs a new knob; each
# mode-specific callable is traced exactly once under its own mode
# (mpi4dl_tpu/evaluate.py), so jit caching never crosses modes.
_BN_MODE = ["batch"]


def current_bn_mode() -> str:
    return _BN_MODE[0]


@contextlib.contextmanager
def bn_stats_mode(mode: str):
    """Trace the enclosed model application in the given BN mode
    ("batch" | "collect" | "running"). See module docstring."""
    if mode not in ("batch", "collect", "running"):
        raise ValueError(f"bn mode must be batch|collect|running, got {mode!r}")
    prev = _BN_MODE[0]
    _BN_MODE[0] = mode
    try:
        yield
    finally:
        _BN_MODE[0] = prev


def _bn_moments_plain(x):
    """Stock-AD variant of :func:`bn_moments` (``MPI4DL_TPU_BN_BWD=xla``)
    for A/B isolation; numerics identical modulo where rounding lands."""
    red = tuple(range(x.ndim - 1))
    n = math.prod(x.shape[a] for a in red)
    mean = jnp.sum(x, red, dtype=jnp.float32) / n
    mean_sq = jnp.sum(jnp.square(x.astype(jnp.float32)), red) / n
    return mean, mean_sq


def bn_bwd_impl() -> str:
    """BN-moments backward selector: "xla" (default — stock AD) or
    "fused" (the convert-free custom VJP below). Measured on one v5e
    (docs/PERF.md round 4): fused is NEUTRAL at @2048 (1.271 vs 1.273)
    and @1024 (6.311 vs 6.371) — the convert_element_type self-time it
    removes from the jaxpr was already fused traffic. Kept as an A/B
    lever; gradcheck-verified equal to stock AD."""
    import os

    impl = os.environ.get("MPI4DL_TPU_BN_BWD", "xla")
    if impl not in ("fused", "xla"):
        raise ValueError(f"MPI4DL_TPU_BN_BWD must be fused|xla, got {impl!r}")
    return impl


def bn_moments(x):
    """Dispatch: stock AD (default) or the convert-free custom backward
    (``MPI4DL_TPU_BN_BWD=fused`` — see :func:`bn_bwd_impl` for the
    measured-neutral verdict that set the default)."""
    if bn_bwd_impl() == "fused":
        return _bn_moments_fused(x)
    return _bn_moments_plain(x)


@jax.custom_vjp
def _bn_moments_fused(x):
    """:func:`_bn_moments_plain` with a hand-written backward that never
    materializes a full-resolution f32 cotangent.

    Motivation: the stock AD of ``sum(square(x.astype(f32)))`` computes
    ``2x·ct`` in f32 and converts it down — traced as full-res
    convert_element_type + f32-width mul traffic. The cotangents of
    per-channel SUMS are per-channel scalars, so the backward here stays
    entirely in the input dtype: ``dx = x * (2·ct_sq/n) + ct_mean/n``.
    Same formula stock AD computes, modulo where the bf16 rounding lands;
    gradcheck-verified equal. Measured NEUTRAL end to end on one v5e
    (the traced converts were already fused traffic — see
    :func:`bn_bwd_impl`), so this is the ``fused`` A/B lever, not the
    default.
    """
    return _bn_moments_plain(x)


def _bn_moments_fwd(x):
    return _bn_moments_plain(x), x


def _bn_moments_bwd(x, cts):
    ct_mean, ct_sq = cts  # [C], f32
    red = tuple(range(x.ndim - 1))
    n = math.prod(x.shape[a] for a in red)
    scale = ((2.0 / n) * ct_sq).astype(x.dtype)
    shift = (ct_mean / n).astype(x.dtype)
    return (x * scale + shift,)


_bn_moments_fused.defvjp(_bn_moments_fwd, _bn_moments_bwd)


class TrainBatchNorm(nn.Module):
    """Batch normalization using current-batch statistics.

    reduce_axes: mesh axis names to average statistics over (cross-tile BN
    under spatial partitioning). Empty → local statistics (torch
    ``BatchNorm2d`` training-mode parity per device/tile).

    Under ``bn_stats_mode("collect")`` the (cross-tile-reduced) per-batch
    moments are additionally summed into a ``batch_stats`` collection;
    under ``bn_stats_mode("running")`` frozen ``{mean, var}`` stats from
    that collection replace the batch statistics (eval / inference).
    """

    eps: float = 1e-5
    reduce_axes: tuple[str, ...] = ()
    interior: tuple[int, int] = (0, 0)  # (halo_h, halo_w) rows/cols to EXCLUDE
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(), (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (c,), jnp.float32)
        if current_bn_mode() == "running":
            mean = self.variable(
                "batch_stats", "mean", jnp.zeros, (c,), jnp.float32
            ).value
            var = self.variable(
                "batch_stats", "var", jnp.ones, (c,), jnp.float32
            ).value
            w = (lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
            b = (bias - mean * lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
            return x * w + b
        # D2 fused-halo tiles carry `interior` rows/cols of neighbor data;
        # excluding them from the statistics makes cross-tile (pmean) stats
        # bit-identical to the plain model's — a correctness refinement over
        # the reference, which lets halo pixels skew per-tile BN.
        ih, iw = self.interior
        stat_src = x
        if ih:
            stat_src = stat_src[:, ih:-ih, :, :]
        if iw:
            stat_src = stat_src[:, :, iw:-iw, :]
        # Statistics in f32 with the upcast fused into the reductions, the
        # squaring AFTER the upcast (E[x^2]-E[x]^2 cancels catastrophically
        # if x^2 is rounded to bf16 first), and a custom backward that never
        # materializes a full-res f32 cotangent (see bn_moments). The
        # normalize below stays in the input dtype, which profiling showed
        # otherwise costs ~12% of a bf16 train step in converts alone.
        mean, mean_sq = bn_moments(stat_src)
        if self.reduce_axes:
            mean = lax.pmean(mean, self.reduce_axes)
            mean_sq = lax.pmean(mean_sq, self.reduce_axes)
        if current_bn_mode() == "collect":
            _accumulate_bn_stats(self, mean, mean_sq)
        var = mean_sq - jnp.square(mean)
        w = (lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
        b = (bias - mean * lax.rsqrt(var + self.eps) * scale).astype(x.dtype)
        return x * w + b


def _accumulate_bn_stats(mod: nn.Module, mean, mean_sq) -> None:
    """Sum this batch's (cross-tile-reduced) moments into the module's
    ``batch_stats`` collection. Equal-size calibration batches make the
    averaged moments EXACT pooled statistics (mean of per-batch E[x] and
    E[x²] over equal counts = pooled E[x] / E[x²]) — no EMA decay error."""
    c = mean.shape
    cnt = mod.variable("batch_stats", "count", jnp.zeros, (), jnp.float32)
    ms = mod.variable("batch_stats", "mean_sum", jnp.zeros, c, jnp.float32)
    mq = mod.variable("batch_stats", "mean_sq_sum", jnp.zeros, c, jnp.float32)
    cnt.value = cnt.value + 1.0
    ms.value = ms.value + mean
    mq.value = mq.value + mean_sq


class Conv2d(nn.Module):
    """2-D convolution, optionally spatially partitioned.

    Plain mode: symmetric zero padding ``padding`` (default (k-1)//2, torch
    style), stride ``strides``.

    Spatial mode (ref ``conv_spatial.forward`` ``spatial.py:1019-1029``):
    halo-exchange ``padding`` rows/cols from neighbor tiles, VALID conv on the
    extended tile, trim to ``H_local/stride`` outputs (exact equivalence with
    the global padded conv when tile sizes divide by the stride — the
    power-of-two constraint the reference asserts).

    ``exchange=False`` (with ``spatial=True``) gives the D2 "shrink" conv: no
    exchange, VALID conv on an input that already carries a wide halo — the
    output halo shrinks by (k-1)/2 (ref ``resnet_spatial_d2.py``).

    ``overlap``: ``"monolithic"`` | ``"decomposed"`` | None (None reads
    ``MPI4DL_TPU_CONV_OVERLAP``). The decomposed impl splits the exchange
    form into an interior conv with no halo dependency plus boundary-strip
    convs (:func:`overlap_decompose`) so XLA can hide the
    collective-permutes behind the interior MXU work; outputs are
    window-for-window identical and the permute inventory is unchanged
    (``halo_exchange`` is still called exactly once). NHWC only — the
    packed layout keeps the monolithic exchange.
    """

    features: int
    kernel_size: Any = 3
    strides: Any = 1
    padding: Any = None  # int/pair; None → (k-1)//2
    use_bias: bool = True
    spatial: bool = False
    exchange: bool = True
    pack: tuple[int, int] = (1, 1)  # (pack_in, pack_out); (1,1) = NHWC
    overlap: "str | None" = None  # None → MPI4DL_TPU_CONV_OVERLAP
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.strides)
        if self.padding is None:
            ph, pw = (kh - 1) // 2, (kw - 1) // 2
        else:
            ph, pw = _pair(self.padding)

        if self.pack != (1, 1):
            # Persistently-packed activation layout (ops/packed.py): the
            # input is [B, H, W/pack_in, pack_in*C]; emit packed too.
            # Spatial mode halo-exchanges whole packed columns (see
            # conv2d_packed) — the D1 per-op exchange form only; the D2
            # shrink form (exchange=False) has no packed variant.
            if self.spatial and not self.exchange:
                raise NotImplementedError(
                    "packed layout has no D2 (pre-fetched halo) conv form"
                )
            if self.spatial:
                _check_window_coverage(kh, kw, sh, sw, ph, pw)
            # Packed columns fold W into C: the recorded extents cannot be
            # interpreted as image rows/cols, so geometry consumers refuse.
            _record_windowed_op("packed", x, kh, kw, sh, sw, ph, pw)
            from mpi4dl_tpu.ops.packed import PackedConv

            return PackedConv(
                features=self.features,
                kernel_size=(kh, kw),
                pack_in=self.pack[0],
                pack_out=self.pack[1],
                strides=(sh, sw),
                padding=((ph, ph), (pw, pw)),
                use_bias=self.use_bias,
                spatial=self.spatial,
                dtype=self.dtype,
                name="conv",
            )(x)

        conv = FastConv(
            features=self.features,
            kernel_size=(kh, kw),
            strides=(sh, sw),
            padding="VALID" if self.spatial else ((ph, ph), (pw, pw)),
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="conv",
        )

        if not self.spatial:
            _record_windowed_op("conv", x, kh, kw, sh, sw, ph, pw)
            return conv(x)

        if self.exchange:
            _check_window_coverage(kh, kw, sh, sw, ph, pw)
            h_loc, w_loc = x.shape[1], x.shape[2]
            xe = halo_exchange(x, ph, pw, AXIS_TILE_H, AXIS_TILE_W)
            impl = self.overlap if self.overlap is not None else (
                conv_overlap_impl()
            )
            if impl not in ("monolithic", "decomposed"):
                raise ValueError(
                    f"overlap must be monolithic|decomposed, got {impl!r}"
                )
            if impl == "decomposed" and (ph or pw):
                # Interior conv reads the UN-exchanged tile: no data
                # dependency on the halo ppermutes, so the scheduler can
                # overlap them; boundary strips consume xe. Flax binds all
                # calls to the one "conv" submodule, so the param tree is
                # identical to the monolithic form.
                y = overlap_decompose(x, xe, conv, kh, kw, sh, sw, ph, pw)
                if y is not None:
                    return y
            # Trim to this tile's share of the global output grid. The first
            # VALID output aligns with the global grid because tile sizes are
            # multiples of the stride (power-of-two asserts, config.validate).
            return conv(xe)[:, : h_loc // sh, : w_loc // sw, :]

        # D2 shrink conv: input already carries a wide halo; VALID conv eats
        # (k-1) of it per dim. Strided shrink convs are handled by the D2
        # builder's halo-size formulas.
        return conv(x)


def pool_bwd_impl() -> str:
    """Strided-max-pool backward selector: "xla" (default — reduce_window's
    ``select_and_scatter`` transpose) or "decomposed" (the first-match mask
    decomposition below). ``MPI4DL_TPU_POOL_BWD`` overrides for A/B runs.

    Measured (AmoebaNet-D @2048 bs1, one v5e, docs/PERF.md round 4): the
    decomposition REGRESSED 1.273 → 0.871 img/s despite select_and_scatter
    profiling at 10.5% of the step — its kh*kw interior-padded scatter
    terms materialize ~9 input-resolution tensors (1.7 GB each at the
    reduction cells' widths) where select_and_scatter makes one pass. The
    implementation stays (semantics proven bit-equal in
    tests/test_spatial_layers.py) as the A/B lever, default off."""
    import os

    impl = os.environ.get("MPI4DL_TPU_POOL_BWD", "xla")
    if impl not in ("decomposed", "xla"):
        raise ValueError(
            f"MPI4DL_TPU_POOL_BWD must be decomposed|xla, got {impl!r}"
        )
    return impl


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def max_pool_strided(x, kh, kw, sh, sw, ph, pw):
    """Strided max pool (−inf edge padding — torch ``MaxPool2d`` parity)
    with a decomposed backward.

    Forward: stock ``reduce_window`` max (fast everywhere). Backward: XLA's
    transpose rule emits ``select_and_scatter``, whose sequential window
    walk profiled at 10.5% of the AmoebaNet@2048 train step on TPU (the
    REDUCTION cells' stride-2 pools — docs/PERF.md round 4). Here the
    gradient routes through kh*kw strided window views instead: visiting
    window positions in row-major order, a position claims the gradient
    where it equals the pooled max AND no earlier position claimed it —
    bit-identical semantics to ``select_and_scatter``'s first-max-wins GE
    select (tests/test_spatial_layers.py proves equality on tie-heavy
    data), so golden comparisons cannot tell the implementations apart.
    Each step is elementwise compare/select at output resolution plus an
    interior-padded scatter-add — ops XLA fuses well on TPU.
    """
    return _max_pool_fwd_val(x, kh, kw, sh, sw, ph, pw)


def _max_pool_fwd_val(x, kh, kw, sh, sw, ph, pw):
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    return lax.reduce_window(
        xp, neg, lax.max, (1, kh, kw, 1), (1, sh, sw, 1), "valid"
    )


def _max_pool_strided_fwd(x, kh, kw, sh, sw, ph, pw):
    y = _max_pool_fwd_val(x, kh, kw, sh, sw, ph, pw)
    return y, (x, y)


def _max_pool_strided_bwd(kh, kw, sh, sw, ph, pw, res, dy):
    x, y = res
    b, h, w, c = x.shape
    ho, wo = y.shape[1], y.shape[2]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    hp, wp = h + 2 * ph, w + 2 * pw
    claimed = jnp.zeros(y.shape, jnp.bool_)
    zero = jnp.zeros((), dy.dtype)
    dxp = None
    for u in range(kh):
        for v in range(kw):
            # This window position's view of the input, one value per window.
            x_uv = lax.slice(
                xp,
                (0, u, v, 0),
                (b, u + (ho - 1) * sh + 1, v + (wo - 1) * sw + 1, c),
                (1, sh, sw, 1),
            )
            eq = (x_uv == y) & ~claimed
            claimed = claimed | eq
            contrib = jnp.where(eq, dy, zero)
            # Scatter back: output (i, j) wrote input (i*sh + u, j*sw + v)
            # in padded coordinates — an interior pad places every value.
            term = lax.pad(
                contrib,
                zero,
                (
                    (0, 0, 0),
                    (u, hp - (u + (ho - 1) * sh + 1), sh - 1),
                    (v, wp - (v + (wo - 1) * sw + 1), sw - 1),
                    (0, 0, 0),
                ),
            )
            dxp = term if dxp is None else dxp + term
    dx = dxp[:, ph : ph + h, pw : pw + w, :]
    return (dx,)


max_pool_strided.defvjp(_max_pool_strided_fwd, _max_pool_strided_bwd)


def max_pool_s1_valid(x, kh: int, kw: int):
    """Stride-1 VALID max pool as a tree of shifted ``jnp.maximum``s.

    Numerically identical forward to ``lax.reduce_window(max)``, but the
    backward lowers to selects + pads instead of ``select_and_scatter`` —
    measured 17% of the AmoebaNet train step on TPU (docs/PERF.md round 3);
    the genotype runs a 3×3 s1 max pool in every cell.

    Gradient tie-breaking is impl-consistent **per backend**, not globally:
    on CPU (and wherever the Pallas gate declines) every model path (plain,
    spatial, D2) uses the tree backward (maximum-chain subgradients), so
    same-backend golden comparisons are impl-consistent, like the
    reference's CUDA pooling is with itself. On TPU, shapes the one-pass
    Pallas backward admits dispatch to :mod:`mpi4dl_tpu.ops.pool_pallas`
    instead (identical forward values; first-max-wins backward — the
    ``select_and_scatter`` tie rule). Cross-backend gradient comparisons on
    tie-heavy data (e.g. bf16) must therefore run with
    ``MPI4DL_TPU_POOL_PALLAS=off``; the tree stays the CPU/test path and
    the fallback.
    """
    from mpi4dl_tpu.ops import pool_pallas

    if pool_pallas.dispatchable(x, kh, kw, 1, 1, 0, 0):
        return pool_pallas.max_pool(x, kh, kw, 1, 1, 0, 0)
    h, w = x.shape[1], x.shape[2]
    # Separable: max over rows, then cols (associativity makes the forward
    # identical to the 2-D window) — kh+kw maximum ops instead of kh*kw, and
    # the backward's select/accumulate chain shrinks proportionally.
    y = None
    for u in range(kh):
        s = lax.slice_in_dim(x, u, u + h - kh + 1, axis=1)
        y = s if y is None else jnp.maximum(y, s)
    x, y = y, None
    for v in range(kw):
        s = lax.slice_in_dim(x, v, v + w - kw + 1, axis=2)
        y = s if y is None else jnp.maximum(y, s)
    return y


class Pool(nn.Module):
    """Max/avg pooling, optionally with halo exchange (ref ``Pool``,
    ``spatial.py:1416-1509``).

    The reference asserts halo_len == padding and square kernels
    (``spatial.py:1445-1464``); we support rectangular but keep the same
    halo == padding rule.
    """

    kind: str  # "max" | "avg"
    kernel_size: Any = 2
    strides: Any = None  # None → kernel_size (torch default)
    padding: Any = 0
    spatial: bool = False
    count_include_pad: bool = True  # torch AvgPool2d default; AmoebaNet uses False
    overlap: "str | None" = None  # None → MPI4DL_TPU_CONV_OVERLAP

    @nn.compact
    def __call__(self, x):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.strides if self.strides is not None else (kh, kw))
        ph, pw = _pair(self.padding)
        h_loc, w_loc = x.shape[1], x.shape[2]

        if self.spatial:
            # Applies to the padding==0 case too (e.g. kernel 3 stride 2
            # padding 0 would silently drop cross-boundary windows).
            _check_window_coverage(kh, kw, sh, sw, ph, pw)
        if self.spatial and (ph or pw):
            fill = float("-inf") if self.kind == "max" else 0.0
            if self.kind == "avg" and not self.count_include_pad:
                # Monolithic only: the mask-ratio form below couples the
                # numerator and divisor pools to one exchanged layout; the
                # overlap decomposition covers the fill-value forms.
                # Exact distributed count_include_pad=False: average = ratio
                # of two sum-pools. The divisor pool runs on a validity mask
                # built LOCALLY from tile position (ones, zeroed on the
                # outside-image ring of global-boundary tiles) — no second
                # exchange needed; boundary windows then divide by the true
                # (unpadded) element count at any tile position.
                xe = halo_exchange(x, ph, pw, AXIS_TILE_H, AXIS_TILE_W)
                ones = zero_boundary_halo(
                    jnp.ones_like(xe), ph, pw, AXIS_TILE_H, AXIS_TILE_W
                )
                num = lax.reduce_window(
                    xe, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), "valid"
                )
                den = lax.reduce_window(
                    ones, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), "valid"
                )
                y = num / den
                return y[:, : h_loc // sh, : w_loc // sw, :]
            exchanged = True
            pad = ((0, 0), (0, 0))
        else:
            exchanged = False
            pad = ((ph, ph), (pw, pw))

        def apply_pool(t, pad):
            if self.kind == "max":
                from mpi4dl_tpu.ops import pool_pallas

                if (
                    (sh, sw) != (1, 1)
                    and pool_bwd_impl() != "decomposed"  # explicit A/B lever
                    and pool_pallas.dispatchable(
                        t, kh, kw, sh, sw, pad[0][0], pad[1][0]
                    )
                ):
                    # Strided pools (the REDUCTION cells' k3 s2 / k2 s2):
                    # identical forward to reduce_window; the backward is
                    # the one-pass Pallas kernel instead of
                    # select_and_scatter (6.9% of the AmoebaNet@1024 step —
                    # docs/PERF.md round 4).
                    return pool_pallas.max_pool(
                        t, kh, kw, sh, sw, pad[0][0], pad[1][0]
                    )
                if (sh, sw) == (1, 1):
                    # Stride-1: shifted-maximum decomposition (cheap
                    # backward; see max_pool_s1_valid). -inf edge pad ==
                    # torch MaxPool2d. Strided pools deliberately stay on
                    # reduce_window: slicing the s1 maxima by the stride is
                    # forward-identical but measured a 22% END-TO-END
                    # REGRESSION on AmoebaNet@1024 (6.37 -> 4.94 img/s) —
                    # the full-resolution maximum tree + its full-res
                    # backward select chain costs far more than the
                    # select_and_scatter it removes (docs/PERF.md round 3).
                    if pad != ((0, 0), (0, 0)):
                        t = lax.pad(
                            t,
                            jnp.asarray(float("-inf"), t.dtype),
                            ((0, 0, 0), (*pad[0], 0), (*pad[1], 0), (0, 0, 0)),
                        )
                    return max_pool_s1_valid(t, kh, kw)
                if pool_bwd_impl() == "decomposed":
                    # A/B lever only (default "xla" — see pool_bwd_impl for
                    # the measured negative result): reduce_window forward +
                    # the first-match mask backward, bit-matching the XLA
                    # path in both directions.
                    return max_pool_strided(
                        t, kh, kw, sh, sw, pad[0][0], pad[1][0]
                    )
                return nn.max_pool(t, (kh, kw), strides=(sh, sw), padding=pad)
            if self.kind == "avg":
                return nn.avg_pool(
                    t,
                    (kh, kw),
                    strides=(sh, sw),
                    padding=pad,
                    count_include_pad=self.count_include_pad,
                )
            raise ValueError(f"unknown pool kind {self.kind!r}")

        if not exchanged:
            _record_windowed_op(
                "pool", x, kh, kw, sh, sw, ph, pw,
                pool_kind=self.kind,
                count_include_pad=self.count_include_pad,
            )
            return apply_pool(x, pad)

        xe = halo_exchange(x, ph, pw, AXIS_TILE_H, AXIS_TILE_W, fill_value=fill)
        impl = self.overlap if self.overlap is not None else (
            conv_overlap_impl()
        )
        if impl not in ("monolithic", "decomposed"):
            raise ValueError(
                f"overlap must be monolithic|decomposed, got {impl!r}"
            )
        if impl == "decomposed":
            # Same interior/boundary split as the spatial conv: the
            # interior pool needs no neighbor data (windows that touch the
            # halo — fill included — live in the boundary strips, which
            # slice xe and so see the exact monolithic bytes).
            y = overlap_decompose(
                x, xe, lambda t: apply_pool(t, ((0, 0), (0, 0))),
                kh, kw, sh, sw, ph, pw,
            )
            if y is not None:
                return y
        y = apply_pool(xe, ((0, 0), (0, 0)))
        return y[:, : h_loc // sh, : w_loc // sw, :]


class HaloExchange(nn.Module):
    """Standalone halo-exchange layer (ref ``halo_exchange_layer``,
    ``spatial.py:1032-1413``): pad the tile with ``halo_len`` rows/cols of
    neighbor data and return it. Used by the D2 fused-halo design to amortize
    one wide exchange over several shrink convs."""

    halo_len: Any = 1

    @nn.compact
    def __call__(self, x):
        ph, pw = _pair(self.halo_len)
        return halo_exchange(x, ph, pw, AXIS_TILE_H, AXIS_TILE_W)


class Identity(nn.Module):
    """Pass-through module. Used as the `none` genotype op (stride 1) and as
    the plain twin of :class:`HaloExchange` (on the full image a halo
    exchange is a no-op), keeping param-list positions aligned."""

    @nn.compact
    def __call__(self, x):
        return x


class Dense(nn.Module):
    features: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.features, dtype=self.dtype, name="fc")(x)


class Sequential(nn.Module):
    """Flat layer sequence — the unit the stage partitioner slices
    (ref builds flat ``nn.Sequential(OrderedDict)`` for the same reason,
    ``resnet.py:149-178``). Values between layers may be pytrees (AmoebaNet
    cells pass ``(concat, skip)`` tuples)."""

    layers: Sequence[Callable]

    @nn.compact
    def __call__(self, x):
        for layer in self.layers:
            x = layer(x)
        return x
