"""MXU-packed convolution: same math, lane-filling output channels.

Motivation (measured on the bench TPU, see ``docs/PERF.md``): the MXU's
effective rate is gated by the matmul's N dimension (output channels for a
conv). The reference models' CIFAR-style ResNet/AmoebaNet trunks carry 16-64
channels at very high resolution, so their convs run a [M, K] x [K, 16]
matmul — ~2.5 TF/s on hardware whose [M, K] x [K, 128] rate is ~25 TF/s.
The image is huge and the channel count tiny: exactly the wrong aspect
ratio for a 128x128 systolic array.

The fix is a layout identity, not an approximation. A stride-1 ``kh x kw``
conv producing ``O`` channels equals a stride-``(fh, fw)`` conv with a
``(kh+fh-1) x (kw+fw-1)`` *scattered* kernel producing ``fh*fw*O``
channels — output channel group (py, px) holds the original kernel shifted
by (py, px) and computes the original output subpixel (py, px) of each
``fh x fw`` output block — followed by a depth-to-space reshuffle. Zero
taps add exact zeros to the accumulator, so the result is the same sum of
the same products (mod f32 accumulation order). FLOPs inflate by
``(kh+fh-1)(kw+fw-1) / (kh kw)`` while the MXU N-dimension grows
``fh*fw``-fold — a large net win for small ``O`` (measured ~2x+ for 3x3 at
16-64 channels). 1x1 convs never profit: inflation is exactly ``fh*fw``,
cancelling the N gain — they stay on the stock path.

Custom VJPs cover BOTH stride-1 and strided convs. The stride-1 backward
packs the data gradient too — itself a small-N stride-1 conv of ``dy``
with the flipped/io-swapped kernel; the weight gradient uses the classic
transposed-wgrad conv (x as "CHWN", dy as the kernel) at ordinary sizes
and switches to per-tap ``dot_general``s (``wgrad_taps``) in the big-
size/small-batch regime where the conv form materializes pathologically-
padded operand copies (docs/PERF.md round 4). Strided convs keep XLA's
forward and dx but route their wgrad through the same taps gate.

Used by :class:`mpi4dl_tpu.ops.layers.Conv2d` via :class:`FastConv`;
selection is automatic (TPU + profitable shapes) and can be forced or
disabled with ``MPI4DL_TPU_CONV_IMPL`` = ``packed`` | ``xla`` | ``auto``.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name

_DIMNUMS = ("NHWC", "HWIO", "NHWC")

# Pack to at least this many output channels (the MXU lane count; measured
# rates keep improving up to ~128 lanes — see docs/PERF.md).
_TARGET_N = 128
# Accept at most this much FLOP inflation from kernel scattering.
_MAX_INFLATE = 4.0
# Candidate W-axis output-block factors. H is never packed (fh == 1):
# with W-only packing the depth-to-space is a pure reshape — the (py, px)
# interleave transpose that H-packing needs was measured at ~20 ms/step in
# the backward (profiled at 512px), far more than the FLOP delta between
# e.g. (2,4) and (1,8) packing.
_FACTORS_W = (2, 4, 8)


_SAVE_COMPACT = False


def save_compact_enabled() -> bool:
    """True while a trainer is tracing under the "scan_save" remat policy
    (the ``conv_out`` tag + compact reshape are emitted only then, so other
    policies pay no extra copies)."""
    return _SAVE_COMPACT


class save_conv_outputs:
    """Context manager enabling the ``conv_out`` tagging during tracing."""

    def __enter__(self):
        global _SAVE_COMPACT
        self._prev = _SAVE_COMPACT
        _SAVE_COMPACT = True

    def __exit__(self, *exc):
        global _SAVE_COMPACT
        _SAVE_COMPACT = self._prev


def conv_impl() -> str:
    """Global conv implementation selector: "auto" (default), "packed",
    or "xla" (``MPI4DL_TPU_CONV_IMPL``). Unknown values fail loudly."""
    impl = os.environ.get("MPI4DL_TPU_CONV_IMPL", "auto")
    if impl not in ("auto", "packed", "xla"):
        raise ValueError(
            f"MPI4DL_TPU_CONV_IMPL must be auto|packed|xla, got {impl!r}"
        )
    return impl


def _wgrad_impl_allows(c: int) -> bool:
    """Pallas-wgrad dispatch policy. ``MPI4DL_TPU_WGRAD_IMPL`` = ``xla``
    (default; never dispatch the kernel) | ``pallas`` (dispatch wherever
    the kernel's shape gate + compile probe admit, bounded by
    ``MPI4DL_TPU_WGRAD_CMAX`` input channels). Read at trace time so
    benchmark processes can A/B the dispatch without code edits.

    Default is XLA's backward-filter conv because it wins END TO END:
    standalone the Pallas kernel is 3-9x faster (docs/PERF.md round-2
    table), but in the full train step XLA chooses operand layouts
    globally and fuses the wgrad with its neighbors, and the measured
    bench is 2.296 img/s (xla) vs 2.252 (pallas C<=16) vs 2.117 (pallas
    everywhere). Standalone microbenchmarks mislead on this device."""
    impl = os.environ.get("MPI4DL_TPU_WGRAD_IMPL", "xla")
    if impl not in ("pallas", "xla"):
        raise ValueError(
            f"MPI4DL_TPU_WGRAD_IMPL must be pallas|xla, got {impl!r}"
        )
    if impl == "xla":
        return False
    cmax = int(os.environ.get("MPI4DL_TPU_WGRAD_CMAX", "1024"))
    return c <= cmax


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - device probing never fatal
        return False


@functools.lru_cache(maxsize=None)
def pack_factors(kh: int, kw: int, c_out: int, w_out: int) -> tuple[int, int]:
    """Choose (1, fw) output-block factors for a stride-1 conv; (1, 1)
    means "don't pack". Only the W axis is ever packed (see ``_FACTORS_W``).

    Profitability model from the measured MXU rate curve: rate grows
    ~linearly in N up to ``_TARGET_N`` lanes, while scattering inflates
    FLOPs by ``(kw+fw-1)/kw``. Maximize ``min(N', TARGET)/inflation``;
    require a >1.3x modeled win.
    """
    if (kh == 1 and kw == 1) or c_out >= _TARGET_N:
        return (1, 1)

    def score(fw: int) -> float:
        inflation = (kw + fw - 1) / kw
        if inflation > _MAX_INFLATE:
            return 0.0
        gain = min(fw * c_out, _TARGET_N) / min(c_out, _TARGET_N)
        return gain / inflation

    best, best_s = (1, 1), 1.3
    for fw in _FACTORS_W:
        if w_out % fw:
            continue
        s = score(fw)
        if s > best_s:
            best, best_s = (1, fw), s
    return best


def _scatter_kernel(w, fh: int, fw: int):
    """[kh, kw, C, O] -> [kh+fh-1, kw+fw-1, C, fh*fw*O] scattered kernel.

    Built by padding + stacking (kernel-sized, fuses under jit)."""
    kh, kw, c, o = w.shape
    blocks = [
        jnp.pad(w, ((py, fh - 1 - py), (px, fw - 1 - px), (0, 0), (0, 0)))
        for py in range(fh)
        for px in range(fw)
    ]
    wp = jnp.stack(blocks, axis=3)  # [kh', kw', C, fh*fw, O]
    return wp.reshape(kh + fh - 1, kw + fw - 1, c, fh * fw * o)


def _depth_to_space(y, fh: int, fw: int):
    """[B, H, W, fh*fw*O] -> [B, H*fh, W*fw, O]."""
    b, h, w, c = y.shape
    o = c // (fh * fw)
    y = y.reshape(b, h, w, fh, fw, o)
    y = y.transpose(0, 1, 3, 2, 4, 5)
    return y.reshape(b, h * fh, w * fw, o)


def _conv_packed(x, w, padding, fh: int, fw: int):
    """Stride-1 conv with explicit padding pairs, packed formulation.

    The padding rides on the strided conv itself (no separate pad copy);
    window starts are identical to pad-then-VALID since the packed output
    extent divides exactly (checked by the dispatch policy)."""
    wp = _scatter_kernel(w, fh, fw)
    y = lax.conv_general_dilated(
        x, wp, (fh, fw), padding, dimension_numbers=_DIMNUMS
    )
    return _depth_to_space(y, fh, fw)


def _conv_plain(x, w, strides, padding):
    return lax.conv_general_dilated(
        x, w, strides, padding, dimension_numbers=_DIMNUMS
    )


def _packed_dispatch(x, w, padding):
    """Stride-1 conv: packed when the policy says so, else plain."""
    (ph0, ph1), (pw0, pw1) = padding
    if min(ph0, ph1, pw0, pw1) < 0:
        # Negative explicit padding (a full-correlation dx whose forward
        # padding exceeded kernel-1): jnp.pad can't express it; XLA can.
        return _conv_plain(x, w, (1, 1), padding)
    if w.shape[0] == 1 and w.shape[1] == 1 and max(ph0, ph1, pw0, pw1) == 0:
        # 1x1 conv: a plain matmul over pixels. Layout packing can't help
        # (FLOP inflation exactly cancels the lane gain) but skipping the
        # conv lowering measurably does. Contract on the 4-D tensor
        # directly — an explicit [B*H*W, C] reshape pins C as the minor
        # (lane) dim, and for C < 128 XLA then materializes the operand
        # padded up to 8x (measured: 2.25 GB for a 288 MB [3072^2, 16]
        # reshape, part of the >2048px OOM — docs/PERF.md round 4); on
        # 4-D operands the compiler keeps its own (H/W-minor) layouts.
        return lax.dot_general(
            x, w.reshape(w.shape[2], w.shape[3]), (((3,), (0,)), ((), ()))
        )
    w_out = x.shape[2] + pw0 + pw1 - w.shape[1] + 1
    fh, fw = pack_factors(w.shape[0], w.shape[1], w.shape[3], w_out)
    if (fh, fw) == (1, 1):
        return _conv_plain(x, w, (1, 1), padding)
    return _conv_packed(x, w, padding, fh, fw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _conv2d_s1(x, w, padding):
    return _packed_dispatch(x, w, padding)


def _conv2d_s1_fwd(x, w, padding):
    return _packed_dispatch(x, w, padding), (x, w)


def _conv2d_s1_bwd(padding, res, dy):
    x, w = res
    kh, kw, _, _ = w.shape
    (ph0, ph1), (pw0, pw1) = padding

    if kh == 1 and kw == 1 and max(ph0, ph1, pw0, pw1) == 0:
        # Fused one-pass Pallas backward where the dispatch admits it:
        # dx and dw from ONE dy read (stock AD's two dots stream dy from
        # HBM twice — the dominant HBM-bound cost class of the AmoebaNet
        # step, docs/PERF.md round 5).
        from mpi4dl_tpu.ops import dot1x1_pallas

        if _on_tpu() and dot1x1_pallas.dispatchable(x, dy, w):
            c, o = x.shape[-1], dy.shape[-1]
            dx, dw = dot1x1_pallas.bwd_1x1(
                x, dy, w.reshape(c, o)
            )
            return dx.astype(x.dtype), dw.reshape(1, 1, c, o).astype(w.dtype)

    big = (
        not (kh == 1 and kw == 1)  # the 1x1 dx IS the layout-safe 4-D dot
        and _wgrad_taps_profitable(
            x.shape[0], x.shape[-1],
            float(np.prod(x.shape)) * x.dtype.itemsize,
        )
    )
    # dx: full correlation with the flipped, io-swapped kernel — a stride-1
    # small-N conv itself, so it goes through the packed dispatch too. In
    # the big-size regime the W-packed dx form materializes an 8x-padded
    # space-to-depth copy of dy (2.28 GB at 3072px — docs/PERF.md round
    # 4); leave the lowering to XLA there.
    wt = jnp.flip(w, axis=(0, 1)).swapaxes(2, 3)  # [kh, kw, O, C]
    dx_pad = ((kh - 1 - ph0, kh - 1 - ph1), (kw - 1 - pw0, kw - 1 - pw1))
    dx = _conv_plain(dy, wt, (1, 1), dx_pad) if big else _packed_dispatch(
        dy, wt, dx_pad
    )

    # dw[u, v, c, o] = sum_{b,h,w} xp[b, h+u, w+v, c] * dy[b, h, w, o].
    # 1x1: that's a plain x^T @ dy dot over pixels — no conv machinery.
    # Contract (B, H, W) on the 4-D operands directly (no [M, C] reshape —
    # see the layout note in _packed_dispatch's 1x1 branch).
    if kh == 1 and kw == 1 and max(ph0, ph1, pw0, pw1) == 0:
        c, o = x.shape[-1], dy.shape[-1]
        dw = lax.dot_general(
            x,
            dy,
            (((0, 1, 2), (0, 1, 2)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(1, 1, c, o)
        return dx.astype(x.dtype), dw.astype(w.dtype)

    xt = x
    if ph0 or ph1 or pw0 or pw1:
        xt = lax.pad(
            x,
            jnp.zeros((), x.dtype),
            ((0, 0, 0), (ph0, ph1, 0), (pw0, pw1, 0), (0, 0, 0)),
        )

    # k x k: the Pallas streaming kernel on TPU when the dispatch policy
    # admits the shape (see wgrad_impl_allows); fallback: the canonical
    # "CHWN" backward-filter conv, row-folded when the plain form would
    # materialize pathologically-padded operand copies (see wgrad_folded).
    from mpi4dl_tpu.ops import wgrad_pallas

    if (
        _on_tpu()
        and _wgrad_impl_allows(x.shape[-1])
        and wgrad_pallas.usable(xt, dy, kh, kw)
    ):
        dw = wgrad_pallas.wgrad(xt, dy, kh, kw)
    else:
        dw = wgrad_folded(xt, dy, kh, kw)
    return dx.astype(x.dtype), dw.astype(w.dtype)


# Padded-copy threshold (MB) above which the per-tap wgrad engages — ONE
# value shared by the fastconv and packed gates. Default 3072 MB: padded
# copies up to a few GB are cheaper than the taps' kh*kw operand re-reads
# (the @1024 stem conv taking taps at a 537 MB copy measured a 13%
# END-TO-END loss, docs/PERF.md round 4); only the >=3072px regime (where
# the copies OOM) wants the aggressive setting, which Trainer.train_step
# arms via the context manager below. MPI4DL_TPU_WGRAD_TAPS_MIN_MB
# overrides BOTH gates unconditionally.
_TAPS_MIN_MB = [3072.0]


def taps_min_mb() -> float:
    env = os.environ.get("MPI4DL_TPU_WGRAD_TAPS_MIN_MB")
    return float(env) if env else _TAPS_MIN_MB[0]


class wgrad_taps_threshold:
    """Context manager scoping the taps gate threshold (MB) for the
    enclosed trace — how :class:`mpi4dl_tpu.train.Trainer` arms the
    aggressive big-image setting without mutating process state."""

    def __init__(self, mb: float):
        self._mb = float(mb)

    def __enter__(self):
        self._prev = _TAPS_MIN_MB[0]
        _TAPS_MIN_MB[0] = self._mb

    def __exit__(self, *exc):
        _TAPS_MIN_MB[0] = self._prev


def _wgrad_taps_profitable(b: int, c: int, x_bytes: float) -> bool:
    """True when the canonical backward-filter conv would materialize
    pathologically-padded operand copies and the per-tap dot form should
    be used instead.

    The backward-filter conv maps x's BATCH axis to the conv feature
    (lane) dim and x's CHANNEL axis to the conv batch (sublane) dim, so at
    batch 1 / small C the TPU materializes x in a layout padded to
    ~256/(B*C) times its logical bytes — measured 4.5 GB (16x) for a
    288 MB [1,3072,3072,16] tensor, the allocation that made every
    >2048px ResNet train step exceed HBM at compile (docs/PERF.md round
    4; row-folding the batch was tried first and just moved the padding
    into 5x-padded chunk copies). Gate: expansion >= 4 AND the padded
    copy would exceed :func:`taps_min_mb`.
    ``MPI4DL_TPU_WGRAD_TAPS`` = auto (default) | off.
    """
    if os.environ.get("MPI4DL_TPU_WGRAD_TAPS", "auto") == "off":
        return False
    expansion = 256.0 / (b * c)
    return expansion >= 4.0 and x_bytes * expansion >= taps_min_mb() * 1e6


def wgrad_taps(xt, dy, kh: int, kw: int, sh: int = 1, sw: int = 1):
    """dw[u,v,c,o] = sum_{b,i,j} xt[b,i*sh+u,j*sw+v,c] * dy[b,i,j,o] as
    kh*kw per-tap ``dot_general``s contracting (B, H, W) on plain 4-D
    (strided) SLICES of the operands — no reshape, no transposed copy, so
    XLA keeps its own (H/W-minor, unpadded) layouts for x and dy and the
    only temporaries are one product at a time. This is what makes
    >2048px train steps fit HBM; cost is kh*kw reads of x and dy.
    ``xt`` is the already-padded input."""
    b, hp, wp, c = xt.shape
    _, ho, wo, o = dy.shape
    taps = []
    for u in range(kh):
        for v in range(kw):
            xs = lax.slice(
                xt,
                (0, u, v, 0),
                (b, u + (ho - 1) * sh + 1, v + (wo - 1) * sw + 1, c),
                (1, sh, sw, 1),
            )
            taps.append(
                lax.dot_general(
                    xs,
                    dy,
                    (((0, 1, 2), (0, 1, 2)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
    return jnp.stack(taps).reshape(kh, kw, c, o)


def wgrad_folded(xt, dy, kh: int, kw: int):
    """Stride-1 wgrad: per-tap dots when the canonical backward-filter
    conv would materialize pathologically-padded copies
    (:func:`_wgrad_taps_profitable`), else the fast conv form. Identical
    math either way (mod f32 accumulation order — both contract in f32
    on the MXU)."""
    if _wgrad_taps_profitable(
        xt.shape[0], xt.shape[-1],
        float(np.prod(xt.shape)) * xt.dtype.itemsize,
    ):
        return wgrad_taps(xt, dy, kh, kw)
    dw = lax.conv_general_dilated(
        xt,
        dy,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("CHWN", "IHWO", "NHWC"),
    )  # out: [C, kh, kw, O]
    return dw.transpose(1, 2, 0, 3)


def conv_bwd_with_taps(conv_fn, taps_gate, x, w, dy, strides, padding):
    """Shared backward for the strided/packed custom VJPs: dx always via
    XLA's own transpose of ``conv_fn`` (its base-dilated form keeps
    natural layouts — measured fine at every size); dw via per-tap
    strided dots when ``taps_gate(x)`` says the backward-filter form
    would materialize pathological copies (docs/PERF.md round 4), via
    the same pullback otherwise. ``conv_fn(x, w)`` must be the forward
    these gradients belong to."""
    kh, kw = w.shape[0], w.shape[1]
    _, pullback = jax.vjp(conv_fn, x, w)
    if taps_gate(x):
        dx, _ = pullback(dy)
        (ph0, ph1), (pw0, pw1) = padding
        xt = x
        if ph0 or ph1 or pw0 or pw1:
            xt = lax.pad(
                x,
                jnp.zeros((), x.dtype),
                ((0, 0, 0), (ph0, ph1, 0), (pw0, pw1, 0), (0, 0, 0)),
            )
        dw = wgrad_taps(xt, dy, kh, kw, strides[0], strides[1])
    else:
        dx, dw = pullback(dy)
    return dx.astype(x.dtype), dw.astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _conv2d_strided(x, w, strides, padding):
    return _conv_plain(x, w, strides, padding)


def _conv2d_strided_fwd(x, w, strides, padding):
    return _conv_plain(x, w, strides, padding), (x, w)


def _conv2d_strided_bwd(strides, padding, res, dy):
    x, w = res
    return conv_bwd_with_taps(
        lambda xx, ww: _conv_plain(xx, ww, strides, padding),
        lambda xx: _wgrad_taps_profitable(
            xx.shape[0],
            xx.shape[-1],
            float(np.prod(xx.shape)) * xx.dtype.itemsize,
        ),
        x, w, dy, strides, padding,
    )


_conv2d_strided.defvjp(_conv2d_strided_fwd, _conv2d_strided_bwd)


_conv2d_s1.defvjp(_conv2d_s1_fwd, _conv2d_s1_bwd)


def conv2d(x, w, strides=(1, 1), padding=((0, 0), (0, 0))):
    """2-D conv (NHWC x HWIO -> NHWC), explicit padding pairs.

    Uses the MXU-packed formulation (with matching packed backward) for
    stride-1 convs when profitable on this platform; otherwise identical to
    ``lax.conv_general_dilated``.
    """
    strides = tuple(int(s) for s in strides)
    padding = tuple((int(p[0]), int(p[1])) for p in padding)
    impl = conv_impl()
    use_packed = impl == "packed" or (impl == "auto" and _on_tpu())
    if not use_packed:
        return _conv_plain(x, w, strides, padding)
    if strides != (1, 1):
        # Custom backward only when the big-size wgrad pathology gate is
        # armed for this shape (see _conv2d_strided_bwd) — the custom_vjp
        # wrapper itself costs fusion opportunities at small sizes.
        if _wgrad_taps_profitable(
            x.shape[0], x.shape[-1],
            float(np.prod(x.shape)) * x.dtype.itemsize,
        ):
            return _conv2d_strided(x, w, strides, padding)
        return _conv_plain(x, w, strides, padding)
    return _conv2d_s1(x, w, padding)


class FastConv(nn.Module):
    """Drop-in for ``nn.Conv`` (NHWC, explicit padding) routing through
    :func:`conv2d`. Parameter tree ("kernel", "bias"), shapes, and
    initialization match ``nn.Conv`` exactly, so models can swap freely."""

    features: int
    kernel_size: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: Any = "SAME"  # pairs, "SAME", or "VALID" (nn.Conv default: SAME)
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        sh, sw = self.strides
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features),
            jnp.float32,
        )
        bias = (
            self.param("bias", nn.initializers.zeros_init(), (self.features,), jnp.float32)
            if self.use_bias
            else None
        )
        x, kernel, bias = nn.dtypes.promote_dtype(x, kernel, bias, dtype=self.dtype)
        padding = self.padding
        if padding == "VALID":
            padding = ((0, 0), (0, 0))
        elif padding == "SAME":
            # Explicit SAME pairs (XLA formula), so the packed path applies.
            def same(dim, k, s):
                total = max((-(-dim // s) - 1) * s + k - dim, 0)
                return (total // 2, total - total // 2)

            padding = (same(x.shape[1], kh, sh), same(x.shape[2], kw, sw))
        y = conv2d(x, kernel, (sh, sw), padding)
        if bias is not None:
            y = y + bias
        # Tag for the "scan_save" remat policy (convs then run once in
        # forward — backward recomputes only the cheap elementwise/BN
        # segments between conv outputs). When saving is active, tag a
        # compact [B, H, W*C] view: small-channel NHWC tensors store ~8x
        # larger in HBM (minor dim padded to the 128-lane tile), which is
        # exactly the footprint the policy is spending memory on.
        if not save_compact_enabled():
            return y
        if y.ndim == 4 and y.shape[-1] < 128:
            shape = y.shape
            yc = checkpoint_name(y.reshape(shape[0], shape[1], -1), "conv_out")
            return yc.reshape(shape)
        return checkpoint_name(y, "conv_out")
